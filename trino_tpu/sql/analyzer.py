"""Analyzer + logical planner: AST -> typed logical plan.

Reference parity: sql/analyzer/StatementAnalyzer.java:423 (+ExpressionAnalyzer,
AggregationAnalyzer, Scope/Field) and sql/planner/LogicalPlanner.java:165
(QueryPlanner, RelationPlanner, SubqueryPlanner).  The reference splits
analysis (producing an Analysis side-table) from planning; here the two are
fused into one bottom-up pass producing plan nodes with typed expr IR —
the Analysis artifacts (resolved types, coercions, aggregate extraction)
are materialized directly in the plan.

Naming: every relation column gets a unique *symbol* (Symbol allocator
analog); scopes map (qualifier, name) -> (symbol, type).

Aggregation planning mirrors QueryPlanner.planGroupByAggregation: group-key
and aggregate-argument expressions are computed in a pre-projection, the
Aggregate node consumes symbols only, and post-aggregation expressions are
rewritten over key/agg output symbols (AggregationAnalyzer's validation
that select expressions are composed of grouping keys and aggregates).

Subqueries: uncorrelated IN -> SemiJoin; uncorrelated EXISTS / scalar ->
ScalarJoin (EnforceSingleRow analog).  Correlated subqueries decorrelate
into multi-key SemiJoins / grouped joins on the correlation keys (the
TransformCorrelated* rules' role — see _plan_exists / _plan_scalar_subquery
below).
"""
from __future__ import annotations

import dataclasses
import datetime
import random as _random
from typing import Dict, List, Optional, Sequence, Tuple

from .. import types as T
from ..catalog import Metadata
from ..expr import ir
from ..expr.functions import arith_result_type, days_from_civil
from ..ops.sort import SortKey
from ..plan import nodes as P
from . import ast

# canonical aggregate kinds (ops/aggregation.py families) + SQL aliases
AGG_ALIASES = {
    "stddev": "stddev_samp",
    "variance": "var_samp",
    "every": "bool_and",
    "any_value": "arbitrary",
}
ONE_ARG_AGGREGATES = {
    "sum", "count", "min", "max", "avg",
    "var_samp", "var_pop", "stddev_samp", "stddev_pop", "geometric_mean",
    "bool_and", "bool_or",
    "bitwise_and_agg", "bitwise_or_agg", "bitwise_xor_agg",
    "checksum", "arbitrary", "count_if", "approx_distinct",
    "array_agg",
}
TWO_ARG_AGGREGATES = {
    "min_by", "max_by", "map_agg", "listagg",
    "covar_pop", "covar_samp", "corr",
    "regr_slope", "regr_intercept",
    "approx_percentile",
}
AGGREGATES = (
    ONE_ARG_AGGREGATES | TWO_ARG_AGGREGATES | set(AGG_ALIASES)
)

WINDOW_ONLY_FUNCTIONS = {
    "row_number", "rank", "dense_rank", "percent_rank", "cume_dist",
    "ntile", "lag", "lead", "first_value", "last_value", "nth_value",
}

SCALAR_FUNCTIONS = {
    "abs", "sqrt", "round", "floor", "ceil", "ceiling", "year", "month",
    "day", "quarter", "length", "like",
}


class SemanticError(ValueError):
    pass


@dataclasses.dataclass
class Field:
    qualifier: Optional[str]
    name: str
    symbol: str
    type: T.Type


class Scope:
    def __init__(self, fields: List[Field]):
        self.fields = fields

    def resolve(self, parts: Tuple[str, ...]) -> Field:
        if len(parts) == 1:
            matches = [f for f in self.fields if f.name == parts[0]]
        else:
            q, n = parts[-2], parts[-1]
            matches = [
                f for f in self.fields if f.name == n and f.qualifier == q
            ]
        if not matches:
            raise SemanticError(f"column not found: {'.'.join(parts)}")
        if len(matches) > 1:
            raise SemanticError(f"ambiguous column: {'.'.join(parts)}")
        return matches[0]


class SymbolAllocator:
    def __init__(self):
        self._counts: Dict[str, int] = {}

    def new(self, base: str) -> str:
        base = base.lower()[:40] or "expr"
        n = self._counts.get(base, 0)
        self._counts[base] = n + 1
        return base if n == 0 else f"{base}_{n}"


@dataclasses.dataclass
class RelationPlan:
    root: P.PlanNode
    scope: Scope


class Analyzer:
    """One statement analysis+planning session (LogicalPlanner.plan)."""

    def __init__(self, metadata: Metadata, default_catalog: Optional[str],
                 sql_functions: Optional[Dict[str, "SqlFunction"]] = None):
        self.metadata = metadata
        self.default_catalog = default_catalog
        self.symbols = SymbolAllocator()
        self.ctes: Dict[str, ast.Query] = {}
        # CREATE FUNCTION registry (LanguageFunctionManager analog);
        # expanded inline at analysis like the reference inlines SQL
        # routines into the plan (sql/routine/SqlRoutineCompiler inlining)
        self.sql_functions = sql_functions or {}
        self._udf_stack: set = set()
        # correlated-subquery support: while planning a subquery, outer
        # scopes are visible for resolution; outer symbols actually used
        # are recorded per level (ApplyNode correlation list analog)
        self.outer_scopes: List[Scope] = []
        self.correlation_used: List[Dict[str, T.Type]] = []
        # window placeholder symbol -> output type ($w names are not
        # reachable from SQL identifiers, so visibility is harmless
        # across nested query specs)
        self.window_fields: Dict[str, T.Type] = {}
        # id(ast node) -> analyzed ir for window sub-expressions whose
        # aggregates were extracted during _plan_aggregation
        self.win_ir_cache: Dict[int, ir.Expr] = {}
        # stack of analyzed-but-unattached window state (nested specs)
        self._pending_windows: List[tuple] = []

    def _plan_subquery_correlated(self, q: ast.Query, outer: Scope):
        """Plan q with `outer` visible; returns (RelationPlan, names,
        {outer symbol -> type} actually referenced)."""
        self.outer_scopes.append(outer)
        self.correlation_used.append({})
        try:
            rp, names = self.plan_query(q)
            used = self.correlation_used[-1]
        finally:
            self.outer_scopes.pop()
            self.correlation_used.pop()
        return rp, names, used

    # ------------------------------------------------------------------
    def plan_statement(self, stmt: ast.Node) -> P.PlanNode:
        if isinstance(stmt, ast.Query):
            rp, names = self.plan_root_query(stmt)
            return P.Output(
                rp.root, tuple(names), tuple(f.symbol for f in rp.scope.fields)
            )
        if isinstance(stmt, ast.Insert):
            return self._plan_insert(stmt)
        if isinstance(stmt, ast.CreateTableAs):
            return self._plan_ctas(stmt)
        if isinstance(stmt, ast.Delete):
            return self._plan_delete(stmt)
        if isinstance(stmt, ast.Update):
            return self._plan_update(stmt)
        if isinstance(stmt, ast.MergeInto):
            return self._plan_merge(stmt)
        raise SemanticError(f"unsupported statement: {type(stmt).__name__}")

    # -- DML planning (QueryPlanner.planInsert / planDelete analogs) -----
    def _coerced_source(self, rp: RelationPlan, target_types) -> P.PlanNode:
        """Project the query output onto the target column types, inserting
        casts where the analyzer's types differ (implicit INSERT coercion)."""
        assigns = []
        changed = False
        for f, tt in zip(rp.scope.fields, target_types):
            ref: ir.Expr = ir.ColumnRef(f.type, f.symbol)
            if f.type != tt:
                try:
                    ok = f.type.name == "unknown" or T.common_super_type(
                        f.type, tt
                    ) is not None
                except TypeError:
                    ok = False
                if not ok:
                    raise SemanticError(
                        f"cannot insert {f.type} into column of type {tt}"
                    )
                ref = _fold(ir.Cast(tt, ref))
                changed = True
            assigns.append((self.symbols.new("ins"), ref))
        if not changed:
            return rp.root
        return P.Project(rp.root, tuple(assigns))

    def _plan_insert(self, stmt: ast.Insert) -> P.PlanNode:
        catalog, schema = self.metadata.resolve_table(
            stmt.table, self.default_catalog
        )
        if stmt.columns:
            known = {c.name for c in schema.columns}
            targets = []
            for c in stmt.columns:
                if c.lower() not in known:
                    raise SemanticError(
                        f"column {c} not in table {schema.name}"
                    )
                if c.lower() in targets:
                    raise SemanticError(f"duplicate insert column {c}")
                targets.append(c.lower())
        else:
            targets = [c.name for c in schema.columns]
        rp, _ = self.plan_query(stmt.query)
        if len(rp.scope.fields) != len(targets):
            raise SemanticError(
                f"INSERT has {len(rp.scope.fields)} expressions but "
                f"{len(targets)} target columns"
            )
        ttypes = [schema.column_type(c) for c in targets]
        src = self._coerced_source(rp, ttypes)
        writer = P.TableWriter(src, catalog, schema.name, tuple(targets))
        return P.Output(writer, ("rows",), ("rows",))

    def _plan_ctas(self, stmt: ast.CreateTableAs) -> P.PlanNode:
        catalog, table = self.metadata.resolve_new_table(
            stmt.table, self.default_catalog
        )
        if self.metadata.lookup_view(stmt.table, self.default_catalog):
            raise SemanticError(
                f"view with that name already exists: {table}"
            )
        rp, names = self.plan_query(stmt.query)
        seen = set()
        for n in names:
            if n.lower() in seen:
                raise SemanticError(f"duplicate output column name {n}")
            seen.add(n.lower())
        create_schema = tuple(
            (n.lower(), f.type if f.type.name != "unknown" else T.BIGINT)
            for n, f in zip(names, rp.scope.fields)
        )
        writer = P.TableWriter(
            rp.root, catalog, table, tuple(n for n, _ in create_schema),
            create_schema=create_schema,
            if_not_exists=stmt.if_not_exists,
        )
        return P.Output(writer, ("rows",), ("rows",))

    def _plan_update(self, stmt: ast.Update) -> P.PlanNode:
        """UPDATE as whole-table rewrite: each column becomes
        CASE WHEN pred THEN new_value ELSE old END, plus a marker column
        counting changed rows (the reference routes updates through
        MergeWriterNode; rewrite matches this engine's DELETE path)."""
        catalog, schema = self.metadata.resolve_table(
            stmt.table, self.default_catalog
        )
        known = {c.name for c in schema.columns}
        assigned = {}
        for col, expr in stmt.assignments:
            if col.lower() not in known:
                raise SemanticError(f"column {col} not in table {schema.name}")
            if col.lower() in assigned:
                raise SemanticError(f"column {col} assigned twice")
            assigned[col.lower()] = expr
        pred = stmt.where if stmt.where is not None else ast.Literal(
            "boolean", True
        )
        items = []
        for c in schema.columns:
            old = ast.Identifier((c.name,))
            if c.name in assigned:
                e = ast.CaseExpr(
                    None,
                    (ast.WhenClause(pred, assigned[c.name]),),
                    old,
                )
            else:
                e = old
            items.append(ast.SelectItem(e, c.name))
        items.append(
            ast.SelectItem(
                ast.CaseExpr(
                    None,
                    (ast.WhenClause(pred, ast.Literal("integer", 1)),),
                    ast.Literal("integer", 0),
                ),
                "__updated__",
            )
        )
        spec = ast.QuerySpec(
            items=tuple(items),
            relation=ast.Table(stmt.table),
            where=None,
            group_by=(),
            having=None,
        )
        rp, _ = self.plan_query(ast.Query(spec))
        ttypes = [schema.column_type(c.name) for c in schema.columns]
        ttypes.append(T.BIGINT)
        src = self._coerced_source(rp, ttypes)
        count_sym = src.output_symbols()[-1]
        writer = P.TableWriter(
            src, catalog, schema.name,
            tuple(c.name for c in schema.columns),
            overwrite=True, count_symbol=count_sym,
        )
        return P.Output(writer, ("rows",), ("rows",))

    def _plan_merge(self, stmt: ast.MergeInto) -> P.PlanNode:
        """MERGE as whole-table rewrite (the reference's MergeWriterNode
        machinery): kept target rows = target LEFT JOIN source with CASE
        per column (UPDATE) and a keep-predicate (DELETE), UNION ALL the
        NOT-MATCHED inserts from an anti-join; a marker column (1=updated,
        2=inserted) plus the before/after counts yields the affected-row
        count.  First-listed matched clause wins when both apply."""
        catalog, schema = self.metadata.resolve_table(
            stmt.table, self.default_catalog
        )
        talias = stmt.target_alias or schema.name
        upd = dele = ins = None
        order: Dict[str, int] = {}
        for i, w in enumerate(stmt.whens):
            if w.matched and w.action == "update":
                if upd:
                    raise SemanticError("multiple WHEN MATCHED UPDATE clauses")
                upd = w
                order["update"] = i
            elif w.matched and w.action == "delete":
                if dele:
                    raise SemanticError("multiple WHEN MATCHED DELETE clauses")
                dele = w
                order["delete"] = i
            elif not w.matched and w.action == "insert":
                if ins:
                    raise SemanticError("multiple WHEN NOT MATCHED clauses")
                ins = w
            else:
                raise SemanticError(
                    f"WHEN {'MATCHED' if w.matched else 'NOT MATCHED'} THEN "
                    f"{w.action.upper()} is not a valid MERGE clause"
                )
        salias = getattr(stmt.source, "alias", None)
        if salias is None and isinstance(stmt.source, ast.Table):
            salias = stmt.source.name[-1]
        if salias is None:
            raise SemanticError("MERGE source requires an alias")
        known = {c.name for c in schema.columns}
        if upd:
            for col, _ in upd.assignments:
                if col.lower() not in known:
                    raise SemanticError(
                        f"column {col} not in table {schema.name}"
                    )

        TRUE = ast.Literal("boolean", True)

        def and_(*terms):
            terms = [t for t in terms if t is not None]
            if not terms:
                return TRUE
            if len(terms) == 1:
                return terms[0]
            return ast.LogicalOp("and", tuple(terms))

        def not_true(e):
            # NOT (e IS TRUE): false only when e evaluates true
            return ast.LogicalOp(
                "or", (ast.NotOp(e), ast.IsNullOp(e, False))
            )

        marker_col = "__merge_matched__"
        matched = ast.IsNullOp(ast.Identifier((salias, marker_col)), True)
        upd_eff = del_eff = None
        if upd:
            upd_eff = and_(matched, upd.condition)
        if dele:
            del_eff = and_(matched, dele.condition)
        if upd and dele:  # first-listed clause wins
            if order["update"] < order["delete"]:
                guard = upd.condition
                del_eff = (
                    and_(del_eff, not_true(guard)) if guard is not None
                    else ast.Literal("boolean", False)
                )
            else:
                guard = dele.condition
                upd_eff = (
                    and_(upd_eff, not_true(guard)) if guard is not None
                    else ast.Literal("boolean", False)
                )

        wrapped_source = ast.SubqueryRelation(
            ast.Query(ast.QuerySpec(
                items=(ast.Star(),
                       ast.SelectItem(TRUE, marker_col)),
                relation=stmt.source,
                where=None, group_by=(), having=None,
            )),
            alias=salias,
        )
        assigned = {c.lower(): e for c, e in (upd.assignments if upd else ())}
        items = []
        for c in schema.columns:
            base: ast.Node = ast.Identifier((talias, c.name))
            if c.name in assigned and upd_eff is not None:
                base = ast.CaseExpr(
                    None,
                    (ast.WhenClause(upd_eff, assigned[c.name]),),
                    base,
                )
            items.append(ast.SelectItem(base, c.name))
        mark_a: ast.Node = ast.Literal("integer", 0)
        if upd_eff is not None:
            mark_a = ast.CaseExpr(
                None,
                (ast.WhenClause(upd_eff, ast.Literal("integer", 1)),),
                ast.Literal("integer", 0),
            )
        items.append(ast.SelectItem(mark_a, "__merge_marker__"))
        part_a = ast.QuerySpec(
            items=tuple(items),
            relation=ast.Join(
                "left",
                ast.Table(stmt.table, stmt.target_alias),
                wrapped_source,
                stmt.condition,
            ),
            where=not_true(del_eff) if del_eff is not None else None,
            group_by=(), having=None,
        )
        body: ast.Node = part_a
        if ins:
            ins_cols = (
                [c.lower() for c in ins.insert_columns]
                if ins.insert_columns
                else [c.name for c in schema.columns]
            )
            if len(ins_cols) != len(ins.insert_values):
                raise SemanticError(
                    "MERGE INSERT column/value count mismatch"
                )
            for c in ins_cols:
                if c not in known:
                    raise SemanticError(
                        f"column {c} not in table {schema.name}"
                    )
            by_col = dict(zip(ins_cols, ins.insert_values))
            b_items = []
            for c in schema.columns:
                b_items.append(ast.SelectItem(
                    by_col.get(c.name, ast.Literal("null", None)), c.name
                ))
            b_items.append(ast.SelectItem(
                ast.Literal("integer", 2), "__merge_marker__"
            ))
            anti = ast.Exists(
                ast.Query(ast.QuerySpec(
                    items=(ast.SelectItem(ast.Literal("integer", 1)),),
                    relation=ast.Table(stmt.table, stmt.target_alias),
                    where=stmt.condition,
                    group_by=(), having=None,
                )),
                negate=True,
            )
            part_b = ast.QuerySpec(
                items=tuple(b_items),
                relation=stmt.source,
                where=and_(anti, ins.condition),
                group_by=(), having=None,
            )
            body = ast.SetOp("union", True, part_a, part_b)
        rp, _ = self.plan_query(ast.Query(body))
        ttypes = [schema.column_type(c.name) for c in schema.columns]
        ttypes.append(T.BIGINT)
        src = self._coerced_source(rp, ttypes)
        marker_sym = src.output_symbols()[-1]
        writer = P.TableWriter(
            src, catalog, schema.name,
            tuple(c.name for c in schema.columns),
            overwrite=True, count_symbol=marker_sym, count_mode="merge",
        )
        return P.Output(writer, ("rows",), ("rows",))

    def _plan_delete(self, stmt: ast.Delete) -> P.PlanNode:
        catalog, schema = self.metadata.resolve_table(
            stmt.table, self.default_catalog
        )
        # DELETE rows WHERE pred == rewrite with rows where pred IS NOT TRUE
        # (the reference routes row-level deletes through MergeWriterNode;
        # the memory-style connectors here rewrite the table)
        if stmt.where is None:
            keep: Optional[ast.Node] = ast.Literal("boolean", False)
        else:
            keep = ast.LogicalOp(
                "or", (ast.NotOp(stmt.where), ast.IsNullOp(stmt.where, False))
            )
        spec = ast.QuerySpec(
            items=(ast.Star(),),
            relation=ast.Table(stmt.table),
            where=keep,
            group_by=(),
            having=None,
        )
        rp, _ = self.plan_query(ast.Query(spec))
        writer = P.TableWriter(
            rp.root, catalog, schema.name,
            tuple(c.name for c in schema.columns),
            overwrite=True, report_deleted=True,
        )
        return P.Output(writer, ("rows",), ("rows",))

    def plan_root_query(self, q: ast.Query) -> Tuple[RelationPlan, List[str]]:
        rp, names = self.plan_query(q)
        return rp, names

    # ------------------------------------------------------------------
    def plan_query(self, q: ast.Query) -> Tuple[RelationPlan, List[str]]:
        saved = dict(self.ctes)
        for w in q.withs:
            self.ctes[w.name.lower()] = w
        try:
            if isinstance(q.body, ast.QuerySpec):
                rp, names = self.plan_query_spec(
                    q.body, q.order_by, q.limit, q.offset
                )
            else:
                rp, names = self.plan_set_op(q.body)
                rp = self._apply_order_limit(
                    rp, names, q.order_by, q.limit, post_agg=None,
                    offset=q.offset,
                )
            return rp, names
        finally:
            self.ctes = saved

    def plan_values_relation(
        self, v: ast.ValuesRelation
    ) -> Tuple[RelationPlan, List[str]]:
        """VALUES rows -> P.Values (constant folding required; the reference
        additionally allows non-constant rows, out of scope here)."""
        arity = len(v.rows[0])
        for r in v.rows:
            if len(r) != arity:
                raise SemanticError("VALUES rows must all have the same arity")
        dummy = RelationPlan(P.Values((), (), ()), Scope([]))
        ea = ExprAnalyzer(self, dummy)
        cells: List[List[ir.Constant]] = []
        for r in v.rows:
            row = []
            for x in r:
                e = _fold(ea.analyze(x))
                if not isinstance(e, ir.Constant):
                    raise SemanticError("VALUES rows must be constant")
                row.append(e)
            cells.append(row)
        col_types: List[T.Type] = []
        for i in range(arity):
            t = cells[0][i].type
            for row in cells[1:]:
                t = T.common_super_type(t, row[i].type)
            col_types.append(t)
        symbols = tuple(self.symbols.new(f"_col{i}") for i in range(arity))
        dicts: List[Tuple[str, Tuple[str, ...]]] = []
        codes: List[Dict[str, int]] = [dict() for _ in range(arity)]
        out_rows = []
        for row in cells:
            vals = []
            for i, (c, t) in enumerate(zip(row, col_types)):
                if c.value is None:
                    vals.append(None)
                elif t.is_dictionary:
                    entry = (
                        tuple(c.value)
                        if getattr(t, "is_array", False)
                        else str(c.value)
                    )
                    code = codes[i].setdefault(entry, len(codes[i]))
                    vals.append(code)
                elif t.is_decimal:
                    cs = c.type.scale if c.type.is_decimal else 0
                    vals.append(int(c.value) * 10 ** (t.scale - cs)
                                if t.scale >= cs
                                else int(c.value) // 10 ** (cs - t.scale))
                elif t.name in ("double", "real"):
                    cv = c.value
                    if c.type.is_decimal:
                        cv = cv / 10 ** c.type.scale
                    vals.append(float(cv))
                else:
                    vals.append(c.value)
            out_rows.append(tuple(vals))
        for i, t in enumerate(col_types):
            if t.is_dictionary:
                dicts.append((symbols[i], tuple(codes[i])))
        node = P.Values(
            symbols,
            tuple(zip(symbols, col_types)),
            tuple(out_rows),
            tuple(dicts),
        )
        names = [f"_col{i}" for i in range(arity)]
        fields = [
            Field(None, n, s, t)
            for n, s, t in zip(names, symbols, col_types)
        ]
        return RelationPlan(node, Scope(fields)), names

    def plan_set_op(self, s: ast.Node) -> Tuple[RelationPlan, List[str]]:
        if isinstance(s, ast.QuerySpec):
            return self.plan_query_spec(s, (), None)
        if isinstance(s, ast.ValuesRelation):
            return self.plan_values_relation(s)
        if isinstance(s, ast.Query):
            # parenthesized branch with its own ORDER BY / LIMIT
            return self.plan_query(s)
        assert isinstance(s, ast.SetOp)
        lp, lnames = self.plan_set_op(s.left)
        rp, rnames = self.plan_set_op(s.right)
        lt = [f.type for f in lp.scope.fields]
        rt = [f.type for f in rp.scope.fields]
        if len(lt) != len(rt):
            raise SemanticError("set operation arity mismatch")
        out_types = [T.common_super_type(a, b) for a, b in zip(lt, rt)]
        syms = [self.symbols.new(n) for n in lnames]
        node = P.SetOperation(
            s.kind,
            s.all,
            (self._coerce_output(lp, out_types), self._coerce_output(rp, out_types)),
            tuple(syms),
            tuple(zip(syms, out_types)),
        )
        scope = Scope(
            [Field(None, n, sym, t) for n, sym, t in zip(lnames, syms, out_types)]
        )
        return RelationPlan(node, scope), lnames

    def _coerce_output(self, rp: RelationPlan, out_types) -> P.PlanNode:
        assigns = []
        changed = False
        for f, ot in zip(rp.scope.fields, out_types):
            e: ir.Expr = ir.ColumnRef(f.type, f.symbol)
            if f.type != ot:
                e = ir.Cast(ot, e)
                changed = True
            assigns.append((f.symbol, e))
        if not changed:
            return rp.root
        return P.Project(rp.root, tuple(assigns))

    # ------------------------------------------------------------------
    def plan_query_spec(
        self,
        spec: ast.QuerySpec,
        order_by: Tuple[ast.SortItem, ...],
        limit: Optional[int],
        offset: int = 0,
    ) -> Tuple[RelationPlan, List[str]]:
        # FROM
        if spec.relation is None:
            sym = self.symbols.new("dual")
            rel = RelationPlan(
                P.Values((sym,), ((sym, T.BIGINT),), ((0,),)), Scope([])
            )
        else:
            rel = self.plan_relation(spec.relation)

        # WHERE (conjuncts; IN/EXISTS subquery conjuncts become semi joins)
        if spec.where is not None:
            rel = self._plan_where(rel, spec.where)

        # expand stars
        items: List[ast.SelectItem] = []
        for it in spec.items:
            if isinstance(it, ast.Star):
                for f in rel.scope.fields:
                    if it.qualifier is None or f.qualifier == it.qualifier:
                        items.append(
                            ast.SelectItem(
                                ast.Identifier(
                                    (f.qualifier, f.name)
                                    if f.qualifier
                                    else (f.name,)
                                ),
                                None,
                            )
                        )
            else:
                items.append(it)

        # window calls are pulled out of the select items and planned as
        # WindowNodes after aggregation (QueryPlanner.planWindowFunctions)
        win_calls: List[Tuple[str, ast.FunctionCall]] = []
        if any(_contains_window(it.expr) for it in items):
            items = [
                ast.SelectItem(
                    self._rewrite_windows(it.expr, win_calls), it.alias
                )
                for it in items
            ]

        has_aggs = bool(spec.group_by) or any(
            _contains_aggregate(it.expr) for it in items
        ) or (spec.having is not None and _contains_aggregate(spec.having)) or any(
            _contains_aggregate(x)
            for _, c in win_calls
            for x in _window_subexprs(c)
        )

        ea = ExprAnalyzer(self, rel)
        if has_aggs:
            rel, post = self._plan_aggregation(rel, spec, items, ea, win_calls)
            proj_analyzer = post
        else:
            if spec.having is not None:
                raise SemanticError("HAVING without aggregation")
            proj_analyzer = ea
            if win_calls:
                self._analyze_windows(win_calls, ea.analyze)
        if win_calls:
            self._attach_windows(proj_analyzer)

        # SELECT projection
        names: List[str] = []
        assigns: List[Tuple[str, ir.Expr]] = []
        out_fields: List[Field] = []
        for i, it in enumerate(items):
            e = proj_analyzer.analyze(it.expr)
            name = it.alias or _derive_name(it.expr, i)
            sym = self.symbols.new(name)
            names.append(name)
            assigns.append((sym, e))
            out_fields.append(Field(None, name.lower(), sym, e.type))
        rel = RelationPlan(proj_analyzer.relation.root, proj_analyzer.relation.scope)
        proj = P.Project(rel.root, tuple(assigns))
        out = RelationPlan(proj, Scope(out_fields))

        if spec.distinct:
            out = RelationPlan(P.Distinct(out.root), out.scope)

        out = self._apply_order_limit(
            out, names, order_by, limit,
            post_agg=proj_analyzer if has_aggs else None,
            pre_projection=rel,
            select_assigns=assigns,
            offset=offset,
        )
        return out, names

    # ------------------------------------------------------------------
    def _plan_where(self, rel: RelationPlan, where: ast.Node) -> RelationPlan:
        conjuncts = _flatten_and(where)
        plain: List[ast.Node] = []
        for c in conjuncts:
            if isinstance(c, ast.InSubquery):
                rel = self._plan_semijoin(rel, c.value, c.query, c.negate)
            elif isinstance(c, ast.Exists):
                rel = self._plan_exists(rel, c.query, c.negate)
            elif isinstance(c, ast.NotOp) and isinstance(c.operand, ast.Exists):
                rel = self._plan_exists(rel, c.operand.query, not c.operand.negate)
            else:
                plain.append(c)
        if plain:
            ea = ExprAnalyzer(self, rel)
            pred = ea.analyze(_combine_and(plain))
            rel = ea.relation  # scalar joins may have extended the plan
            if pred.type != T.BOOLEAN:
                raise SemanticError("WHERE must be boolean")
            rel = RelationPlan(P.Filter(rel.root, pred), rel.scope)
        return rel

    def _plan_semijoin(
        self, rel: RelationPlan, value: ast.Node, query: ast.Query, negate: bool
    ) -> RelationPlan:
        ea = ExprAnalyzer(self, rel)
        v = ea.analyze(value)
        rel = ea.relation
        if not isinstance(v, ir.ColumnRef):
            # compute the key in a projection first
            sym = self.symbols.new("semikey")
            assigns = [
                (f.symbol, ir.ColumnRef(f.type, f.symbol))
                for f in rel.scope.fields
            ] + [(sym, v)]
            rel = RelationPlan(
                P.Project(rel.root, tuple(assigns)), rel.scope
            )
            v = ir.ColumnRef(v.type, sym)
        sub, sub_names = self.plan_query(query)
        if len(sub.scope.fields) != 1:
            raise SemanticError("IN subquery must return one column")
        out = self.symbols.new("semi")
        node = P.SemiJoin(
            rel.root, sub.root, (v.name,), (sub.scope.fields[0].symbol,), out
        )
        # filter on the mark (negated for NOT IN; NULL semantics simplified
        # to not-matched, exact NOT IN null semantics handled at kernel)
        mark = ir.ColumnRef(T.BOOLEAN, out)
        pred: ir.Expr = ir.Not(mark) if negate else mark
        return RelationPlan(P.Filter(node, pred), rel.scope)

    # -- decorrelation (TransformCorrelated* rules analog) --------------
    def _decorrelate(self, root: P.PlanNode, outer_syms: Dict[str, T.Type]):
        """Extract correlated equality conjuncts from the subplan.

        Returns (new_root, pairs, residuals) where pairs =
        [(outer_symbol, inner_symbol)], residuals are correlated
        non-equality conjuncts (kept verbatim, referencing outer + inner
        symbols — the mark-join filter), and new_root exposes every inner
        symbol at its top (pass-through projections added; Aggregates gain
        the inner symbols as group keys, turning a correlated scalar
        aggregate into a grouped one).
        """
        outer = set(outer_syms)

        def rec(node: P.PlanNode):
            if isinstance(node, P.Filter):
                src2, pairs, residuals = rec(node.source)
                rest: List[ir.Expr] = []
                my_pairs: List[Tuple[str, str]] = []
                my_res: List[ir.Expr] = []
                extra_proj: List[Tuple[str, ir.Expr]] = []
                for c in _flatten_ir_and(node.predicate):
                    refs = set(ir.referenced_columns(c)) & outer
                    if not refs:
                        rest.append(c)
                        continue
                    pair = _as_correlated_equality(c, outer)
                    if pair is None:
                        my_res.append(c)
                        continue
                    osym, inner = pair
                    if isinstance(inner, ir.ColumnRef):
                        my_pairs.append((osym, inner.name))
                    else:
                        isym = self.symbols.new("corrkey")
                        extra_proj.append((isym, inner))
                        my_pairs.append((osym, isym))
                src3 = src2
                if extra_proj:
                    passthrough = [
                        (s, ir.ColumnRef(t, s))
                        for s, t in src2.output_types().items()
                    ]
                    src3 = P.Project(src2, tuple(passthrough + extra_proj))
                out = P.Filter(src3, _combine_ir(rest)) if rest else src3
                return out, pairs + my_pairs, residuals + my_res
            if isinstance(node, P.Project):
                src2, pairs, residuals = rec(node.source)
                if not pairs and not residuals:
                    return dataclasses.replace(node, source=src2), pairs, residuals
                types = src2.output_types()
                have = {s for s, _ in node.assignments}
                need = [isym for _, isym in pairs]
                for r in residuals:
                    need.extend(
                        c for c in ir.referenced_columns(r)
                        if c not in outer and c in types
                    )
                extra = tuple(
                    (isym, ir.ColumnRef(types[isym], isym))
                    for isym in dict.fromkeys(need)
                    if isym not in have
                )
                return (
                    P.Project(src2, tuple(node.assignments) + extra),
                    pairs,
                    residuals,
                )
            if isinstance(node, P.Aggregate):
                src2, pairs, residuals = rec(node.source)
                if residuals:
                    raise SemanticError(
                        "non-equality correlation below an aggregate is not "
                        "decorrelatable"
                    )
                if not pairs:
                    return dataclasses.replace(node, source=src2), pairs, residuals
                new_keys = tuple(
                    dict.fromkeys(
                        list(node.keys) + [isym for _, isym in pairs]
                    )
                )
                return (
                    P.Aggregate(src2, new_keys, node.aggs, node.step),
                    pairs,
                    residuals,
                )
            if isinstance(node, (P.Limit, P.TopN, P.Sort, P.Distinct)):
                src2, pairs, residuals = rec(node.sources[0])
                if pairs or residuals:
                    raise SemanticError(
                        "correlation below ORDER BY/LIMIT/DISTINCT is not "
                        "decorrelatable"
                    )
                return node, pairs, residuals
            # joins/scans/semijoins: correlation must not appear below
            for s in node.sources:
                for t in _walk_plan_exprs(s):
                    if set(ir.referenced_columns(t)) & outer:
                        raise SemanticError(
                            "correlated reference in unsupported position"
                        )
            return node, [], []

        return rec(root)

    def _plan_exists(
        self, rel: RelationPlan, query: ast.Query, negate: bool
    ) -> RelationPlan:
        sub, _, corr = self._plan_subquery_correlated(query, rel.scope)
        if corr:
            new_root, pairs, residuals = self._decorrelate(sub.root, corr)
            if not pairs:
                raise SemanticError("correlated EXISTS without usable equality")
            out = self.symbols.new("semi")
            node = P.SemiJoin(
                rel.root,
                new_root,
                tuple(o for o, _ in pairs),
                tuple(i for _, i in pairs),
                out,
                filter=_combine_ir(residuals) if residuals else None,
            )
            mark = ir.ColumnRef(T.BOOLEAN, out)
            pred: ir.Expr = ir.Not(mark) if negate else mark
            return RelationPlan(P.Filter(node, pred), rel.scope)
        cnt = self.symbols.new("exists_count")
        agg = P.Aggregate(
            sub.root,
            (),
            (P.AggInfo(cnt, "count_star", None, False, None, T.BIGINT),),
        )
        flag_sym = self.symbols.new("exists")
        flag = P.Project(
            agg,
            (
                (
                    flag_sym,
                    ir.Comparison(
                        ">", ir.ColumnRef(T.BIGINT, cnt), ir.Constant(T.BIGINT, 0)
                    ),
                ),
            ),
        )
        node = P.ScalarJoin(rel.root, flag)
        mark = ir.ColumnRef(T.BOOLEAN, flag_sym)
        pred: ir.Expr = ir.Not(mark) if negate else mark
        return RelationPlan(P.Filter(node, pred), rel.scope)

    # ------------------------------------------------------------------
    def _plan_aggregation(self, rel, spec, items, ea: "ExprAnalyzer",
                          win_calls=()):
        # group keys: ordinals or expressions, possibly inside grouping
        # elements (ROLLUP/CUBE/GROUPING SETS -> cross-product of per-item
        # sets, StatementAnalyzer.analyzeGroupBy semantics)
        import itertools

        key_exprs: List[ir.Expr] = []

        def key_index(g: ast.Node) -> int:
            if isinstance(g, ast.Literal) and g.kind == "integer":
                idx = int(g.value) - 1
                if not (0 <= idx < len(items)):
                    raise SemanticError(
                        f"GROUP BY ordinal {g.value} out of range"
                    )
                e = ea.analyze(items[idx].expr)
            else:
                e = ea.analyze(g)
            for i, k in enumerate(key_exprs):
                if k == e:
                    return i
            key_exprs.append(e)
            return len(key_exprs) - 1

        set_lists: List[List[Tuple[int, ...]]] = []
        for g in spec.group_by:
            if isinstance(g, ast.Rollup):
                idxs = [key_index(x) for x in g.items]
                set_lists.append(
                    [tuple(idxs[:k]) for k in range(len(idxs), -1, -1)]
                )
            elif isinstance(g, ast.Cube):
                idxs = [key_index(x) for x in g.items]
                subs: List[Tuple[int, ...]] = []
                for r in range(len(idxs), -1, -1):
                    subs.extend(itertools.combinations(idxs, r))
                set_lists.append(subs)
            elif isinstance(g, ast.GroupingSets):
                set_lists.append(
                    [tuple(key_index(x) for x in s) for s in g.sets]
                )
            else:
                set_lists.append([(key_index(g),)])
        sets_idx: List[Tuple[int, ...]] = []
        for combo in itertools.product(*set_lists):
            merged: List[int] = []
            for part in combo:
                for i in part:
                    if i not in merged:
                        merged.append(i)
            sets_idx.append(tuple(merged))
        rel = ea.relation

        # pre-projection: pass-through + key symbols
        pre_assigns: List[Tuple[str, ir.Expr]] = [
            (f.symbol, ir.ColumnRef(f.type, f.symbol)) for f in rel.scope.fields
        ]
        key_syms: List[str] = []
        key_map: List[Tuple[ir.Expr, ir.ColumnRef]] = []
        for ke in key_exprs:
            if isinstance(ke, ir.ColumnRef):
                key_syms.append(ke.name)
                key_map.append((ke, ke))
            else:
                sym = self.symbols.new("groupkey")
                pre_assigns.append((sym, ke))
                ref = ir.ColumnRef(ke.type, sym)
                key_syms.append(sym)
                key_map.append((ke, ref))

        multi_sets = len(sets_idx) > 1
        gid_sym = gid_ref = sets_syms = None
        if multi_sets:
            sets_syms = tuple(
                tuple(key_syms[i] for i in st) for st in sets_idx
            )
            gid_sym = self.symbols.new("groupid")
            gid_ref = ir.ColumnRef(T.BIGINT, gid_sym)
        agg_collector = AggCollector(
            self, rel, key_map, pre_assigns,
            grouping_sets=sets_syms, gid_ref=gid_ref,
        )
        # window args/partition/order are evaluated over the aggregation
        # output: extract their aggregates first (before the Aggregate node
        # is frozen) and register placeholder types for the item analysis
        if win_calls:
            for _, call in win_calls:
                for x in _window_subexprs(call):
                    self.win_ir_cache[id(x)] = agg_collector.analyze_post(x)
            self._analyze_windows(win_calls, agg_collector.analyze_post)
        # analyze select + having with aggregate extraction
        post_exprs = {}
        for it in items:
            post_exprs[id(it)] = agg_collector.analyze_post(it.expr)
        having_pred = (
            agg_collector.analyze_post(spec.having)
            if spec.having is not None
            else None
        )
        rel = agg_collector.relation

        pre = P.Project(rel.root, tuple(agg_collector.pre_assigns))
        agg_src: P.PlanNode = pre
        agg_keys = tuple(key_syms)
        if multi_sets:
            agg_src = P.GroupId(pre, sets_syms, gid_sym)
            agg_keys = agg_keys + (gid_sym,)
        agg_node = P.Aggregate(agg_src, agg_keys, tuple(agg_collector.aggs))
        new_fields = [
            Field(None, s, s, t)
            for s, t in agg_node.output_types().items()
        ]
        root: P.PlanNode = agg_node
        for subplan in agg_collector.pending_scalar:
            root = P.ScalarJoin(root, subplan)
        rel2 = RelationPlan(root, Scope(new_fields))
        if having_pred is not None:
            rel2 = RelationPlan(P.Filter(rel2.root, having_pred), rel2.scope)
        post_analyzer = PostAggAnalyzer(
            self, rel2, agg_collector, post_exprs, dict((id(it), it) for it in items)
        )
        return rel2, post_analyzer

    # -- window planning (QueryPlanner.planWindowFunctions analog) -------
    def _rewrite_windows(self, e: ast.Node, out: List) -> ast.Node:
        """Replace windowed FunctionCalls with placeholder identifiers
        ($w symbols, unreachable from SQL text); collects (placeholder,
        call) pairs.  Does not descend into subqueries — their windows are
        planned when the subquery is planned."""
        if isinstance(e, ast.FunctionCall) and e.window is not None:
            ph = self.symbols.new("$w")
            out.append((ph, e))
            return ast.Identifier((ph,))
        if isinstance(e, ast.Query) or not isinstance(e, ast.Node):
            return e
        kwargs = {}
        changed = False
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            if isinstance(v, ast.Node):
                nv = self._rewrite_windows(v, out)
            elif isinstance(v, tuple):
                nv = tuple(self._rewrite_windows(x, out) for x in v)
                if all(a is b for a, b in zip(nv, v)):
                    nv = v
            else:
                nv = v
            if nv is not v:
                changed = True
            kwargs[f.name] = nv
        return dataclasses.replace(e, **kwargs) if changed else e

    def _analyze_windows(self, win_calls, analyze) -> None:
        """Phase 1 of window planning: analyze partition/order/arg
        expressions (via `analyze`, the agg-aware analyzer when grouping),
        build WindowFunc specs, and register placeholder output types so
        the select projection can reference them.  The built state is
        pushed for _attach_windows (a stack: subquery planning nests)."""

        def an(x: ast.Node) -> ir.Expr:
            cached = self.win_ir_cache.get(id(x))
            return cached if cached is not None else analyze(x)

        computed: List[Tuple[str, ir.Expr]] = []
        seen: Dict[ir.Expr, str] = {}

        def as_symbol(e: ir.Expr) -> str:
            if isinstance(e, ir.ColumnRef):
                return e.name
            if e in seen:
                return seen[e]
            sym = self.symbols.new("winarg")
            computed.append((sym, e))
            seen[e] = sym
            return sym

        groups: Dict[tuple, List[P.WindowFunc]] = {}
        for ph, call in win_calls:
            spec = call.window
            psyms = tuple(as_symbol(an(p)) for p in spec.partition_by)
            okeys = []
            for si in spec.order_by:
                sym = as_symbol(an(si.expr))
                asc = si.ascending
                nf = si.nulls_first if si.nulls_first is not None else (not asc)
                okeys.append(SortKey(sym, asc, nf))
            func = self._window_func(ph, call, an, as_symbol)
            groups.setdefault((psyms, tuple(okeys)), []).append(func)
            self.window_fields[ph] = func.output_type
        self._pending_windows.append((computed, groups))

    def _attach_windows(self, pa) -> None:
        """Phase 2: place the pre-projection + Window nodes on top of the
        (possibly aggregated) relation the projection analyzer sees."""
        computed, groups = self._pending_windows.pop()
        rel = pa.relation
        root = rel.root
        if computed:
            passthrough = [
                (s, ir.ColumnRef(t, s))
                for s, t in root.output_types().items()
            ]
            root = P.Project(root, tuple(passthrough + computed))
        for (psyms, okeys), funcs in groups.items():
            root = P.Window(root, psyms, okeys, tuple(funcs))
        pa.relation = RelationPlan(root, rel.scope)

    def _window_func(self, ph, call: ast.FunctionCall, an, as_symbol):
        kind = call.name
        if call.distinct:
            raise SemanticError("DISTINCT in window functions is not supported")
        frame = self._window_frame(call.window.frame)
        args: Tuple[str, ...] = ()
        constants: Tuple[object, ...] = ()
        in_t: Optional[T.Type] = None
        if kind in ("row_number", "rank", "dense_rank", "percent_rank",
                    "cume_dist"):
            if call.args:
                raise SemanticError(f"{kind}() takes no arguments")
            out_t = T.DOUBLE if kind in ("percent_rank", "cume_dist") else T.BIGINT
        elif kind == "ntile":
            if len(call.args) != 1:
                raise SemanticError("ntile(n) takes one argument")
            n = self._const_int(call.args[0], "ntile")
            if n < 1:
                raise SemanticError("ntile buckets must be positive")
            constants = (n,)
            out_t = T.BIGINT
        elif kind in ("lag", "lead"):
            if not call.args:
                raise SemanticError(f"{kind}() requires a value argument")
            v = an(call.args[0])
            args = (as_symbol(v),)
            in_t = out_t = v.type
            off = 1
            if len(call.args) > 1:
                off = self._const_int(call.args[1], kind)
            default = None
            if len(call.args) > 2:
                d = an(call.args[2])
                if not isinstance(d, ir.Constant):
                    raise SemanticError(f"{kind} default must be a constant")
                default = d.value
                if default is not None and in_t.is_dictionary:
                    raise SemanticError(
                        f"{kind} with a non-null varchar default is not "
                        "supported"
                    )
                if default is not None and in_t.is_decimal:
                    src_scale = d.type.scale if d.type.is_decimal else 0
                    default = default * 10 ** (in_t.scale - src_scale)
            constants = (off, default)
        elif kind in ("first_value", "last_value"):
            if len(call.args) != 1:
                raise SemanticError(f"{kind}(x) takes one argument")
            v = an(call.args[0])
            args = (as_symbol(v),)
            in_t = out_t = v.type
        elif kind == "nth_value":
            if len(call.args) != 2:
                raise SemanticError("nth_value(x, n) takes two arguments")
            v = an(call.args[0])
            args = (as_symbol(v),)
            n = self._const_int(call.args[1], "nth_value")
            if n < 1:
                raise SemanticError("nth_value offset must be positive")
            constants = (n,)
            in_t = out_t = v.type
        elif kind in AGGREGATES:
            if call.is_star:
                kind = "count_star"
                out_t = T.BIGINT
            else:
                v = an(call.args[0])
                args = (as_symbol(v),)
                in_t = v.type
                out_t = _agg_output_type(kind, in_t)
                if kind in ("min", "max") and in_t.is_dictionary:
                    raise SemanticError(
                        f"window {kind}(varchar) is not supported"
                    )
        else:
            raise SemanticError(f"unknown window function: {kind}")
        return P.WindowFunc(ph, kind, args, constants, frame, in_t, out_t)

    def _window_frame(self, f: Optional[ast.WindowFrame]) -> P.WindowFrame:
        if f is None:
            # SQL default: RANGE UNBOUNDED PRECEDING .. CURRENT ROW
            # (without ORDER BY all rows are peers, so this spans the
            # whole partition — compute_bounds' peer geometry covers both)
            return P.WindowFrame()
        if f.unit == "groups":
            raise SemanticError("GROUPS frames are not supported")

        def bound(b: ast.FrameBound, which: str) -> Tuple[str, int]:
            if b.kind in ("preceding", "following"):
                if f.unit == "range":
                    raise SemanticError(
                        "RANGE frames support only UNBOUNDED/CURRENT ROW "
                        "bounds"
                    )
                return b.kind, self._const_int(b.value, f"frame {which}")
            return b.kind, 0

        sk, so = bound(f.start, "start")
        ek, eo = bound(f.end, "end")
        if sk == "unbounded_following" or ek == "unbounded_preceding":
            raise SemanticError("invalid window frame bounds")
        return P.WindowFrame(f.unit, sk, so, ek, eo)

    @staticmethod
    def _const_int(e: ast.Node, what: str) -> int:
        if isinstance(e, ast.Literal) and e.kind == "integer":
            return int(e.value)
        raise SemanticError(f"{what} requires a constant integer")

    # ------------------------------------------------------------------
    def _apply_order_limit(
        self,
        out: RelationPlan,
        names: List[str],
        order_by,
        limit,
        post_agg=None,
        pre_projection: Optional[RelationPlan] = None,
        select_assigns=None,
        offset: int = 0,
    ) -> RelationPlan:
        if order_by:
            keys: List[SortKey] = []
            extra_assigns: List[Tuple[str, ir.Expr]] = []
            for si in order_by:
                sym = self._resolve_sort_expr(
                    si.expr, out, names, post_agg, pre_projection, extra_assigns
                )
                asc = si.ascending
                nf = si.nulls_first if si.nulls_first is not None else (not asc)
                keys.append(SortKey(sym, asc, nf))
            root = out.root
            if extra_assigns:
                # hidden sort columns: extend the projection feeding the sort
                assert isinstance(root, P.Project)
                root = P.Project(
                    root.source, tuple(list(root.assignments) + extra_assigns)
                )
            if limit is not None:
                # TopN keeps offset+limit, then Limit skips the offset
                node: P.PlanNode = P.TopN(
                    root, tuple(keys), limit + offset
                )
                if offset:
                    node = P.Limit(node, limit, offset)
            else:
                node = P.Sort(root, tuple(keys))
                if offset:
                    node = P.Limit(node, (1 << 62), offset)
            if extra_assigns:
                # project hidden columns away
                node = P.Project(
                    node,
                    tuple(
                        (f.symbol, ir.ColumnRef(f.type, f.symbol))
                        for f in out.scope.fields
                    ),
                )
            return RelationPlan(node, out.scope)
        if limit is not None:
            return RelationPlan(
                P.Limit(out.root, limit, offset), out.scope
            )
        if offset:
            return RelationPlan(
                P.Limit(out.root, (1 << 62), offset), out.scope
            )
        return out

    def _resolve_sort_expr(
        self, e, out: RelationPlan, names, post_agg, pre_projection, extra_assigns
    ) -> str:
        # ordinal
        if isinstance(e, ast.Literal) and e.kind == "integer":
            idx = int(e.value) - 1
            if not (0 <= idx < len(out.scope.fields)):
                raise SemanticError(f"ORDER BY ordinal {e.value} out of range")
            return out.scope.fields[idx].symbol
        # output alias / name
        if isinstance(e, ast.Identifier) and len(e.parts) == 1:
            matches = [
                f for f in out.scope.fields if f.name == e.parts[0].lower()
            ]
            if len(matches) == 1:
                return matches[0].symbol
        # expression over the underlying relation (hidden column)
        if post_agg is not None:
            expr = post_agg.analyze(e)
        elif pre_projection is not None:
            expr = ExprAnalyzer(self, pre_projection).analyze(e)
        else:
            raise SemanticError("cannot resolve ORDER BY expression")
        sym = self.symbols.new("sortkey")
        extra_assigns.append((sym, expr))
        return sym

    # ------------------------------------------------------------------
    def plan_relation(self, rel: ast.Node) -> RelationPlan:
        if isinstance(rel, ast.Table):
            return self._plan_table(rel)
        if isinstance(rel, ast.SubqueryRelation):
            rp, names = self.plan_query(rel.query)
            cols = rel.columns or names
            if len(cols) != len(rp.scope.fields):
                raise SemanticError("derived table column count mismatch")
            fields = [
                Field(rel.alias, c.lower(), f.symbol, f.type)
                for c, f in zip(cols, rp.scope.fields)
            ]
            return RelationPlan(rp.root, Scope(fields))
        if isinstance(rel, ast.Join):
            return self._plan_join(rel)
        if isinstance(rel, ast.MatchRecognize):
            return self._plan_match_recognize(rel)
        if isinstance(rel, ast.UnnestRelation):
            # standalone FROM UNNEST(constant-array): expand against dual
            sym = self.symbols.new("dual")
            dual = RelationPlan(
                P.Values((sym,), ((sym, T.BIGINT),), ((0,),)), Scope([])
            )
            return self._plan_unnest(dual, rel)
        if isinstance(rel, ast.TableFunctionRelation):
            return self._plan_table_function(rel)
        raise SemanticError(f"unsupported relation: {type(rel).__name__}")

    # -- table functions (spi/function/table + operator/table) ----------
    def _plan_table_function(
        self, rel: "ast.TableFunctionRelation"
    ) -> RelationPlan:
        """Built-in polymorphic table functions (sequence,
        exclude_columns) + the connector SPI seam
        (Connector.table_functions() — ConnectorTableFunction analog)."""
        name = rel.name
        if name == "sequence":
            return self._tf_sequence(rel)
        if name == "exclude_columns":
            return self._tf_exclude_columns(rel)
        # connector-provided table functions (searched over catalogs)
        for cat in self.metadata.catalogs.names():
            conn = self.metadata.catalogs.get(cat)
            tf = (conn.table_functions() or {}).get(name)
            if tf is None:
                continue
            scalars = [
                self._const_scalar(a, name)
                for kind, a in rel.args if kind == "scalar"
            ]
            schema, rows = tf(*scalars)
            syms, fields, types = [], [], []
            for col, t in schema:
                sym = self.symbols.new(col)
                syms.append(sym)
                types.append((sym, t))
                fields.append(Field(rel.alias, col, sym, t))
            return RelationPlan(
                P.Values(tuple(syms), tuple(types),
                         tuple(tuple(r) for r in rows)),
                Scope(fields),
            )
        raise SemanticError(f"unknown table function: {name}")

    def _const_scalar(self, e: ast.Node, what: str) -> object:
        v = self._analyze_standalone(e)
        if not isinstance(v, ir.Constant):
            raise SemanticError(
                f"table function {what} requires constant arguments"
            )
        return v.value

    def _analyze_standalone(self, e: ast.Node):
        dummy = RelationPlan(P.Values((), (), ()), Scope([]))
        return ExprAnalyzer(self, dummy).analyze(e)

    def _tf_sequence(self, rel) -> RelationPlan:
        """TABLE(sequence(start, stop [, step])) -> one bigint column
        `sequential_number` (io.trino.operator.table.Sequence)."""
        scalars = [a for kind, a in rel.args if kind == "scalar"]
        if len(scalars) not in (2, 3):
            raise SemanticError("sequence(start, stop [, step])")
        vals = [self._const_scalar(a, "sequence") for a in scalars]
        start, stop = int(vals[0]), int(vals[1])
        step = int(vals[2]) if len(vals) == 3 else 1
        if step == 0:
            raise SemanticError("sequence step cannot be zero")
        n = max(0, (stop - start) // step + 1)
        if n > 1_000_000:
            raise SemanticError("sequence result exceeds 1,000,000 rows")
        sym = self.symbols.new("sequential_number")
        col = rel.columns[0] if rel.columns else "sequential_number"
        return RelationPlan(
            P.Values(
                (sym,), ((sym, T.BIGINT),),
                tuple((start + i * step,) for i in range(n)),
            ),
            Scope([Field(rel.alias, col.lower(), sym, T.BIGINT)]),
        )

    def _tf_exclude_columns(self, rel) -> RelationPlan:
        """TABLE(exclude_columns(TABLE(t), DESCRIPTOR(a, b))) — passes the
        input through minus the descriptor columns
        (io.trino.operator.table.ExcludeColumns)."""
        tables = [a for kind, a in rel.args if kind == "table"]
        descs = [a for kind, a in rel.args if kind == "descriptor"]
        if len(tables) != 1 or len(descs) != 1:
            raise SemanticError(
                "exclude_columns(TABLE(t), DESCRIPTOR(col, ...))"
            )
        inp = self.plan_relation(tables[0])
        drop = {c.lower() for c in descs[0]}
        fields = [f for f in inp.scope.fields if f.name not in drop]
        if len(fields) == len(inp.scope.fields):
            missing = drop - {f.name for f in inp.scope.fields}
            if missing:
                raise SemanticError(
                    f"exclude_columns: unknown columns {sorted(missing)}"
                )
        if not fields:
            raise SemanticError("exclude_columns would drop every column")
        if rel.alias:
            fields = [
                Field(rel.alias, f.name, f.symbol, f.type) for f in fields
            ]
        return RelationPlan(inp.root, Scope(fields))

    def _plan_match_recognize(self, mr: ast.MatchRecognize) -> RelationPlan:
        """MATCH_RECOGNIZE -> P.MatchRecognize (PatternRecognitionNode):
        DEFINE/MEASURES analyzed with a navigation-aware resolver
        (PREV/NEXT/FIRST/LAST/CLASSIFIER/MATCH_NUMBER; A.col == LAST(A.col))."""
        inner = self.plan_relation(mr.relation)
        vars_: set = set()

        def collect(t):
            if t.kind == "var":
                vars_.add(t.var)
            for s in t.items:
                collect(s)

        collect(mr.pattern)
        mrea = MrExprAnalyzer(self, inner, vars_)
        part_syms = []
        for p in mr.partition_by:
            e = mrea.analyze(p)
            if not isinstance(e, ir.ColumnRef):
                raise SemanticError(
                    "MATCH_RECOGNIZE PARTITION BY must be input columns"
                )
            part_syms.append(e.name)
        order_keys = []
        for si in mr.order_by:
            e = mrea.analyze(si.expr)
            if not isinstance(e, ir.ColumnRef):
                raise SemanticError(
                    "MATCH_RECOGNIZE ORDER BY must be input columns"
                )
            nf = si.nulls_first
            order_keys.append(SortKey(
                e.name, si.ascending,
                (not si.ascending) if nf is None else nf,
            ))
        defines = []
        for var, cond in mr.defines:
            if var not in vars_:
                raise SemanticError(
                    f"DEFINE variable {var.upper()} not in PATTERN"
                )
            c = mrea.analyze(cond)
            defines.append((var, c))
        measures = []
        if mr.rows_per_match == "all":
            fields = list(inner.scope.fields)
        else:
            fields = [
                f for f in inner.scope.fields if f.symbol in part_syms
            ]
        for expr, name in mr.measures:
            e = mrea.analyze(expr)
            sym = self.symbols.new(name)
            measures.append((sym, e, e.type))
            fields.append(Field(mr.alias, name.lower(), sym, e.type))
        node = P.MatchRecognize(
            inner.root, tuple(part_syms), tuple(order_keys), mr.pattern,
            tuple(defines), tuple(measures), mr.after_match,
            mr.rows_per_match,
        )
        return RelationPlan(node, Scope(fields))

    def _plan_using_join(
        self, j: ast.Join, left: RelationPlan, right: RelationPlan, scope
    ) -> RelationPlan:
        """JOIN ... USING (cols): equi-join on same-named columns; each
        using column appears ONCE in the output, coalesced across sides
        (outer-join null-extension picks the present side), per the
        standard and StatementAnalyzer.analyzeJoinUsing."""
        pairs = []
        for c in j.using:
            lc = c.lower()
            lf = [f for f in left.scope.fields if f.name == lc]
            rf = [f for f in right.scope.fields if f.name == lc]
            if len(lf) != 1 or len(rf) != 1:
                raise SemanticError(
                    f"USING column {c} must appear exactly once on each side"
                )
            _check_comparable(lf[0].type, rf[0].type)
            pairs.append((lf[0], rf[0]))
        criteria = [(lf.symbol, rf.symbol) for lf, rf in pairs]
        planned = self._build_join(
            j.kind, left, right, criteria, None, scope
        )
        # coalesce each using pair into one output field, drop the pair
        used = {lf.symbol for lf, _ in pairs} | {rf.symbol for _, rf in pairs}
        assigns = []
        fields = []
        for lf, rf in pairs:
            # after RIGHT/FULL rewrites the scope may remap symbols; find
            # the current symbols by field identity
            cur_l = next(
                f for f in planned.scope.fields
                if f.name == lf.name and f.qualifier == lf.qualifier
            )
            cur_r = next(
                f for f in planned.scope.fields
                if f.name == rf.name and f.qualifier == rf.qualifier
                and f is not cur_l
            )
            t = T.common_super_type(cur_l.type, cur_r.type)
            lref = ir.ColumnRef(cur_l.type, cur_l.symbol)
            rref = ir.ColumnRef(cur_r.type, cur_r.symbol)
            e: ir.Expr = ir.Case(
                t,
                (ir.WhenClause(ir.IsNull(lref, negate=True), lref),),
                rref,
            )
            sym = self.symbols.new(lf.name)
            assigns.append((sym, e))
            fields.append(Field(None, lf.name, sym, t))
            used.add(cur_l.symbol)
            used.add(cur_r.symbol)
        for f in planned.scope.fields:
            if f.symbol in used:
                continue
            assigns.append((f.symbol, ir.ColumnRef(f.type, f.symbol)))
            fields.append(f)
        node = P.Project(planned.root, tuple(assigns))
        return RelationPlan(node, Scope(fields))

    def _plan_unnest(
        self, left: RelationPlan, u: ast.UnnestRelation, outer: bool = False
    ) -> RelationPlan:
        """CROSS JOIN UNNEST(arr): one output row per array element, left
        columns replicated (UnnestNode + UnnestOperator; the reference also
        zips multiple arrays/maps — single-array form here)."""
        if len(u.exprs) != 1:
            raise SemanticError("UNNEST supports a single array argument")
        ea = ExprAnalyzer(self, left)
        arr = ea.analyze(u.exprs[0])
        left = ea.relation
        if not getattr(arr.type, "is_array", False):
            raise SemanticError("UNNEST argument must be an array")
        if isinstance(arr, ir.ColumnRef):
            arr_sym = arr.name
            root = left.root
        else:
            arr_sym = self.symbols.new("unnestarr")
            passthrough = [
                (f.symbol, ir.ColumnRef(f.type, f.symbol))
                for f in left.scope.fields
            ]
            root = P.Project(left.root, tuple(passthrough + [(arr_sym, arr)]))
        elem_t = arr.type.element
        elem_sym = self.symbols.new("unnest")
        ord_sym = self.symbols.new("ordinality") if u.ordinality else None
        node = P.Unnest(root, arr_sym, elem_sym, elem_t, ord_sym, outer)
        cols = list(u.columns) if u.columns else []
        elem_name = (cols[0] if cols else (u.alias or "unnest")).lower()
        fields = list(left.scope.fields)
        fields.append(Field(u.alias, elem_name, elem_sym, elem_t))
        if ord_sym is not None:
            ord_name = (cols[1] if len(cols) > 1 else "ordinality").lower()
            fields.append(Field(u.alias, ord_name, ord_sym, T.BIGINT))
        return RelationPlan(node, Scope(fields))

    def _plan_table(self, t: ast.Table) -> RelationPlan:
        name = t.name[-1].lower()
        if name in self.ctes and len(t.name) == 1:
            w = self.ctes[name]
            # avoid infinite recursion for self-referencing names
            saved = dict(self.ctes)
            del self.ctes[name]
            try:
                rp, names = self.plan_query(w.query)
            finally:
                self.ctes = saved
            cols = w.columns or names
            fields = [
                Field(t.alias or name, c.lower(), f.symbol, f.type)
                for c, f in zip(cols, rp.scope.fields)
            ]
            return RelationPlan(rp.root, Scope(fields))
        view = self.metadata.lookup_view(t.name, self.default_catalog)
        if view is not None:
            # view expansion (StatementAnalyzer.java visitTable view
            # branch): plan the stored query in place, renaming output
            # fields to the view's declared columns
            vkey = (view.catalog, view.name.lower())
            expanding = getattr(self, "_expanding_views", None)
            if expanding is None:
                expanding = self._expanding_views = set()
            if vkey in expanding:
                raise SemanticError(
                    f"view is recursive: {view.catalog}.{view.name}"
                )
            expanding.add(vkey)
            saved_catalog = self.default_catalog
            if view.context_catalog is not None:
                self.default_catalog = view.context_catalog
            try:
                rp, _names = self.plan_query(view.query)
            finally:
                self.default_catalog = saved_catalog
                expanding.discard(vkey)
            if len(view.columns) != len(rp.scope.fields):
                raise SemanticError(
                    f"view {view.name} is stale: column count changed"
                )
            # the declared types are part of the view's contract too
            # (VIEW_IS_STALE covers type drift, not just arity): a base
            # table whose column changed type under the view must fail
            # expansion, not silently return the new type
            for (cname, ctype), fld in zip(view.columns, rp.scope.fields):
                if ctype and str(fld.type) != ctype:
                    raise SemanticError(
                        f"view {view.name} is stale: column '{cname}' "
                        f"type changed ({ctype} -> {fld.type})"
                    )
            qual = t.alias or view.name
            fields = [
                Field(qual, c.lower(), f.symbol, f.type)
                for (c, _t), f in zip(view.columns, rp.scope.fields)
            ]
            return RelationPlan(rp.root, Scope(fields))
        catalog, schema = self.metadata.resolve_table(
            t.name, self.default_catalog
        )
        handle = schema.name
        if t.version is not None:
            # time travel: resolve FOR VERSION|TIMESTAMP AS OF to a
            # snapshot id and pin the scan by suffixing the handle —
            # "orders@3" — so splits, stats, caches and data_version all
            # key on the pinned snapshot with no extra plumbing
            kind, expr = t.version
            if isinstance(expr, (ast.Literal, ast.TypedLiteral)):
                value = expr.value
            else:
                raise SemanticError(
                    "FOR VERSION/TIMESTAMP AS OF expects a literal"
                )
            conn = self.metadata.catalogs.get(catalog)
            resolve = getattr(
                conn.metadata(), "resolve_snapshot", None
            )
            if resolve is None:
                raise SemanticError(
                    f"catalog {catalog} does not support time travel"
                )
            try:
                snap = resolve(schema.name, kind, value)
            except (ValueError, KeyError) as exc:
                raise SemanticError(str(exc)) from None
            handle = f"{schema.name}@{snap}"
        assigns = []
        types_ = []
        fields = []
        qual = t.alias or schema.name
        for c in schema.columns:
            sym = self.symbols.new(c.name)
            assigns.append((sym, c.name))
            types_.append((sym, c.type))
            fields.append(Field(qual, c.name.lower(), sym, c.type))
        node: P.PlanNode = P.TableScan(
            catalog, handle, tuple(assigns), tuple(types_)
        )
        if t.sample is not None:
            _, pct = t.sample
            if not (0.0 <= pct <= 100.0):
                raise SemanticError("TABLESAMPLE percentage must be in [0, 100]")
            node = P.Sample(node, pct / 100.0)
        return RelationPlan(node, Scope(fields))

    def _plan_join(self, j: ast.Join) -> RelationPlan:
        if isinstance(j.right, ast.UnnestRelation):
            if j.kind not in ("cross", "inner", "left"):
                raise SemanticError(f"{j.kind} JOIN UNNEST is not supported")
            if j.kind == "left" and j.condition is not None:
                c = j.condition
                if not (isinstance(c, ast.Literal) and c.value is True):
                    raise SemanticError(
                        "LEFT JOIN UNNEST supports ON TRUE only"
                    )
            left = self.plan_relation(j.left)
            return self._plan_unnest(left, j.right, outer=(j.kind == "left"))
        left = self.plan_relation(j.left)
        right = self.plan_relation(j.right)
        scope = Scope(left.scope.fields + right.scope.fields)
        if j.kind == "cross":
            node = P.Join("cross", left.root, right.root, ())
            return RelationPlan(node, scope)
        if j.using:
            return self._plan_using_join(j, left, right, scope)
        ea = ExprAnalyzer(self, RelationPlan(left.root, scope))
        cond = ea.analyze(j.condition)
        lsyms = {f.symbol for f in left.scope.fields}
        rsyms = {f.symbol for f in right.scope.fields}
        criteria, residual = _extract_equi_criteria(cond, lsyms, rsyms)
        if not criteria:
            raise SemanticError("join requires at least one equi condition")
        return self._build_join(
            j.kind, left, right, criteria, residual, scope
        )

    def _build_join(self, kind, left, right, criteria, residual, scope):
        if kind == "right":
            # RIGHT = LEFT with sides swapped; the scope keeps the written
            # column order (plan side order is independent of it)
            node: P.PlanNode = P.Join(
                "left", right.root, left.root,
                tuple((r, l) for l, r in criteria), residual,
            )
            return RelationPlan(node, scope)
        if kind == "full":
            # FULL = LEFT(L, R) union-all right-only rows null-extended on
            # the left side (the LookupOuterOperator unmatched-build pass,
            # expressed as an anti join + projection)
            if residual is not None:
                raise SemanticError(
                    "FULL JOIN supports equi conditions only"
                )
            lj = P.Join(
                "left", left.root, right.root, tuple(criteria), None
            )
            mark = self.symbols.new("fullmark")
            anti = P.Filter(
                P.SemiJoin(
                    right.root, left.root,
                    tuple(r for _, r in criteria),
                    tuple(l for l, _ in criteria),
                    mark,
                ),
                ir.Not(ir.ColumnRef(T.BOOLEAN, mark)),
            )
            lj_syms = lj.output_symbols()
            lj_types = lj.output_types()
            in_right = set(right.root.output_symbols())
            # fresh output symbols: reusing the left-join branch's names
            # would collide in the executor's dictionary registry
            assigns = tuple(
                (
                    self.symbols.new("fn"),
                    ir.ColumnRef(lj_types[s], s) if s in in_right
                    else ir.Constant(lj_types[s], None),
                )
                for s in lj_syms
            )
            proj = P.Project(anti, assigns)
            usyms = tuple(self.symbols.new("fo") for _ in lj_syms)
            union = P.SetOperation(
                "union", True, (lj, proj), usyms,
                tuple((u, lj_types[s]) for u, s in zip(usyms, lj_syms)),
            )
            remap = dict(zip(lj_syms, usyms))
            new_fields = [
                Field(f.qualifier, f.name, remap[f.symbol], f.type)
                for f in scope.fields
            ]
            return RelationPlan(union, Scope(new_fields))
        node = P.Join(kind, left.root, right.root, tuple(criteria), residual)
        return RelationPlan(node, scope)


# ----------------------------------------------------------------------
# expression analysis


def _flatten_ir_and(e: ir.Expr) -> List[ir.Expr]:
    if isinstance(e, ir.Logical) and e.op == "and":
        out: List[ir.Expr] = []
        for t in e.terms:
            out.extend(_flatten_ir_and(t))
        return out
    return [e]


def _combine_ir(terms: List[ir.Expr]) -> ir.Expr:
    return terms[0] if len(terms) == 1 else ir.Logical("and", tuple(terms))


def _as_correlated_equality(c: ir.Expr, outer: set):
    """Match `outer_col = inner_expr` (either orientation); returns
    (outer_symbol, inner_expr) or None."""
    if not (isinstance(c, ir.Comparison) and c.op == "="):
        return None
    lrefs = set(ir.referenced_columns(c.left))
    rrefs = set(ir.referenced_columns(c.right))
    if (
        isinstance(c.left, ir.ColumnRef)
        and c.left.name in outer
        and not (rrefs & outer)
    ):
        return c.left.name, c.right
    if (
        isinstance(c.right, ir.ColumnRef)
        and c.right.name in outer
        and not (lrefs & outer)
    ):
        return c.right.name, c.left
    return None


def _walk_plan_exprs(node: P.PlanNode):
    """All expressions inside a plan subtree (for correlation checks)."""
    if isinstance(node, P.Filter):
        yield node.predicate
    elif isinstance(node, P.Project):
        for _, e in node.assignments:
            yield e
    elif isinstance(node, P.Join) and node.filter is not None:
        yield node.filter
    for s in node.sources:
        yield from _walk_plan_exprs(s)


def _flatten_and(e: ast.Node) -> List[ast.Node]:
    if isinstance(e, ast.LogicalOp) and e.op == "and":
        out = []
        for t in e.terms:
            out.extend(_flatten_and(t))
        return out
    return [e]


def _combine_and(terms: List[ast.Node]) -> ast.Node:
    if len(terms) == 1:
        return terms[0]
    return ast.LogicalOp("and", tuple(terms))


def _derive_name(e: ast.Node, i: int) -> str:
    if isinstance(e, ast.Identifier):
        return e.parts[-1]
    if isinstance(e, ast.FunctionCall):
        return e.name
    return f"_col{i}"


def _ast_children(e: ast.Node):
    """Direct AST children, not descending into subqueries (their
    aggregates/windows belong to the inner query)."""
    if not dataclasses.is_dataclass(e):
        return
    for f in dataclasses.fields(e):
        v = getattr(e, f.name)
        if isinstance(v, ast.Node) and not isinstance(v, ast.Query):
            yield v
        elif isinstance(v, tuple):
            for x in v:
                if isinstance(x, ast.Node) and not isinstance(x, ast.Query):
                    yield x


def _contains_aggregate(e: ast.Node) -> bool:
    if (
        isinstance(e, ast.FunctionCall)
        and e.name in AGGREGATES
        and e.window is None
    ):
        return True
    return any(_contains_aggregate(c) for c in _ast_children(e))


def _contains_window(e: ast.Node) -> bool:
    if isinstance(e, ast.FunctionCall) and e.window is not None:
        return True
    return any(_contains_window(c) for c in _ast_children(e))


def _window_subexprs(call: ast.FunctionCall):
    """Value/partition/order expressions of a windowed call (the parts
    evaluated against the window's input relation)."""
    if not call.is_star:
        yield from call.args
    yield from call.window.partition_by
    for si in call.window.order_by:
        yield si.expr


def _extract_equi_criteria(cond: ir.Expr, lsyms, rsyms):
    conj: List[ir.Expr] = []

    def flat(e):
        if isinstance(e, ir.Logical) and e.op == "and":
            for t in e.terms:
                flat(t)
        else:
            conj.append(e)

    flat(cond)
    criteria = []
    residual = []
    for c in conj:
        if isinstance(c, ir.Comparison) and c.op == "=":
            ls = set(ir.referenced_columns(c.left))
            rs = set(ir.referenced_columns(c.right))
            if (
                isinstance(c.left, ir.ColumnRef)
                and isinstance(c.right, ir.ColumnRef)
            ):
                if c.left.name in lsyms and c.right.name in rsyms:
                    criteria.append((c.left.name, c.right.name))
                    continue
                if c.left.name in rsyms and c.right.name in lsyms:
                    criteria.append((c.right.name, c.left.name))
                    continue
        residual.append(c)
    res = None
    if residual:
        res = residual[0] if len(residual) == 1 else ir.Logical(
            "and", tuple(residual)
        )
    return criteria, res


class ExprAnalyzer:
    """AST expression -> typed ir over the relation's symbols.

    Scalar subqueries extend self.relation via ScalarJoin (SubqueryPlanner).
    """

    def __init__(self, analyzer: Analyzer, relation: RelationPlan):
        self.a = analyzer
        self.relation = relation
        # symbols produced by scalar subqueries (allowed post-aggregation)
        self.scalar_syms: set = set()
        # lambda parameter types, bound while analyzing a lambda body
        self.lambda_bindings: Dict[str, T.Type] = {}

    # -- entry ----------------------------------------------------------
    def analyze(self, e: ast.Node) -> ir.Expr:
        out = self._an(e)
        return out

    def _resolve_column(self, parts) -> ir.Expr:
        key = tuple(p.lower() for p in parts)
        if len(key) == 1 and key[0] in self.a.window_fields:
            # placeholder for an extracted window function output
            return ir.ColumnRef(self.a.window_fields[key[0]], key[0])
        try:
            f = self.relation.scope.resolve(key)
        except SemanticError:
            # correlated reference into an enclosing query's scope
            for i in range(len(self.a.outer_scopes) - 1, -1, -1):
                try:
                    f = self.a.outer_scopes[i].resolve(key)
                except SemanticError:
                    continue
                for lvl in range(i, len(self.a.correlation_used)):
                    self.a.correlation_used[lvl][f.symbol] = f.type
                return ir.ColumnRef(f.type, f.symbol)
            raise
        return ir.ColumnRef(f.type, f.symbol)

    def _an(self, e: ast.Node) -> ir.Expr:
        if isinstance(e, ast.Resolved):
            return e.expr
        if isinstance(e, ast.Identifier):
            if (len(e.parts) == 1
                    and e.parts[0].lower() in self.lambda_bindings):
                name = e.parts[0].lower()
                return ir.ColumnRef(self.lambda_bindings[name], name)
            return self._resolve_column(e.parts)
        if isinstance(e, ast.ArrayLiteral):
            return self._array_literal(e)
        if isinstance(e, ast.Lambda):
            raise SemanticError(
                "lambda expressions are only valid as arguments of "
                "higher-order functions (transform, filter, reduce, ...)"
            )
        if isinstance(e, ast.Literal):
            return _literal(e)
        if isinstance(e, ast.TypedLiteral):
            return _typed_literal(e)
        if isinstance(e, ast.UnaryOp):
            v = self._an(e.operand)
            return _fold(ir.Call(v.type, "negate", (v,)))
        if isinstance(e, ast.BinaryOp):
            l, r = self._an(e.left), self._an(e.right)
            return _fold(_binary(e.op, l, r))
        if isinstance(e, ast.ComparisonOp):
            l, r = self._an(e.left), self._an(e.right)
            _check_comparable(l.type, r.type)
            return ir.Comparison(e.op, l, r)
        if isinstance(e, ast.LogicalOp):
            return ir.Logical(e.op, tuple(self._an(t) for t in e.terms))
        if isinstance(e, ast.NotOp):
            return ir.Not(self._an(e.operand))
        if isinstance(e, ast.IsNullOp):
            return ir.IsNull(self._an(e.operand), e.negate)
        if isinstance(e, ast.BetweenOp):
            return ir.Between(
                self._an(e.value), self._an(e.low), self._an(e.high), e.negate
            )
        if isinstance(e, ast.InList):
            return ir.In(
                self._an(e.value),
                tuple(self._an(i) for i in e.items),
                e.negate,
            )
        if isinstance(e, ast.LikeOp):
            v = self._an(e.value)
            pat = self._an(e.pattern)
            args = [v, pat]
            if e.escape is not None:
                args.append(self._an(e.escape))
            call = ir.Call(T.BOOLEAN, "like", tuple(args))
            return ir.Not(call) if e.negate else call
        if isinstance(e, ast.FunctionCall):
            return self._function(e)
        if isinstance(e, ast.CastOp):
            to = T.parse_type(e.type_name)
            return _fold(ir.Cast(to, self._an(e.operand)))
        if isinstance(e, ast.ExtractOp):
            v = self._an(e.operand)
            field = {
                "dow": "day_of_week",
                "doy": "day_of_year",
                "yow": "year_of_week",
            }.get(e.field, e.field)
            if field not in (
                "year", "month", "day", "quarter", "week",
                "day_of_week", "day_of_year", "day_of_month", "year_of_week",
            ):
                raise SemanticError(f"extract({e.field}) unsupported")
            return ir.Call(T.BIGINT, field, (v,))
        if isinstance(e, ast.CaseExpr):
            return self._case(e)
        if isinstance(e, ast.ScalarSubquery):
            return self._scalar_subquery(e.query)
        if isinstance(e, (ast.InSubquery, ast.Exists)):
            raise SemanticError(
                "IN/EXISTS subqueries are only supported as top-level WHERE conjuncts"
            )
        raise SemanticError(f"unsupported expression: {type(e).__name__}")

    def _case(self, e: ast.CaseExpr) -> ir.Expr:
        whens = []
        if e.operand is not None:
            op = self._an(e.operand)
            for w in e.whens:
                cond = ir.Comparison("=", op, self._an(w.condition))
                whens.append(ir.WhenClause(cond, self._an(w.result)))
        else:
            for w in e.whens:
                c = self._an(w.condition)
                if c.type != T.BOOLEAN:
                    raise SemanticError("CASE WHEN must be boolean")
                whens.append(ir.WhenClause(c, self._an(w.result)))
        default = self._an(e.default) if e.default is not None else None
        rts = [w.result.type for w in whens] + (
            [default.type] if default is not None else []
        )
        rt = rts[0]
        for t in rts[1:]:
            rt = T.common_super_type(rt, t)
        return ir.Case(rt, tuple(whens), default)

    def _function(self, e: ast.FunctionCall) -> ir.Expr:
        if e.window is not None:
            raise SemanticError(
                "window functions are only allowed in the SELECT list"
            )
        if e.name in WINDOW_ONLY_FUNCTIONS:
            raise SemanticError(f"{e.name}() requires an OVER clause")
        if e.name in AGGREGATES:
            raise SemanticError(
                f"aggregate {e.name}() not allowed here"
            )
        if e.name in ("year", "month", "day", "quarter"):
            return ir.Call(T.BIGINT, e.name, (self._an(e.args[0]),))
        if e.name in ("abs",):
            v = self._an(e.args[0])
            return ir.Call(v.type, "abs", (v,))
        if e.name == "sqrt":
            return ir.Call(T.DOUBLE, "sqrt", (self._an(e.args[0]),))
        if e.name in ("round", "floor", "ceil", "ceiling"):
            v = self._an(e.args[0])
            args = [v]
            rt = v.type
            if e.name == "round" and len(e.args) > 1:
                args.append(self._an(e.args[1]))
            if e.name in ("floor", "ceil", "ceiling") and v.type.is_decimal:
                rt = T.decimal(v.type.precision, 0)
            return ir.Call(rt, e.name, tuple(args))
        if e.name == "length":
            return ir.Call(T.BIGINT, "length", (self._an(e.args[0]),))
        if e.name in ("substring", "substr"):
            args = tuple(self._an(a) for a in e.args)
            if not args[0].type.is_dictionary:
                raise SemanticError("substring() requires a varchar argument")
            return ir.Call(T.VARCHAR, "substring", args)
        if e.name == "coalesce":
            args = tuple(self._an(a) for a in e.args)
            rt = args[0].type
            for a in args[1:]:
                rt = T.common_super_type(rt, a.type)
            # lower as CASE WHEN a IS NOT NULL THEN a ...
            whens = tuple(
                ir.WhenClause(ir.IsNull(a, negate=True), a) for a in args[:-1]
            )
            return ir.Case(rt, whens, args[-1])
        if e.name == "nullif":
            a, b = self._an(e.args[0]), self._an(e.args[1])
            # CASE WHEN a = b THEN null ELSE a
            whens = (
                ir.WhenClause(
                    ir.Comparison("=", a, b), ir.Constant(a.type, None)
                ),
            )
            return ir.Case(a.type, whens, a)
        if e.name == "if":
            c = self._an(e.args[0])
            t = self._an(e.args[1])
            f = self._an(e.args[2]) if len(e.args) > 2 else None
            rt = t.type if f is None else T.common_super_type(t.type, f.type)
            return ir.Case(rt, (ir.WhenClause(c, t),), f)
        if e.name in ("try", "try_cast"):
            # our kernels already mask error rows to NULL (divide-by-zero,
            # bad casts), matching TRY semantics without a control transfer
            return self._an(e.args[0])
        fdef = self.a.sql_functions.get(e.name)
        if fdef is not None and not e.is_star and e.window is None:
            return self._expand_sql_function(fdef, e)
        if e.name in ("transform", "filter", "any_match", "all_match",
                      "none_match", "reduce"):
            return self._lambda_call(e)
        if e.name == "sequence":
            return self._sequence(e)
        if e.name == "map":
            return self._map_constructor(e)
        if e.name in ("current_date", "current_timestamp", "now",
                      "localtimestamp"):
            # evaluated once per query at analysis (reference: constant per
            # query via Session start time); nondeterministic_origin keeps
            # the FunctionMetadata.isDeterministic bit visible after
            # folding so plan/result caches never reuse the frozen instant
            now = datetime.datetime.now(datetime.timezone.utc)
            if e.name == "current_date":
                d = now.date()
                return ir.Constant(
                    T.DATE, days_from_civil(d.year, d.month, d.day),
                    nondeterministic_origin=True,
                )
            us = int(now.timestamp() * 1_000_000)
            return ir.Constant(
                T.TIMESTAMP, us, nondeterministic_origin=True
            )
        if e.name in ("rand", "random"):
            # per-row pseudorandom double in [0, 1): the kernel is a pure
            # function of (row index, seed) so the traced program stays
            # deterministic per execution while each QUERY draws a fresh
            # analysis-time seed (never folded, never cached)
            if e.args:
                raise SemanticError(f"{e.name}() takes no arguments")
            seed = _random.getrandbits(63)
            return ir.Call(
                T.DOUBLE, "rand", (ir.Constant(T.BIGINT, seed),)
            )
        from ..expr.functions import SIGNATURES

        if e.name in SIGNATURES:
            args = tuple(self._an(a) for a in e.args)
            try:
                rt = SIGNATURES[e.name](args)
            except (ValueError, TypeError) as err:
                raise SemanticError(str(err)) from err
            return _fold(ir.Call(rt, e.name, args))
        raise SemanticError(f"unknown function: {e.name}")

    def _expand_sql_function(self, fdef: "SqlFunction",
                             e: ast.FunctionCall) -> ir.Expr:
        """Inline a CREATE FUNCTION body with arguments substituted for
        parameters, then analyze it (the reference compiles routine IR to
        bytecode; this engine inlines the expression so it fuses into the
        surrounding kernel)."""
        if fdef.name in self.a._udf_stack:
            raise SemanticError(
                f"recursive SQL function {fdef.name} is not supported"
            )
        if len(e.args) != len(fdef.params):
            raise SemanticError(
                f"{fdef.name}() takes {len(fdef.params)} argument(s)"
            )
        # arguments are analyzed in the caller's scope FIRST (so a nested
        # call of the same function in an argument is not mistaken for
        # recursion), then adopt the declared parameter types
        mapping = {}
        for (p, ptype), arg in zip(fdef.params, e.args):
            a = self._an(arg)
            pt = T.parse_type(ptype)
            if a.type != pt:
                a = _fold(ir.Cast(pt, a))
            mapping[p.lower()] = ast.Resolved(a)

        def subst(n):
            if (isinstance(n, ast.Identifier) and len(n.parts) == 1
                    and n.parts[0].lower() in mapping):
                return mapping[n.parts[0].lower()]
            return n

        body = ast.transform(fdef.body, subst)
        self.a._udf_stack.add(fdef.name)
        try:
            expr = self._an(body)
        finally:
            self.a._udf_stack.discard(fdef.name)
        rt = T.parse_type(fdef.return_type)
        if expr.type != rt:
            expr = _fold(ir.Cast(rt, expr))
        return expr

    def _array_literal(self, e: ast.ArrayLiteral) -> ir.Expr:
        """ARRAY[...] of constants -> ir.Constant with a tuple value
        (ArrayConstructor; non-constant elements are out of scope — array
        columns are dictionary-encoded, see types.ArrayType)."""
        items = tuple(_fold(self._an(x)) for x in e.items)
        if not items:
            return ir.Constant(T.array_of(T.UNKNOWN), ())
        if not all(isinstance(x, ir.Constant) for x in items):
            raise SemanticError(
                "ARRAY[...] elements must be constants in this engine"
            )
        et = items[0].type
        for x in items[1:]:
            et = T.common_super_type(et, x.type)
        if et.name == "unknown":
            et = T.BIGINT
        vals = tuple(_coerce_const_value(x, et) for x in items)
        return ir.Constant(T.array_of(et), vals)

    def _sequence(self, e: ast.FunctionCall) -> ir.Expr:
        args = [_fold(self._an(a)) for a in e.args]
        if not (2 <= len(args) <= 3) or not all(
            isinstance(a, ir.Constant) and a.value is not None for a in args
        ):
            raise SemanticError("sequence() requires constant bounds")
        start, stop = int(args[0].value), int(args[1].value)
        step = int(args[2].value) if len(args) > 2 else (
            1 if stop >= start else -1
        )
        if step == 0:
            raise SemanticError("sequence() step must not be zero")
        if len(range(start, stop + (1 if step > 0 else -1), step)) > 10000:
            raise SemanticError("sequence is too large (max 10000)")
        vals = tuple(range(start, stop + (1 if step > 0 else -1), step))
        return ir.Constant(T.array_of(T.BIGINT), vals)

    def _map_constructor(self, e: ast.FunctionCall) -> ir.Expr:
        """map(ARRAY[k...], ARRAY[v...]) over constants -> map Constant
        (MapConstructor; duplicate keys rejected like the reference)."""
        if len(e.args) == 0:
            return ir.Constant(T.map_of(T.UNKNOWN, T.UNKNOWN), ())
        if len(e.args) != 2:
            raise SemanticError("map(keys_array, values_array)")
        ka = _fold(self._an(e.args[0]))
        va = _fold(self._an(e.args[1]))
        for a in (ka, va):
            if not (isinstance(a, ir.Constant)
                    and getattr(a.type, "is_array", False)):
                raise SemanticError(
                    "map() requires constant array arguments in this engine"
                )
        if len(ka.value) != len(va.value):
            raise SemanticError("map() key and value arrays differ in length")
        if any(k is None for k in ka.value):
            raise SemanticError("map keys cannot be NULL")
        if len(set(ka.value)) != len(ka.value):
            raise SemanticError("duplicate map keys")
        entries = tuple(zip(ka.value, va.value))
        return ir.Constant(
            T.map_of(ka.type.element, va.type.element), entries
        )

    def _lambda_call(self, e: ast.FunctionCall) -> ir.Expr:
        """Higher-order functions: type the lambda body with its parameter
        bound to the element type (FunctionResolver's function-type
        inference for ArrayTransformFunction etc.)."""

        def analyze_lambda(lam: ast.Node, bindings: Dict[str, T.Type]):
            if not isinstance(lam, ast.Lambda):
                raise SemanticError(f"{e.name}() expects a lambda argument")
            if len(lam.params) != len(bindings):
                raise SemanticError(
                    f"lambda must take {len(bindings)} parameter(s)"
                )
            names = [p.lower() for p in lam.params]
            saved = dict(self.lambda_bindings)
            self.lambda_bindings.update(zip(names, bindings.values()))
            try:
                body = self._an(lam.body)
            finally:
                self.lambda_bindings = saved
            return ir.Lambda(body.type, tuple(names), body)

        arr = self._an(e.args[0])
        if not getattr(arr.type, "is_array", False):
            raise SemanticError(f"{e.name}() requires an array argument")
        et = arr.type.element
        if e.name == "reduce":
            if len(e.args) != 4:
                raise SemanticError(
                    "reduce(array, initial, (s, x) -> ..., s -> ...)"
                )
            init = _fold(self._an(e.args[1]))
            if not isinstance(init, ir.Constant):
                raise SemanticError("reduce() initial state must be constant")
            st = init.type if init.type.name != "unknown" else T.BIGINT
            step = analyze_lambda(e.args[2], {"s": st, "x": et})
            try:
                st2 = T.common_super_type(st, step.type)
            except TypeError:
                st2 = step.type
            if st2 != st:
                step = analyze_lambda(e.args[2], {"s": st2, "x": et})
            out = analyze_lambda(e.args[3], {"s": st2})
            return ir.Call(out.type, "reduce", (arr, init, step, out))
        if len(e.args) != 2:
            raise SemanticError(f"{e.name}(array, lambda)")
        lam = analyze_lambda(e.args[1], {"x": et})
        if e.name == "transform":
            rt: T.Type = T.array_of(lam.type)
        elif e.name == "filter":
            rt = arr.type
        else:
            rt = T.BOOLEAN
        return ir.Call(rt, e.name, (arr, lam))

    def _scalar_subquery(self, q: ast.Query) -> ir.Expr:
        sub, _, corr = self.a._plan_subquery_correlated(q, self.relation.scope)
        if len(sub.scope.fields) != 1:
            raise SemanticError("scalar subquery must return one column")
        f = sub.scope.fields[0]
        if corr:
            # correlated scalar aggregate -> grouped aggregate + LEFT join
            # (TransformCorrelatedScalarAggregationToJoin)
            new_root, pairs, residuals = self.a._decorrelate(sub.root, corr)
            if not pairs:
                raise SemanticError("correlated scalar subquery without equality")
            if residuals:
                raise SemanticError(
                    "non-equality correlation in scalar subquery unsupported"
                )
            node = P.Join(
                "left",
                self.relation.root,
                new_root,
                tuple(pairs),
                expansion=False,  # grouped by the correlation keys -> unique
            )
            self.relation = RelationPlan(node, self.relation.scope)
            self.scalar_syms.add(f.symbol)
            return ir.ColumnRef(f.type, f.symbol)
        node = P.ScalarJoin(self.relation.root, sub.root)
        self.relation = RelationPlan(node, self.relation.scope)
        self.scalar_syms.add(f.symbol)
        return ir.ColumnRef(f.type, f.symbol)


class AggCollector(ExprAnalyzer):
    """Post-aggregation expression analyzer: extracts aggregate calls into
    AggInfo entries (pre-projected args) and rewrites group-key expressions
    to key symbols (AggregationAnalyzer + QueryPlanner combined)."""

    def __init__(self, analyzer, relation, key_map, pre_assigns,
                 grouping_sets=None, gid_ref=None):
        super().__init__(analyzer, relation)
        self.key_map = key_map  # [(key ir expr, key symbol ref)]
        self.pre_relation = relation  # pre-aggregation scope for resolution
        self.pre_assigns = pre_assigns
        self.aggs: List[P.AggInfo] = []
        self._agg_cache: Dict[tuple, ir.ColumnRef] = {}
        # scalar subqueries in HAVING/post-agg expressions join ABOVE the
        # aggregation (the reference plans Apply above AggregationNode)
        self.pending_scalar: List[P.PlanNode] = []
        # GROUPING SETS context: per-set key-symbol tuples + the group-id
        # column, for grouping() rewriting (GroupingOperationRewriter analog)
        self.grouping_sets = grouping_sets
        self.gid_ref = gid_ref
        if gid_ref is not None:
            self.scalar_syms.add(gid_ref.name)

    def _scalar_subquery(self, q: ast.Query) -> ir.Expr:
        sub, _, corr = self.a._plan_subquery_correlated(q, self.relation.scope)
        if corr:
            raise SemanticError(
                "correlated scalar subquery in post-aggregation position "
                "is not supported"
            )
        if len(sub.scope.fields) != 1:
            raise SemanticError("scalar subquery must return one column")
        f = sub.scope.fields[0]
        self.pending_scalar.append(sub.root)
        self.scalar_syms.add(f.symbol)
        return ir.ColumnRef(f.type, f.symbol)

    def analyze_post(self, e: ast.Node) -> ir.Expr:
        out = self._post(e)
        self._validate(out)
        return out

    def _post(self, e: ast.Node) -> ir.Expr:
        if (
            isinstance(e, ast.FunctionCall)
            and e.name in AGGREGATES
            and e.window is None
        ):
            return self._aggregate_call(e)
        if (
            isinstance(e, ast.FunctionCall)
            and e.name == "grouping"
            and e.window is None
        ):
            return self._grouping_call(e)
        # try: whole expression equals a group key
        try:
            full = self._an(e)
        except SemanticError:
            full = None
        if full is not None:
            for ke, ref in self.key_map:
                if full == ke:
                    return ref
        # recurse structurally
        if isinstance(e, ast.BinaryOp):
            return _fold(_binary(e.op, self._post(e.left), self._post(e.right)))
        if isinstance(e, ast.UnaryOp):
            v = self._post(e.operand)
            return ir.Call(v.type, "negate", (v,))
        if isinstance(e, ast.ComparisonOp):
            return ir.Comparison(e.op, self._post(e.left), self._post(e.right))
        if isinstance(e, ast.LogicalOp):
            return ir.Logical(e.op, tuple(self._post(t) for t in e.terms))
        if isinstance(e, ast.NotOp):
            return ir.Not(self._post(e.operand))
        if isinstance(e, ast.CaseExpr):
            whens = []
            if e.operand is not None:
                op = self._post(e.operand)
                for w in e.whens:
                    whens.append(
                        ir.WhenClause(
                            ir.Comparison("=", op, self._post(w.condition)),
                            self._post(w.result),
                        )
                    )
            else:
                whens = [
                    ir.WhenClause(self._post(w.condition), self._post(w.result))
                    for w in e.whens
                ]
            default = self._post(e.default) if e.default is not None else None
            rts = [w.result.type for w in whens] + (
                [default.type] if default else []
            )
            rt = rts[0]
            for t in rts[1:]:
                rt = T.common_super_type(rt, t)
            return ir.Case(rt, tuple(whens), default)
        if isinstance(e, ast.CastOp):
            return _fold(ir.Cast(T.parse_type(e.type_name), self._post(e.operand)))
        if full is not None:
            return full
        return self._an(e)  # will raise a descriptive error

    def _grouping_call(self, e: ast.FunctionCall) -> ir.Expr:
        """grouping(a, b, ...) -> bitmask, bit i (MSB-first) set when the
        i-th argument is absent from the row's grouping set.  Lowered to a
        CASE over the group-id column, whose value is known per set at plan
        time (sql/planner/GroupingOperationRewriter analog)."""
        if e.is_star or not e.args:
            raise SemanticError("grouping() requires arguments")
        refs: List[ir.ColumnRef] = []
        for a in e.args:
            ae = self._an(a)
            for ke, ref in self.key_map:
                if ae == ke:
                    refs.append(ref)
                    break
            else:
                raise SemanticError(
                    "grouping() arguments must appear in GROUP BY"
                )
        if self.gid_ref is None or self.grouping_sets is None:
            return ir.Constant(T.BIGINT, 0)  # plain GROUP BY: all bits 0
        nbits = len(refs)
        masks = []
        for st in self.grouping_sets:
            m = 0
            for j, ref in enumerate(refs):
                if ref.name not in st:
                    m |= 1 << (nbits - 1 - j)
            masks.append(m)
        whens = tuple(
            ir.WhenClause(
                ir.Comparison("=", self.gid_ref, ir.Constant(T.BIGINT, g)),
                ir.Constant(T.BIGINT, m),
            )
            for g, m in enumerate(masks[:-1])
        )
        return ir.Case(T.BIGINT, whens, ir.Constant(T.BIGINT, masks[-1]))

    def _aggregate_call(self, e: ast.FunctionCall) -> ir.ColumnRef:
        kind = AGG_ALIASES.get(e.name, e.name)
        arg_sym = arg2_sym = None
        in_t = in2_t = None
        param = None

        def to_symbol(arg: ir.Expr, label: str) -> str:
            if isinstance(arg, ir.ColumnRef):
                return arg.name
            sym = self.a.symbols.new(label)
            self.pre_assigns.append((sym, arg))
            return sym

        if e.is_star:
            kind = "count_star"
            out_t = T.BIGINT
        elif kind in TWO_ARG_AGGREGATES:
            if len(e.args) != 2:
                raise SemanticError(f"{e.name} takes two arguments")
            arg = self._an(e.args[0])
            in_t = arg.type
            arg_sym = to_symbol(arg, f"{kind}arg")
            if kind == "approx_percentile":
                # second argument is the constant percentile fraction
                p = self._an(e.args[1])
                if not isinstance(p, ir.Constant) or p.value is None:
                    raise SemanticError(
                        "approx_percentile requires a constant percentile"
                    )
                param = float(p.value) / (
                    10 ** p.type.scale if p.type.is_decimal else 1
                )
                if not (0.0 <= param <= 1.0):
                    raise SemanticError("percentile must be in [0, 1]")
            elif kind == "listagg":
                # second argument is the constant separator string
                p = self._an(e.args[1])
                if not isinstance(p, ir.Constant) or not isinstance(
                    p.value, str
                ):
                    raise SemanticError(
                        "listagg requires a constant varchar separator"
                    )
                param = p.value
            else:
                arg2 = self._an(e.args[1])
                in2_t = arg2.type
                arg2_sym = to_symbol(arg2, f"{kind}arg2")
            out_t = _agg_output_type(kind, in_t, in2_t)
        else:
            # approx_distinct accepts an optional max-standard-error second
            # argument (ignored: this engine's implementation is exact)
            nargs = len(e.args)
            if kind == "approx_distinct" and nargs == 2:
                nargs = 1  # drop the max-standard-error argument
            if nargs != 1:
                raise SemanticError(f"{e.name} takes one argument")
            arg = self._an(e.args[0])  # pre-agg scope
            in_t = arg.type
            out_t = _agg_output_type(kind, in_t)
            arg_sym = to_symbol(arg, f"{kind}arg")
        cache_key = (kind, arg_sym, arg2_sym, param, e.distinct)
        if cache_key in self._agg_cache:
            return self._agg_cache[cache_key]
        out_sym = self.a.symbols.new(kind)
        self.aggs.append(
            P.AggInfo(out_sym, kind, arg_sym, e.distinct, in_t, out_t,
                      arg2_sym, in2_t, param)
        )
        ref = ir.ColumnRef(out_t, out_sym)
        self._agg_cache[cache_key] = ref
        return ref

    def _validate(self, e: ir.Expr):
        allowed = (
            {r.name for _, r in self.key_map}
            | {a.output for a in self.aggs}
            | self.scalar_syms
            | set(self.a.window_fields)
        )
        for n in ir.walk(e):
            if isinstance(n, ir.ColumnRef) and n.name not in allowed:
                raise SemanticError(
                    f"'{n.name}' must appear in GROUP BY or inside an aggregate"
                )


class PostAggAnalyzer:
    """Re-analyzes select/order expressions after aggregation planning,
    reusing the AggCollector's extraction results."""

    def __init__(self, analyzer, relation, collector: AggCollector, cache, items):
        self.a = analyzer
        self.relation = relation
        self.collector = collector
        self._cache = cache  # id(ast item) -> analyzed expr
        self._items = items

    def analyze(self, e: ast.Node) -> ir.Expr:
        for iid, expr in self._cache.items():
            if self._items.get(iid) is not None and self._items[iid].expr is e:
                return expr
        # order-by style expression referencing keys/aggs: resolve against
        # the pre-aggregation scope so group-key expressions match
        self.collector.relation = self.collector.pre_relation
        return self.collector.analyze_post(e)


# ----------------------------------------------------------------------
# literals, folding, typing helpers


def _coerce_const_value(c: "ir.Constant", t: T.Type):
    """Constant value -> IR convention of type t (decimal rescale etc.)."""
    if c.value is None:
        return None
    if t.is_decimal:
        cs = c.type.scale if c.type.is_decimal else 0
        if t.scale >= cs:
            return int(c.value) * 10 ** (t.scale - cs)
        return int(c.value) // 10 ** (cs - t.scale)
    if t.name in ("double", "real"):
        if c.type.is_decimal:
            return float(c.value) / 10 ** c.type.scale
        return float(c.value)
    return c.value


def _literal(e: ast.Literal) -> ir.Constant:
    if e.kind == "integer":
        return ir.Constant(T.BIGINT, int(e.value))
    if e.kind == "double":
        return ir.Constant(T.DOUBLE, float(e.value))
    if e.kind == "decimal":
        txt = str(e.value)
        if "." in txt:
            whole, frac = txt.split(".")
        else:
            whole, frac = txt, ""
        scale = len(frac)
        unscaled = int((whole + frac) or "0")
        precision = max(len((whole + frac).lstrip("0")), scale + 1)
        return ir.Constant(T.decimal(min(38, precision), scale), unscaled)
    if e.kind == "string":
        return ir.Constant(T.VARCHAR, e.value)
    if e.kind == "boolean":
        return ir.Constant(T.BOOLEAN, bool(e.value))
    if e.kind == "null":
        return ir.Constant(T.UNKNOWN, None)
    raise SemanticError(f"literal kind {e.kind}")


def _typed_literal(e: ast.TypedLiteral) -> ir.Constant:
    if e.kind == "date":
        y, m, d = map(int, e.value.split("-"))
        return ir.Constant(T.DATE, days_from_civil(y, m, d))
    if e.kind == "timestamp":
        # 'YYYY-MM-DD[ HH:MM:SS]' -> microseconds
        parts = e.value.split(" ")
        y, m, d = map(int, parts[0].split("-"))
        us = days_from_civil(y, m, d) * 86_400_000_000
        if len(parts) > 1:
            hh, mm, ss = (parts[1].split(":") + ["0", "0"])[:3]
            us += (int(hh) * 3600 + int(mm) * 60 + int(float(ss))) * 1_000_000
        return ir.Constant(T.TIMESTAMP, us)
    if e.kind == "interval":
        n = int(e.value)
        unit = e.unit.rstrip("s")
        # represented as a bigint day count (day) or month count (month/year)
        if unit == "day":
            return ir.Constant(_INTERVAL_DAY, n)
        if unit == "week":
            return ir.Constant(_INTERVAL_DAY, 7 * n)
        if unit == "month":
            return ir.Constant(_INTERVAL_MONTH, n)
        if unit == "year":
            return ir.Constant(_INTERVAL_MONTH, 12 * n)
        raise SemanticError(f"interval unit {e.unit}")
    raise SemanticError(f"typed literal {e.kind}")


_INTERVAL_DAY = T.FixedWidthType("interval_day", "int64")
_INTERVAL_MONTH = T.FixedWidthType("interval_month", "int64")


def _binary(op: str, l: ir.Expr, r: ir.Expr) -> ir.Expr:
    name = {
        "+": "add",
        "-": "subtract",
        "*": "multiply",
        "/": "divide",
        "%": "modulus",
        "||": "concat",
    }[op]
    if name == "concat":
        raise SemanticError("|| not supported yet")
    # date/interval arithmetic
    if l.type.name == "date" and r.type is _INTERVAL_DAY:
        return ir.Call(T.DATE, name, (l, ir.Constant(T.BIGINT, r.value if isinstance(r, ir.Constant) else None)))
    if l.type is _INTERVAL_DAY and r.type.name == "date" and name == "add":
        return ir.Call(T.DATE, name, (r, ir.Constant(T.BIGINT, l.value)))
    if l.type.name == "date" and r.type is _INTERVAL_MONTH:
        if not isinstance(l, ir.Constant) or not isinstance(r, ir.Constant):
            raise SemanticError(
                "date +/- interval month/year requires constant date for now"
            )
        return ir.Constant(T.DATE, _add_months(l.value, r.value if name == "add" else -r.value))
    rt = arith_result_type(name, l.type, r.type)
    return ir.Call(rt, name, (l, r))


def _add_months(epoch_days: int, months: int) -> int:
    d = datetime.date(1970, 1, 1) + datetime.timedelta(days=epoch_days)
    y = d.year + (d.month - 1 + months) // 12
    m = (d.month - 1 + months) % 12 + 1
    import calendar

    day = min(d.day, calendar.monthrange(y, m)[1])
    return (datetime.date(y, m, day) - datetime.date(1970, 1, 1)).days


def _check_comparable(a: T.Type, b: T.Type):
    if a.name == "unknown" or b.name == "unknown":
        return
    try:
        T.common_super_type(a, b)
    except TypeError:
        raise SemanticError(f"cannot compare {a} and {b}")


def _agg_output_type(
    kind: str, in_t: T.Type, in2_t: Optional[T.Type] = None
) -> T.Type:
    if kind in ("count", "count_if", "approx_distinct"):
        if kind == "count_if" and in_t.name not in ("boolean", "unknown"):
            raise SemanticError("count_if requires a boolean argument")
        return T.BIGINT
    if kind == "approx_percentile":
        if not T.is_numeric(in_t) and in_t.name != "unknown":
            raise SemanticError("approx_percentile requires a numeric argument")
        return in_t
    if kind == "array_agg":
        return T.array_of(in_t)
    if kind == "map_agg":
        if in2_t is None:
            raise SemanticError("map_agg(key, value) takes two arguments")
        return T.map_of(in_t, in2_t)
    if kind == "listagg":
        return T.VARCHAR
    if kind in ("min", "max", "arbitrary"):
        return in_t
    if kind in ("min_by", "max_by"):
        if in2_t is not None and not in2_t.orderable:
            raise SemanticError(f"{kind} ordering key must be orderable")
        return in_t
    if kind == "sum":
        if in_t.is_decimal:
            # Trino: sum(decimal(p,s)) -> decimal(38,s) with an Int128
            # accumulator (DecimalSumAggregation); wide chunked sums in
            # ops/aggregation.py make this exact
            return T.decimal(38, in_t.scale)
        if in_t.name in ("double", "real"):
            return T.DOUBLE
        return T.BIGINT
    if kind == "avg":
        if in_t.is_decimal:
            # scale 6 keeps boundary comparisons (e.g. Q17's qty < 0.2*avg)
            # within rounding noise of exact decimal(38) math; integer
            # digits are preserved (Trino: avg(decimal(p,s)) keeps p)
            s = max(in_t.scale, 6)
            return T.decimal(
                min(38, max(in_t.precision - in_t.scale + s, 18)), s
            )
        return T.DOUBLE
    if kind in ("var_samp", "var_pop", "stddev_samp", "stddev_pop",
                "geometric_mean", "covar_pop", "covar_samp", "corr",
                "regr_slope", "regr_intercept"):
        for t in (in_t, in2_t):
            if t is not None and not T.is_numeric(t) and t.name != "unknown":
                raise SemanticError(f"{kind} requires numeric arguments")
        return T.DOUBLE
    if kind in ("bool_and", "bool_or"):
        if in_t.name not in ("boolean", "unknown"):
            raise SemanticError(f"{kind} requires a boolean argument")
        return T.BOOLEAN
    if kind in ("bitwise_and_agg", "bitwise_or_agg", "bitwise_xor_agg"):
        if not T.is_integral(in_t) and in_t.name != "unknown":
            raise SemanticError(f"{kind} requires an integral argument")
        return T.BIGINT
    if kind == "checksum":
        return T.BIGINT
    raise SemanticError(kind)


# constant folding -------------------------------------------------------


def _fold(e: ir.Expr) -> ir.Expr:
    """Evaluate constant-only arithmetic/cast at analysis time
    (IrExpressionInterpreter / constant folding analog)."""
    if isinstance(e, ir.Call):
        # the isDeterministic bit gates folding (the reference's
        # ExpressionInterpreter does the same): rand(seed) over constants
        # is still a fresh value per row
        if e.name in ir.NONDETERMINISTIC_FUNCTIONS:
            return e
        if not all(isinstance(a, ir.Constant) for a in e.args):
            return e
        if any(a.value is None for a in e.args):
            return ir.Constant(e.type, None)
        try:
            v = _eval_const(e.name, e.type, e.args)
        except (NotImplementedError, ValueError, OverflowError, ArithmeticError):
            # domain/overflow errors fall through to the runtime kernels,
            # which mask bad rows to NULL (TRY semantics)
            return e
        if isinstance(v, complex):
            return e
        return ir.Constant(e.type, v)
    if isinstance(e, ir.Cast) and isinstance(e.term, ir.Constant):
        c = e.term
        if c.value is None:
            return ir.Constant(e.type, None)
        if c.type.is_decimal and e.type.is_decimal:
            from ..expr.functions import decimal_rescale
            import numpy as np

            v = int(decimal_rescale(np.int64(c.value), c.type.scale, e.type.scale))
            return ir.Constant(e.type, v)
        if T.is_integral(c.type) and e.type.is_decimal:
            return ir.Constant(e.type, c.value * 10**e.type.scale)
        if c.type.is_decimal and e.type.name == "double":
            return ir.Constant(e.type, c.value / 10**c.type.scale)
    return e


def _eval_const(name: str, out_t: T.Type, args) -> object:
    from ..expr.functions import CONST_EVAL

    if name in CONST_EVAL:
        return CONST_EVAL[name](out_t, args)

    def scaled(a):
        return a.value, (a.type.scale if a.type.is_decimal else 0)

    if name in ("add", "subtract", "multiply", "divide", "negate", "modulus"):
        if out_t.is_decimal:
            (av, asc) = scaled(args[0])
            if name == "negate":
                return -av * 10 ** (out_t.scale - asc)
            (bv, bsc) = scaled(args[1])
            if name == "add" or name == "subtract":
                s = out_t.scale
                av *= 10 ** (s - asc)
                bv *= 10 ** (s - bsc)
                return av + bv if name == "add" else av - bv
            if name == "multiply":
                prod = av * bv  # scale asc+bsc
                from_scale, to_scale = asc + bsc, out_t.scale
                if to_scale >= from_scale:
                    return prod * 10 ** (to_scale - from_scale)
                div = 10 ** (from_scale - to_scale)
                sign = -1 if prod < 0 else 1
                return sign * ((abs(prod) + div // 2) // div)
            if name == "divide":
                shift = out_t.scale - asc + bsc
                num = av * 10**shift
                sign = -1 if (num < 0) != (bv < 0) else 1
                q, r = divmod(abs(num), abs(bv))
                return sign * (q + (1 if 2 * r >= abs(bv) else 0))
        if out_t.name in ("bigint", "integer", "date"):
            av = args[0].value
            if name == "negate":
                return -av
            bv = args[1].value
            return {
                "add": av + bv,
                "subtract": av - bv,
                "multiply": av * bv,
                "divide": av // bv if bv else None,
                "modulus": av % bv if bv else None,
            }[name]
        if out_t.name == "double":
            def dv(a):
                return (
                    a.value / 10**a.type.scale if a.type.is_decimal else float(a.value)
                )

            av = dv(args[0])
            if name == "negate":
                return -av
            bv = dv(args[1])
            return {
                "add": av + bv,
                "subtract": av - bv,
                "multiply": av * bv,
                "divide": av / bv if bv else None,
            }[name]
    raise NotImplementedError(name)


@dataclasses.dataclass(frozen=True)
class SqlFunction:
    """A CREATE FUNCTION definition (expression-bodied SQL routine)."""

    name: str
    params: Tuple[Tuple[str, str], ...]  # (name, type text)
    return_type: str
    body: ast.Node


class MrExprAnalyzer(ExprAnalyzer):
    """MATCH_RECOGNIZE expression analysis: pattern-variable-qualified
    references and navigation functions lower to __mr_*__ calls the
    matcher's evaluator resolves (ops/matcher.py)."""

    NAV = {"prev": "__mr_prev__", "next": "__mr_next__",
           "first": "__mr_first__", "last": "__mr_last__"}

    def __init__(self, analyzer, relation, pattern_vars):
        super().__init__(analyzer, relation)
        self.pattern_vars = pattern_vars

    def _var_ref(self, e: ast.Node):
        """(colref, var) for A.col / col inside navigation, else None."""
        if isinstance(e, ast.Identifier) and len(e.parts) == 2:
            v = e.parts[0].lower()
            if v in self.pattern_vars:
                col = super()._an(ast.Identifier((e.parts[1],)))
                return col, v
        return None

    def _an(self, e: ast.Node) -> ir.Expr:
        vr = self._var_ref(e)
        if vr is not None:  # bare A.col == LAST(A.col)
            col, v = vr
            return ir.Call(col.type, "__mr_last__",
                           (col, ir.Constant(T.VARCHAR, v)))
        if isinstance(e, ast.FunctionCall) and e.name in self.NAV:
            nav = self.NAV[e.name]
            if not e.args:
                raise SemanticError(f"{e.name}() requires an argument")
            if nav in ("__mr_prev__", "__mr_next__"):
                # PREV(A.price) navigates PHYSICAL rows (the variable
                # qualifier is irrelevant to PREV/NEXT in the reference too)
                qual = self._var_ref(e.args[0])
                arg = qual[0] if qual is not None else self._an(e.args[0])
                if not isinstance(arg, ir.ColumnRef):
                    raise SemanticError(
                        f"{e.name}() supports column references only"
                    )
                n = 1
                if len(e.args) > 1:
                    c = self._an(e.args[1])
                    if not isinstance(c, ir.Constant):
                        raise SemanticError(f"{e.name}() offset must be constant")
                    n = int(c.value)
                return ir.Call(arg.type, nav,
                               (arg, ir.Constant(T.BIGINT, n)))
            vr = self._var_ref(e.args[0])
            if vr is not None:
                col, v = vr
            else:
                col = self._an(e.args[0])
                v = ""
                if not isinstance(col, ir.ColumnRef):
                    raise SemanticError(
                        f"{e.name}() supports column references only"
                    )
            return ir.Call(col.type, nav, (col, ir.Constant(T.VARCHAR, v)))
        if isinstance(e, ast.FunctionCall) and e.name == "classifier":
            return ir.Call(T.VARCHAR, "__mr_classifier__", ())
        if isinstance(e, ast.FunctionCall) and e.name == "match_number":
            return ir.Call(T.BIGINT, "__mr_match_number__", ())
        return super()._an(e)
