"""Untyped SQL AST.

Reference parity: core/trino-parser/src/main/java/io/trino/sql/tree/
(289 node classes).  This is the SELECT-core subset that covers TPC-H/
TPC-DS-style analytics: query specification, joins, subqueries, CTEs,
set operations, and the expression grammar.  Nodes are plain dataclasses;
the analyzer (analyzer.py) types them into trino_tpu.expr.ir.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple


class Node:
    pass


# --- expressions -------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Identifier(Node):
    parts: Tuple[str, ...]  # possibly qualified: (table, column)

    def __repr__(self):
        return ".".join(self.parts)


@dataclasses.dataclass(frozen=True)
class Literal(Node):
    kind: str  # 'integer' | 'decimal' | 'string' | 'null' | 'boolean' | 'double'
    value: object

    def __repr__(self):
        return f"{self.value!r}"


@dataclasses.dataclass(frozen=True)
class TypedLiteral(Node):
    """DATE 'x', TIMESTAMP 'x', INTERVAL 'n' unit, DECIMAL 'x'."""

    kind: str
    value: str
    unit: Optional[str] = None  # interval unit


@dataclasses.dataclass(frozen=True)
class UnaryOp(Node):
    op: str  # '-' | '+'
    operand: Node


@dataclasses.dataclass(frozen=True)
class BinaryOp(Node):
    op: str  # + - * / % ||
    left: Node
    right: Node


@dataclasses.dataclass(frozen=True)
class ComparisonOp(Node):
    op: str  # = <> < <= > >=
    left: Node
    right: Node


@dataclasses.dataclass(frozen=True)
class LogicalOp(Node):
    op: str  # 'and' | 'or'
    terms: Tuple[Node, ...]


@dataclasses.dataclass(frozen=True)
class NotOp(Node):
    operand: Node


@dataclasses.dataclass(frozen=True)
class IsNullOp(Node):
    operand: Node
    negate: bool


@dataclasses.dataclass(frozen=True)
class BetweenOp(Node):
    value: Node
    low: Node
    high: Node
    negate: bool


@dataclasses.dataclass(frozen=True)
class InList(Node):
    value: Node
    items: Tuple[Node, ...]
    negate: bool


@dataclasses.dataclass(frozen=True)
class InSubquery(Node):
    value: Node
    query: "Query"
    negate: bool


@dataclasses.dataclass(frozen=True)
class Exists(Node):
    query: "Query"
    negate: bool


@dataclasses.dataclass(frozen=True)
class ScalarSubquery(Node):
    query: "Query"


@dataclasses.dataclass(frozen=True)
class LikeOp(Node):
    value: Node
    pattern: Node
    escape: Optional[Node]
    negate: bool


@dataclasses.dataclass(frozen=True)
class FrameBound(Node):
    kind: str  # unbounded_preceding|preceding|current|following|unbounded_following
    value: Optional[Node] = None  # offset expression for k PRECEDING/FOLLOWING


@dataclasses.dataclass(frozen=True)
class WindowFrame(Node):
    unit: str  # rows | range | groups
    start: FrameBound
    end: FrameBound


@dataclasses.dataclass(frozen=True)
class WindowSpec(Node):
    """OVER ( [PARTITION BY ...] [ORDER BY ...] [frame] )"""

    partition_by: Tuple[Node, ...]
    order_by: Tuple["SortItem", ...]
    frame: Optional[WindowFrame] = None


@dataclasses.dataclass(frozen=True)
class FunctionCall(Node):
    name: str
    args: Tuple[Node, ...]
    distinct: bool = False
    is_star: bool = False  # count(*)
    window: Optional[WindowSpec] = None  # OVER clause -> window function


@dataclasses.dataclass(frozen=True)
class CastOp(Node):
    operand: Node
    type_name: str
    safe: bool = False  # try_cast


@dataclasses.dataclass(frozen=True)
class ExtractOp(Node):
    field: str  # year|month|day|quarter
    operand: Node


@dataclasses.dataclass(frozen=True)
class WhenClause(Node):
    condition: Node
    result: Node


@dataclasses.dataclass(frozen=True)
class CaseExpr(Node):
    operand: Optional[Node]  # simple CASE if set
    whens: Tuple[WhenClause, ...]
    default: Optional[Node]


@dataclasses.dataclass(frozen=True)
class ArrayLiteral(Node):
    """ARRAY[e1, e2, ...]"""

    items: Tuple[Node, ...]


@dataclasses.dataclass(frozen=True)
class Lambda(Node):
    """x -> expr | (x, y) -> expr (higher-order function argument)."""

    params: Tuple[str, ...]
    body: Node


@dataclasses.dataclass(frozen=True)
class Resolved(Node):
    """Wrapper carrying an already-analyzed ir.Expr through AST analysis
    (used when inlining SQL function bodies: arguments are analyzed in the
    caller's scope first, then spliced into the body)."""

    expr: object


@dataclasses.dataclass(frozen=True)
class Star(Node):
    qualifier: Optional[str] = None  # t.* qualifier


# --- relations ---------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Table(Node):
    name: Tuple[str, ...]  # (catalog, schema, table) suffix-qualified
    alias: Optional[str] = None
    # TABLESAMPLE (method, percentage); engine treats both methods as
    # BERNOULLI row sampling
    sample: Optional[Tuple[str, float]] = None
    # time travel: FOR VERSION|TIMESTAMP AS OF <expr> -> ("version"|
    # "timestamp", expr); the analyzer resolves it to a pinned snapshot
    version: Optional[Tuple[str, "Node"]] = None


@dataclasses.dataclass(frozen=True)
class TableFunctionRelation(Node):
    """FROM TABLE(fn(arg, ...)) — polymorphic table function invocation
    (spi/function/table + operator/table/TableFunctionOperator)."""

    name: str
    # each arg: ("scalar", expr) | ("table", relation) |
    #           ("descriptor", (col, ...)); optional `name =>` prefixes
    # are resolved positionally
    args: Tuple[Tuple[str, object], ...]
    alias: Optional[str] = None
    columns: Optional[Tuple[str, ...]] = None


@dataclasses.dataclass(frozen=True)
class SubqueryRelation(Node):
    query: "Query"
    alias: Optional[str] = None
    columns: Optional[Tuple[str, ...]] = None  # ") AS t (a, b)" form


@dataclasses.dataclass(frozen=True)
class Join(Node):
    kind: str  # inner | left | right | full | cross
    left: Node
    right: Node
    condition: Optional[Node]  # ON expr (None for cross)
    using: Tuple[str, ...] = ()  # USING (a, b) join columns


@dataclasses.dataclass(frozen=True)
class PatternTerm(Node):
    """One pattern atom: variable or group, with a quantifier."""

    kind: str  # var | group | alt
    var: Optional[str] = None
    items: Tuple["PatternTerm", ...] = ()  # group: sequence; alt: branches
    quantifier: str = ""  # '' | '*' | '+' | '?'
    greedy: bool = True


@dataclasses.dataclass(frozen=True)
class MatchRecognize(Node):
    """t MATCH_RECOGNIZE (PARTITION BY .. ORDER BY .. MEASURES ..
    [ONE ROW PER MATCH] [AFTER MATCH SKIP ..] PATTERN (..) DEFINE ..)
    (SqlBase.g4 patternRecognition; window/matcher NFA in the reference)."""

    relation: Node
    partition_by: Tuple[Node, ...]
    order_by: Tuple["SortItem", ...]
    measures: Tuple[Tuple[Node, str], ...]  # (expr, name)
    pattern: PatternTerm  # top-level sequence
    defines: Tuple[Tuple[str, Node], ...]  # (variable, condition)
    after_match: str = "past_last_row"  # past_last_row | to_next_row
    alias: Optional[str] = None
    rows_per_match: str = "one"  # one | all


@dataclasses.dataclass(frozen=True)
class UnnestRelation(Node):
    """UNNEST(expr, ...) [WITH ORDINALITY] [AS alias (cols)]"""

    exprs: Tuple[Node, ...]
    alias: Optional[str] = None
    columns: Optional[Tuple[str, ...]] = None
    ordinality: bool = False


# --- query structure ---------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SelectItem(Node):
    expr: Node
    alias: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class SortItem(Node):
    expr: Node
    ascending: bool = True
    nulls_first: Optional[bool] = None  # None = dialect default


@dataclasses.dataclass(frozen=True)
class Rollup(Node):
    """GROUP BY ROLLUP (a, b) — prefix grouping sets (SqlBase.g4 groupingElement)."""

    items: Tuple[Node, ...]


@dataclasses.dataclass(frozen=True)
class Cube(Node):
    """GROUP BY CUBE (a, b) — all-subset grouping sets."""

    items: Tuple[Node, ...]


@dataclasses.dataclass(frozen=True)
class GroupingSets(Node):
    """GROUP BY GROUPING SETS ((a, b), (a), ())."""

    sets: Tuple[Tuple[Node, ...], ...]


@dataclasses.dataclass(frozen=True)
class QuerySpec(Node):
    """SELECT ... FROM ... WHERE ... GROUP BY ... HAVING ..."""

    items: Tuple[Node, ...]  # SelectItem | Star
    relation: Optional[Node]
    where: Optional[Node]
    group_by: Tuple[Node, ...]
    having: Optional[Node]
    distinct: bool = False


@dataclasses.dataclass(frozen=True)
class SetOp(Node):
    kind: str  # union | intersect | except
    all: bool
    left: Node  # QuerySpec | SetOp
    right: Node


@dataclasses.dataclass(frozen=True)
class With(Node):
    name: str
    query: "Query"
    columns: Optional[Tuple[str, ...]] = None


@dataclasses.dataclass(frozen=True)
class Query(Node):
    """Full query: [WITH ...] body [ORDER BY ...] [LIMIT n]"""

    body: Node  # QuerySpec | SetOp
    order_by: Tuple[SortItem, ...] = ()
    limit: Optional[int] = None
    withs: Tuple[With, ...] = ()
    offset: int = 0


@dataclasses.dataclass(frozen=True)
class ValuesRelation(Node):
    """VALUES (a, b), (c, d) as a query body / inline relation."""

    rows: Tuple[Tuple[Node, ...], ...]


@dataclasses.dataclass(frozen=True)
class CreateTable(Node):
    """CREATE TABLE [IF NOT EXISTS] t (col type, ...)"""

    table: Tuple[str, ...]
    columns: Tuple[Tuple[str, str], ...]  # (name, type text)
    if_not_exists: bool = False


@dataclasses.dataclass(frozen=True)
class CreateTableAs(Node):
    """CREATE TABLE [IF NOT EXISTS] t AS query"""

    table: Tuple[str, ...]
    query: Node
    if_not_exists: bool = False


@dataclasses.dataclass(frozen=True)
class Insert(Node):
    """INSERT INTO t [(cols)] query"""

    table: Tuple[str, ...]
    columns: Tuple[str, ...]  # () = positional, all table columns
    query: Node


@dataclasses.dataclass(frozen=True)
class Update(Node):
    """UPDATE t SET c = expr, ... [WHERE pred]"""

    table: Tuple[str, ...]
    assignments: Tuple[Tuple[str, Node], ...]
    where: Optional[Node] = None


@dataclasses.dataclass(frozen=True)
class MergeWhen(Node):
    """One WHEN [NOT] MATCHED [AND cond] THEN action clause."""

    matched: bool
    condition: Optional[Node]  # extra AND condition
    action: str  # update | delete | insert
    assignments: Tuple[Tuple[str, Node], ...] = ()  # update
    insert_columns: Tuple[str, ...] = ()  # insert ((), positional)
    insert_values: Tuple[Node, ...] = ()  # insert


@dataclasses.dataclass(frozen=True)
class MergeInto(Node):
    """MERGE INTO target USING source ON cond WHEN ... (MergeWriterNode)."""

    table: Tuple[str, ...]
    target_alias: Optional[str]
    source: Node  # relation
    condition: Node
    whens: Tuple[MergeWhen, ...]


@dataclasses.dataclass(frozen=True)
class Delete(Node):
    """DELETE FROM t [WHERE pred]"""

    table: Tuple[str, ...]
    where: Optional[Node] = None


@dataclasses.dataclass(frozen=True)
class DropTable(Node):
    table: Tuple[str, ...]
    if_exists: bool = False


@dataclasses.dataclass(frozen=True)
class CreateView(Node):
    """CREATE [OR REPLACE] VIEW v AS query (StatementAnalyzer.java:1027
    visitCreateView analog).  `query_sql` keeps the original text for
    SHOW CREATE VIEW / information_schema, as ViewDefinition.java:28
    stores originalSql."""

    name: Tuple[str, ...]
    query: Node
    query_sql: str
    replace: bool = False


@dataclasses.dataclass(frozen=True)
class DropView(Node):
    name: Tuple[str, ...]
    if_exists: bool = False


@dataclasses.dataclass(frozen=True)
class ShowCreateView(Node):
    name: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class Parameter(Node):
    """Positional ? parameter in a prepared statement."""

    index: int  # 0-based


@dataclasses.dataclass(frozen=True)
class Prepare(Node):
    """PREPARE name FROM statement"""

    name: str
    statement: Node


@dataclasses.dataclass(frozen=True)
class ExecutePrepared(Node):
    """EXECUTE name [USING expr, ...]"""

    name: str
    args: Tuple[Node, ...] = ()


@dataclasses.dataclass(frozen=True)
class Deallocate(Node):
    """DEALLOCATE PREPARE name"""

    name: str


@dataclasses.dataclass(frozen=True)
class Describe(Node):
    """DESCRIBE INPUT name | DESCRIBE OUTPUT name"""

    kind: str  # input | output
    name: str


@dataclasses.dataclass(frozen=True)
class CreateFunction(Node):
    """CREATE [OR REPLACE] FUNCTION name (p type, ...) RETURNS type
    RETURN expr  (SQL routine; reference sql/routine/ + LanguageFunctionManager)"""

    name: str
    params: Tuple[Tuple[str, str], ...]  # (name, type text)
    return_type: str
    body: Node
    replace: bool = False


@dataclasses.dataclass(frozen=True)
class DropFunction(Node):
    name: str
    if_exists: bool = False


@dataclasses.dataclass(frozen=True)
class ShowFunctions(Node):
    pass


@dataclasses.dataclass(frozen=True)
class ShowCatalogs(Node):
    pass


@dataclasses.dataclass(frozen=True)
class ShowSchemas(Node):
    catalog: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class Use(Node):
    """USE catalog | USE catalog.schema"""

    name: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class TransactionControl(Node):
    """START TRANSACTION | COMMIT | ROLLBACK (autocommit engine: START
    and COMMIT are accepted no-ops, ROLLBACK errors — reference
    transaction/TransactionManager runs one transaction per query)."""

    kind: str  # start | commit | rollback


@dataclasses.dataclass(frozen=True)
class ShowStats(Node):
    """SHOW STATS FOR table"""

    table: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class Analyze(Node):
    """ANALYZE table [(col, ...)] — collect table/column statistics."""

    table: Tuple[str, ...]
    columns: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class ShowCreateTable(Node):
    table: Tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class Explain(Node):
    query: Query
    analyze: bool = False
    plan_type: str = "logical"  # logical | distributed


@dataclasses.dataclass(frozen=True)
class ShowTables(Node):
    catalog: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class ShowColumns(Node):
    table: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class SetSession(Node):
    name: str = ""
    value: str = ""


@dataclasses.dataclass(frozen=True)
class ShowSession(Node):
    pass


def transform(node, fn):
    """Bottom-up structural rewrite over the AST (nodes + tuples); `fn`
    maps each rebuilt node to its replacement.  Used for prepared-statement
    parameter binding (the reference's ParameterRewriter)."""
    if isinstance(node, Node):
        kwargs = {}
        changed = False
        for f in dataclasses.fields(node):
            v = getattr(node, f.name)
            nv = transform(v, fn)
            if nv is not v:
                changed = True
            kwargs[f.name] = nv
        node2 = dataclasses.replace(node, **kwargs) if changed else node
        return fn(node2)
    if isinstance(node, tuple):
        out = tuple(transform(x, fn) for x in node)
        if len(out) == len(node) and all(a is b for a, b in zip(out, node)):
            return node
        return out
    return node


def substitute_parameters(node: Node, args) -> Node:
    """Bind ? parameters positionally with the given expression nodes."""

    def fn(n):
        if isinstance(n, Parameter):
            if n.index >= len(args):
                raise ValueError(
                    f"statement has parameter ?{n.index + 1} but only "
                    f"{len(args)} values were supplied"
                )
            return args[n.index]
        return n

    return transform(node, fn)


def count_parameters(node: Node) -> int:
    count = 0

    def fn(n):
        nonlocal count
        if isinstance(n, Parameter):
            count = max(count, n.index + 1)
        return n

    transform(node, fn)
    return count
