"""SQL lexer + Pratt parser.

Reference parity: core/trino-grammar/src/main/antlr4/.../SqlBase.g4 (1419
lines) + SqlParser.java:51.  The reference uses ANTLR; this is a hand-rolled
recursive-descent/Pratt parser over the SELECT-core grammar (ast.py), which
covers the TPC-H/TPC-DS query shapes: joins, subqueries, CTEs, set ops,
CASE/CAST/EXTRACT/BETWEEN/IN/LIKE/EXISTS, date/interval literals.

Operator precedence (low to high), matching SqlBase.g4's expression rules:
  OR < AND < NOT < comparison|BETWEEN|IN|LIKE|IS < + - || < * / % < unary.
"""
from __future__ import annotations

import dataclasses
import re
from typing import List, Optional, Tuple

from . import ast

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+|--[^\n]*\n?|/\*.*?\*/)
  | (?P<number>\d+(\.\d*)?([eE][+-]?\d+)?|\.\d+)
  | (?P<string>'(?:[^']|'')*')
  | (?P<qident>"(?:[^"]|"")*")
  | (?P<ident>[A-Za-z_][A-Za-z0-9_$]*)
  | (?P<op><>|!=|>=|<=|\|\||=>|->|[-+*/%(),.;=<>\[\]?|])
""",
    re.VERBOSE | re.DOTALL,
)

KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "create", "table", "insert", "into", "delete", "drop", "update",
    "as", "and", "or", "not", "in", "exists", "between", "like", "escape",
    "is", "null", "true", "false", "case", "when", "then", "else", "end",
    "cast", "try_cast", "extract", "join", "inner", "left", "right", "full",
    "outer", "cross", "on", "using", "union", "intersect", "except", "all",
    "distinct", "with", "asc", "desc", "nulls", "first", "last", "date",
    "timestamp", "interval", "year", "month", "day", "hour", "minute",
    "second", "quarter", "explain", "analyze", "show", "tables", "columns",
    "substring", "for", "fetch", "offset", "rows", "row", "only", "values",
    "set", "session", "over", "partition", "range", "groups", "unbounded",
    "preceding", "following", "current",
}


class Token:
    __slots__ = ("kind", "text", "pos")

    def __init__(self, kind: str, text: str, pos: int):
        self.kind = kind  # number|string|ident|qident|op|kw|eof
        self.text = text
        self.pos = pos

    def __repr__(self):
        return f"{self.kind}:{self.text}"


def tokenize(sql: str) -> List[Token]:
    out: List[Token] = []
    i = 0
    while i < len(sql):
        m = _TOKEN_RE.match(sql, i)
        if not m:
            raise ParseError(f"unexpected character {sql[i]!r} at {i}")
        i = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        text = m.group()
        if kind == "ident" and text.lower() in KEYWORDS:
            out.append(Token("kw", text.lower(), m.start()))
        elif kind == "qident":
            out.append(Token("ident", text[1:-1].replace('""', '"'), m.start()))
        else:
            out.append(Token(kind, text, m.start()))
    out.append(Token("eof", "", len(sql)))
    return out


class ParseError(ValueError):
    pass


class Parser:
    def __init__(self, sql: str):
        self.sql = sql
        self.toks = tokenize(sql)
        self.i = 0
        self._param_count = 0  # positional ? parameters seen so far

    # --- token helpers -------------------------------------------------
    def peek(self, k: int = 0) -> Token:
        return self.toks[min(self.i + k, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "kw" and t.text in kws

    def accept_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str):
        if not self.accept_kw(kw):
            raise ParseError(f"expected {kw.upper()} at {self.peek()!r}")

    def accept_soft(self, word: str) -> bool:
        """Accept a soft keyword (lexes as ident; e.g. IF in DDL)."""
        t = self.peek()
        if t.kind == "ident" and t.text.lower() == word:
            self.next()
            return True
        return False

    def accept_op(self, op: str) -> bool:
        t = self.peek()
        if t.kind == "op" and t.text == op:
            self.next()
            return True
        return False

    def expect_op(self, op: str):
        if not self.accept_op(op):
            raise ParseError(f"expected {op!r} at {self.peek()!r} in {self.sql[max(0,self.peek().pos-30):self.peek().pos+10]!r}")

    def ident(self) -> str:
        t = self.peek()
        if t.kind == "ident":
            return self.next().text
        # soft keywords usable as identifiers
        if t.kind == "kw" and t.text in (
            "year", "month", "day", "date", "first", "last", "left", "right",
            "tables", "columns", "values", "row", "rows",
        ):
            return self.next().text
        raise ParseError(f"expected identifier at {t!r}")

    # --- entry ---------------------------------------------------------
    def parse_statement(self) -> ast.Node:
        if self.accept_kw("explain"):
            analyze = self.accept_kw("analyze")
            plan_type = "logical"
            if self.accept_op("("):
                while True:
                    if self.accept_soft("type"):
                        t = self.next()
                        if t.text.lower() not in ("logical", "distributed"):
                            raise ParseError(
                                "EXPLAIN (TYPE LOGICAL|DISTRIBUTED)"
                            )
                        plan_type = t.text.lower()
                    else:
                        raise ParseError(f"unknown EXPLAIN option {self.peek()!r}")
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
            q = self.parse_query()
            self._finish()
            return ast.Explain(q, analyze, plan_type)
        if self.accept_kw("analyze"):
            name = self.qualified_name()
            columns = []
            if self.accept_op("("):
                while True:
                    columns.append(self.ident())
                    if not self.accept_op(","):
                        break
                self.expect_op(")")
            self._finish()
            return ast.Analyze(name, tuple(columns))
        if self.accept_kw("show"):
            if self.accept_kw("tables"):
                self._finish()
                return ast.ShowTables()
            if self.accept_soft("functions"):
                self._finish()
                return ast.ShowFunctions()
            if self.accept_soft("catalogs"):
                self._finish()
                return ast.ShowCatalogs()
            if self.accept_soft("schemas"):
                cat = None
                if self.accept_kw("from") or self.accept_kw("in"):
                    cat = self.ident()
                self._finish()
                return ast.ShowSchemas(cat)
            if self.accept_soft("stats"):
                self.expect_kw("for")
                name = self.qualified_name()
                self._finish()
                return ast.ShowStats(name)
            if self.accept_kw("create"):
                if self.accept_soft("view"):
                    name = self.qualified_name()
                    self._finish()
                    return ast.ShowCreateView(name)
                self.expect_kw("table")
                name = self.qualified_name()
                self._finish()
                return ast.ShowCreateTable(name)
            if self.accept_kw("columns"):
                self.expect_kw("from")
                name = self.qualified_name()
                self._finish()
                return ast.ShowColumns(name)
            if self.accept_kw("session"):
                self._finish()
                return ast.ShowSession()
            raise ParseError("SHOW TABLES | SHOW COLUMNS FROM t | SHOW SESSION")
        if self.accept_kw("set"):
            self.expect_kw("session")
            name = self.ident()
            while self.accept_op("."):  # catalog.property form
                name += "." + self.ident()
            self.expect_op("=")
            t = self.next()
            if t.kind == "string":
                value = t.text[1:-1].replace("''", "'")
            elif t.kind in ("number", "ident", "kw"):
                value = t.text
            else:
                raise ParseError(f"bad SET SESSION value {t!r}")
            self._finish()
            return ast.SetSession(name, value)
        if self.accept_soft("use"):
            name = self.qualified_name()
            self._finish()
            return ast.Use(name)
        if self.accept_soft("start"):
            if not self.accept_soft("transaction"):
                raise ParseError("expected TRANSACTION after START")
            self._finish()
            return ast.TransactionControl("start")
        if self.accept_soft("commit"):
            self.accept_soft("work")
            self._finish()
            return ast.TransactionControl("commit")
        if self.accept_soft("rollback"):
            self.accept_soft("work")
            self._finish()
            return ast.TransactionControl("rollback")
        if self.accept_soft("prepare"):
            name = self.ident()
            self.expect_kw("from")
            stmt = self.parse_statement()
            return ast.Prepare(name, stmt)
        if self.accept_soft("execute"):
            name = self.ident()
            args: List[ast.Node] = []
            if self.accept_kw("using"):
                args.append(self.expr())
                while self.accept_op(","):
                    args.append(self.expr())
            self._finish()
            return ast.ExecutePrepared(name, tuple(args))
        if self.accept_soft("deallocate"):
            self.accept_soft("prepare")
            name = self.ident()
            self._finish()
            return ast.Deallocate(name)
        if self.accept_soft("describe"):
            if self.accept_soft("input"):
                name = self.ident()
                self._finish()
                return ast.Describe("input", name)
            if self.accept_soft("output"):
                name = self.ident()
                self._finish()
                return ast.Describe("output", name)
            name = self.qualified_name()
            self._finish()
            return ast.ShowColumns(name)
        if self.accept_kw("create"):
            replace = False
            if self.accept_kw("or"):
                if not self.accept_soft("replace"):
                    raise ParseError("expected REPLACE after CREATE OR")
                replace = True
            if self.accept_soft("function"):
                return self._create_function(replace)
            if self.accept_soft("view"):
                name = self.qualified_name()
                self.expect_kw("as")
                qpos = self.peek().pos
                q = self.parse_query()
                qtext = self.sql[qpos:].strip().rstrip(";").strip()
                self._finish()
                return ast.CreateView(name, q, qtext, replace)
            self.expect_kw("table")
            ine = False
            if self.accept_soft("if"):
                self.expect_kw("not")
                self.expect_kw("exists")
                ine = True
            name = self.qualified_name()
            if self.accept_kw("as"):
                q = self.parse_query()
                self._finish()
                return ast.CreateTableAs(name, q, ine)
            self.expect_op("(")
            cols = [self.column_def()]
            while self.accept_op(","):
                cols.append(self.column_def())
            self.expect_op(")")
            self._finish()
            return ast.CreateTable(name, tuple(cols), ine)
        if self.accept_kw("insert"):
            self.expect_kw("into")
            name = self.qualified_name()
            cols: List[str] = []
            # '(' starts either a column list or a parenthesized query
            if (self.peek().kind == "op" and self.peek().text == "("
                    and self.peek(1).kind == "ident"):
                self.expect_op("(")
                cols.append(self.ident())
                while self.accept_op(","):
                    cols.append(self.ident())
                self.expect_op(")")
            q = self.parse_query()
            self._finish()
            return ast.Insert(name, tuple(cols), q)
        if self.accept_kw("delete"):
            self.expect_kw("from")
            name = self.qualified_name()
            where = self.expr() if self.accept_kw("where") else None
            self._finish()
            return ast.Delete(name, where)
        if self.accept_soft("merge"):
            self.expect_kw("into")
            name = self.qualified_name()
            talias = None
            if self.accept_kw("as"):
                talias = self.ident()
            elif self.peek().kind == "ident" and not self.at_kw("using"):
                talias = self.next().text
            self.expect_kw("using")
            source = self.relation_primary()
            self.expect_kw("on")
            cond = self.expr()
            whens = []
            while self.accept_kw("when"):
                negate = self.accept_kw("not")
                if not self.accept_soft("matched"):
                    raise ParseError("expected MATCHED in MERGE WHEN clause")
                extra = self.expr() if self.accept_kw("and") else None
                self.expect_kw("then")
                if self.accept_kw("update"):
                    self.expect_kw("set")
                    assigns = []
                    while True:
                        col = self.ident()
                        self.expect_op("=")
                        assigns.append((col, self.expr()))
                        if not self.accept_op(","):
                            break
                    whens.append(ast.MergeWhen(
                        not negate, extra, "update", tuple(assigns)
                    ))
                elif self.accept_kw("delete"):
                    whens.append(ast.MergeWhen(not negate, extra, "delete"))
                elif self.accept_kw("insert"):
                    cols = []
                    if self.accept_op("("):
                        cols.append(self.ident())
                        while self.accept_op(","):
                            cols.append(self.ident())
                        self.expect_op(")")
                    self.expect_kw("values")
                    self.expect_op("(")
                    vals = [self.expr()]
                    while self.accept_op(","):
                        vals.append(self.expr())
                    self.expect_op(")")
                    whens.append(ast.MergeWhen(
                        not negate, extra, "insert", (),
                        tuple(cols), tuple(vals),
                    ))
                else:
                    raise ParseError(
                        "MERGE THEN expects UPDATE SET / DELETE / INSERT"
                    )
            if not whens:
                raise ParseError("MERGE requires at least one WHEN clause")
            self._finish()
            return ast.MergeInto(name, talias, source, cond, tuple(whens))
        if self.accept_kw("update"):
            name = self.qualified_name()
            self.expect_kw("set")
            assigns = []
            while True:
                col = self.ident()
                self.expect_op("=")
                assigns.append((col, self.expr()))
                if not self.accept_op(","):
                    break
            where = self.expr() if self.accept_kw("where") else None
            self._finish()
            return ast.Update(name, tuple(assigns), where)
        if self.accept_kw("drop"):
            if self.accept_soft("function"):
                ie = False
                if self.accept_soft("if"):
                    self.expect_kw("exists")
                    ie = True
                name = self.ident()
                self._finish()
                return ast.DropFunction(name, ie)
            if self.accept_soft("view"):
                ie = False
                if self.accept_soft("if"):
                    self.expect_kw("exists")
                    ie = True
                name = self.qualified_name()
                self._finish()
                return ast.DropView(name, ie)
            self.expect_kw("table")
            ie = False
            if self.accept_soft("if"):
                self.expect_kw("exists")
                ie = True
            name = self.qualified_name()
            self._finish()
            return ast.DropTable(name, ie)
        q = self.parse_query()
        self._finish()
        return q

    def column_def(self) -> Tuple[str, str]:
        """column definition: name + SQL type text (types.parse_type forms)."""
        name = self.ident()
        return name, self.type_text()

    def type_text(self) -> str:
        t = self.next()
        if t.kind not in ("ident", "kw"):
            raise ParseError(f"expected a type name at {t!r}")
        type_text = t.text
        if self.accept_op("("):
            depth = 1
            type_text += "("
            while depth:
                tok = self.next()
                if tok.kind == "eof":
                    raise ParseError("unterminated type")
                if tok.kind == "op" and tok.text == "(":
                    depth += 1
                if tok.kind == "op" and tok.text == ")":
                    depth -= 1
                    if not depth:
                        break
                type_text += tok.text
            type_text += ")"
        return type_text

    def _create_function(self, replace: bool) -> ast.Node:
        """CREATE FUNCTION name (p type, ...) RETURNS type
        [DETERMINISTIC] RETURN expr  (SqlBase.g4 functionSpecification,
        expression-bodied SQL routines)."""
        name = self.ident()
        self.expect_op("(")
        params: List[Tuple[str, str]] = []
        if not self.accept_op(")"):
            params.append(self.column_def())
            while self.accept_op(","):
                params.append(self.column_def())
            self.expect_op(")")
        if not self.accept_soft("returns"):
            raise ParseError("expected RETURNS in CREATE FUNCTION")
        rtype = self.type_text()
        self.accept_soft("deterministic")
        if not self.accept_soft("return"):
            raise ParseError(
                "expected RETURN <expression> (only expression-bodied "
                "functions are supported)"
            )
        body = self.expr()
        self._finish()
        return ast.CreateFunction(name, tuple(params), rtype, body, replace)

    def _finish(self):
        self.accept_op(";")
        if self.peek().kind != "eof":
            raise ParseError(f"trailing input at {self.peek()!r}")

    # --- query ---------------------------------------------------------
    def parse_query(self) -> ast.Query:
        withs: List[ast.With] = []
        if self.accept_kw("with"):
            while True:
                name = self.ident()
                cols = None
                if self.accept_op("("):
                    cols = [self.ident()]
                    while self.accept_op(","):
                        cols.append(self.ident())
                    self.expect_op(")")
                self.expect_kw("as")
                self.expect_op("(")
                q = self.parse_query()
                self.expect_op(")")
                withs.append(ast.With(name, q, tuple(cols) if cols else None))
                if not self.accept_op(","):
                    break
        body = self.parse_set_expr()
        order: List[ast.SortItem] = []
        limit = None
        if self.accept_kw("order"):
            self.expect_kw("by")
            order.append(self.sort_item())
            while self.accept_op(","):
                order.append(self.sort_item())
        offset = 0
        if self.accept_kw("offset"):
            offset = self._int_token(self.next(), "OFFSET")
            self.accept_kw("rows") or self.accept_kw("row")
        if self.accept_kw("limit"):
            t = self.next()
            if t.kind == "kw" and t.text == "all":
                limit = None
            else:
                limit = self._int_token(t, "LIMIT")
        elif self.accept_kw("fetch"):
            (self.accept_kw("first") or self.accept_kw("next")
             or self.accept_soft("next"))
            limit = self._int_token(self.next(), "FETCH")
            self.accept_kw("rows") or self.accept_kw("row")
            self.expect_kw("only")
        return ast.Query(body, tuple(order), limit, tuple(withs), offset)

    def sort_item(self) -> ast.SortItem:
        e = self.expr()
        asc = True
        if self.accept_kw("asc"):
            asc = True
        elif self.accept_kw("desc"):
            asc = False
        nf = None
        if self.accept_kw("nulls"):
            if self.accept_kw("first"):
                nf = True
            else:
                self.expect_kw("last")
                nf = False
        return ast.SortItem(e, asc, nf)

    def parse_set_expr(self) -> ast.Node:
        # INTERSECT binds tighter than UNION/EXCEPT (SqlBase.g4 precedence)
        left = self.parse_intersect_expr()
        while self.at_kw("union", "except"):
            kind = self.next().text
            all_ = self.accept_kw("all")
            self.accept_kw("distinct")
            right = self.parse_intersect_expr()
            left = ast.SetOp(kind, all_, left, right)
        return left

    def parse_intersect_expr(self) -> ast.Node:
        left = self.parse_query_primary()
        while self.at_kw("intersect"):
            self.next()
            all_ = self.accept_kw("all")
            self.accept_kw("distinct")
            right = self.parse_query_primary()
            left = ast.SetOp("intersect", all_, left, right)
        return left

    def parse_query_primary(self) -> ast.Node:
        if self.accept_op("("):
            # a parenthesized branch may carry its own ORDER BY / LIMIT
            q = self.parse_query()
            self.expect_op(")")
            if not q.withs and not q.order_by and q.limit is None:
                return q.body
            return q
        if self.at_kw("values"):
            self.next()
            rows = [self._values_row()]
            while self.accept_op(","):
                rows.append(self._values_row())
            return ast.ValuesRelation(tuple(rows))
        return self.parse_query_spec()

    def _values_row(self) -> tuple:
        self.expect_op("(")
        row = [self.expr()]
        while self.accept_op(","):
            row.append(self.expr())
        self.expect_op(")")
        return tuple(row)

    def _int_token(self, t: Token, clause: str) -> int:
        if t.kind != "number" or not t.text.isdigit():
            raise ParseError(f"{clause} expects an integer, got {t!r}")
        return int(t.text)

    def parse_query_spec(self) -> ast.QuerySpec:
        self.expect_kw("select")
        distinct = False
        if self.accept_kw("distinct"):
            distinct = True
        else:
            self.accept_kw("all")
        items: List[ast.Node] = [self.select_item()]
        while self.accept_op(","):
            items.append(self.select_item())
        relation = None
        where = None
        group: List[ast.Node] = []
        having = None
        if self.accept_kw("from"):
            relation = self.parse_relation()
        if self.accept_kw("where"):
            where = self.expr()
        if self.accept_kw("group"):
            self.expect_kw("by")
            group.append(self.group_element())
            while self.accept_op(","):
                group.append(self.group_element())
        if self.accept_kw("having"):
            having = self.expr()
        return ast.QuerySpec(
            tuple(items), relation, where, tuple(group), having, distinct
        )

    def group_element(self) -> ast.Node:
        """groupingElement (SqlBase.g4): ROLLUP (...) | CUBE (...) |
        GROUPING SETS ((...), ...) | expression.  The construct words are
        soft keywords — only recognized in this position."""
        t = self.peek()
        if (t.kind == "ident" and t.text.lower() in ("rollup", "cube")
                and self.peek(1).kind == "op" and self.peek(1).text == "("):
            word = self.next().text.lower()
            self.expect_op("(")
            items = [self.expr()]
            while self.accept_op(","):
                items.append(self.expr())
            self.expect_op(")")
            node = ast.Rollup if word == "rollup" else ast.Cube
            return node(tuple(items))
        if (t.kind == "ident" and t.text.lower() == "grouping"
                and self.peek(1).kind == "ident"
                and self.peek(1).text.lower() == "sets"):
            self.next()
            self.next()
            self.expect_op("(")
            sets = [self._grouping_set()]
            while self.accept_op(","):
                sets.append(self._grouping_set())
            self.expect_op(")")
            return ast.GroupingSets(tuple(sets))
        return self.expr()

    def _grouping_set(self) -> tuple:
        if self.accept_op("("):
            if self.accept_op(")"):
                return ()  # the grand-total set
            items = [self.expr()]
            while self.accept_op(","):
                items.append(self.expr())
            self.expect_op(")")
            return tuple(items)
        return (self.expr(),)

    def select_item(self) -> ast.Node:
        if self.accept_op("*"):
            return ast.Star()
        # t.* form
        if (
            self.peek().kind == "ident"
            and self.peek(1).kind == "op"
            and self.peek(1).text == "."
            and self.peek(2).kind == "op"
            and self.peek(2).text == "*"
        ):
            q = self.next().text
            self.next()
            self.next()
            return ast.Star(q)
        e = self.expr()
        alias = None
        if self.accept_kw("as"):
            alias = self.ident()
        elif self.peek().kind == "ident":
            alias = self.next().text
        return ast.SelectItem(e, alias)

    # --- relations -----------------------------------------------------
    def parse_relation(self) -> ast.Node:
        rel = self.join_chain()
        while self.accept_op(","):  # implicit cross join
            right = self.join_chain()
            rel = ast.Join("cross", rel, right, None)
        return rel

    def join_chain(self) -> ast.Node:
        rel = self.relation_primary()
        while True:
            if self.accept_kw("cross"):
                self.expect_kw("join")
                right = self.relation_primary()
                rel = ast.Join("cross", rel, right, None)
                continue
            kind = None
            if self.at_kw("join"):
                kind = "inner"
            elif self.at_kw("inner") and self.peek(1).text == "join":
                self.next()
                kind = "inner"
            elif self.at_kw("left", "right", "full"):
                k = self.peek().text
                nxt1 = self.peek(1)
                nxt2 = self.peek(2)
                if (nxt1.kind == "kw" and nxt1.text == "join") or (
                    nxt1.kind == "kw" and nxt1.text == "outer"
                    and nxt2.kind == "kw" and nxt2.text == "join"
                ):
                    self.next()
                    self.accept_kw("outer")
                    kind = k
            if kind is None:
                return rel
            self.expect_kw("join")
            right = self.relation_primary()
            if self.accept_kw("on"):
                rel = ast.Join(kind, rel, right, self.expr())
            elif self.accept_kw("using"):
                self.expect_op("(")
                cols = [self.ident()]
                while self.accept_op(","):
                    cols.append(self.ident())
                self.expect_op(")")
                rel = ast.Join(kind, rel, right, None, tuple(cols))
            else:
                raise ParseError("JOIN requires ON or USING")

    def _match_recognize(self, rel: ast.Node) -> ast.Node:
        """MATCH_RECOGNIZE clause after a relation (row pattern recognition)."""
        self.expect_op("(")
        partition: List[ast.Node] = []
        order: List[ast.SortItem] = []
        measures: List[tuple] = []
        after = "past_last_row"
        if self.accept_kw("partition"):
            self.expect_kw("by")
            partition.append(self.expr())
            while self.accept_op(","):
                partition.append(self.expr())
        if self.accept_kw("order"):
            self.expect_kw("by")
            order.append(self.sort_item())
            while self.accept_op(","):
                order.append(self.sort_item())
        if self.accept_soft("measures"):
            while True:
                e = self.expr()
                self.expect_kw("as")
                measures.append((e, self.ident()))
                if not self.accept_op(","):
                    break
        rows_per = "one"
        if self.accept_soft("one"):
            self.expect_kw("row")
            if not self.accept_soft("per"):
                raise ParseError("expected PER MATCH")
            if not self.accept_soft("match"):
                raise ParseError("expected MATCH")
        elif self.accept_kw("all"):
            self.expect_kw("rows")
            if not self.accept_soft("per"):
                raise ParseError("expected PER MATCH")
            if not self.accept_soft("match"):
                raise ParseError("expected MATCH")
            rows_per = "all"
        if self.accept_soft("after"):
            if not self.accept_soft("match"):
                raise ParseError("expected MATCH after AFTER")
            if not self.accept_soft("skip"):
                raise ParseError("expected SKIP")
            if self.accept_soft("past"):
                self.expect_kw("last")
                self.expect_kw("row")
                after = "past_last_row"
            elif self.accept_soft("to"):
                if not self.accept_soft("next"):
                    raise ParseError("expected NEXT ROW")
                self.expect_kw("row")
                after = "to_next_row"
            else:
                raise ParseError("AFTER MATCH SKIP PAST LAST ROW|TO NEXT ROW")
        if not self.accept_soft("pattern"):
            raise ParseError("MATCH_RECOGNIZE requires PATTERN (...)")
        self.expect_op("(")
        pattern = self._pattern_alt()
        self.expect_op(")")
        defines: List[tuple] = []
        if self.accept_soft("define"):
            while True:
                var = self.ident().lower()
                self.expect_kw("as")
                defines.append((var, self.expr()))
                if not self.accept_op(","):
                    break
        self.expect_op(")")
        alias = None
        if self.accept_kw("as"):
            alias = self.ident()
        elif self.peek().kind == "ident":
            alias = self.next().text
        return ast.MatchRecognize(
            rel, tuple(partition), tuple(order), tuple(measures),
            pattern, tuple(defines), after, alias, rows_per,
        )

    def _pattern_alt(self) -> ast.PatternTerm:
        branches = [self._pattern_seq()]
        while self.accept_op("|"):
            branches.append(self._pattern_seq())
        if len(branches) == 1:
            return branches[0]
        return ast.PatternTerm("alt", items=tuple(branches))

    def _pattern_seq(self) -> ast.PatternTerm:
        items: List[ast.PatternTerm] = []
        while True:
            t = self.peek()
            if t.kind == "op" and t.text == "(":
                self.next()
                inner = self._pattern_alt()
                self.expect_op(")")
                atom = ast.PatternTerm("group", items=(inner,))
            elif (
                t.kind in ("ident", "kw")
                and t.text.lower() == "permute"
                and self.peek(1).kind == "op"
                and self.peek(1).text == "("
            ):
                # PERMUTE(A, B, ...) = alternation of every ordering, in
                # lexicographic preference order (SqlBase.g4 patternPermute
                # -> the reference expands identically)
                self.next()
                self.next()
                vars_ = [self._pattern_alt()]
                while self.accept_op(","):
                    vars_.append(self._pattern_alt())
                self.expect_op(")")
                if len(vars_) > 6:
                    raise ParseError(
                        "PERMUTE supports at most 6 elements "
                        f"({len(vars_)} given: {len(vars_)}! orderings)"
                    )
                import itertools

                branches = tuple(
                    ast.PatternTerm("group", items=tuple(perm))
                    for perm in itertools.permutations(vars_)
                )
                atom = ast.PatternTerm(
                    "group",
                    items=(ast.PatternTerm("alt", items=branches),),
                )
            elif t.kind in ("ident", "kw") and t.text not in (")", "|"):
                if t.kind == "kw" and t.text in ("define",):
                    break
                atom = ast.PatternTerm("var", var=self.next().text.lower())
            else:
                break
            q = ""
            greedy = True
            nt = self.peek()
            if nt.kind == "op" and nt.text in ("*", "+", "?"):
                q = self.next().text
                if self.peek().kind == "op" and self.peek().text == "?":
                    self.next()
                    greedy = False
            atom = dataclasses.replace(atom, quantifier=q, greedy=greedy)
            items.append(atom)
        if len(items) == 1:
            return items[0]
        return ast.PatternTerm("group", items=tuple(items))

    def _table_function(self) -> ast.Node:
        """TABLE(fn(arg [, ...])) with scalar, TABLE(rel) and
        DESCRIPTOR(col, ...) arguments; `name =>` prefixes accepted."""
        self.next()  # TABLE
        self.expect_op("(")
        fn = self.ident().lower()
        self.expect_op("(")
        args = []
        if not (self.peek().kind == "op" and self.peek().text == ")"):
            while True:
                # optional named-argument prefix
                if (self.peek().kind == "ident"
                        and self.peek(1).kind == "op"
                        and self.peek(1).text == "=>"):
                    self.next()
                    self.next()
                t = self.peek()
                low = t.text.lower() if t.kind in ("ident", "kw") else ""
                if low == "table" and self.peek(1).text == "(":
                    self.next()
                    self.expect_op("(")
                    rel = self.parse_relation()
                    self.expect_op(")")
                    args.append(("table", rel))
                elif low == "descriptor" and self.peek(1).text == "(":
                    self.next()
                    self.expect_op("(")
                    cols = [self.ident()]
                    while self.accept_op(","):
                        cols.append(self.ident())
                    self.expect_op(")")
                    args.append(("descriptor", tuple(cols)))
                else:
                    args.append(("scalar", self.expr()))
                if not self.accept_op(","):
                    break
        self.expect_op(")")
        self.expect_op(")")
        alias = None
        cols = None
        if self.accept_kw("as"):
            alias = self.ident()
        elif self.peek().kind == "ident":
            alias = self.next().text
        if alias is not None and self.accept_op("("):
            cols = [self.ident()]
            while self.accept_op(","):
                cols.append(self.ident())
            self.expect_op(")")
        return ast.TableFunctionRelation(
            fn, tuple(args), alias, tuple(cols) if cols else None
        )

    def _sample_clause(self):
        t2 = self.next()
        if t2.kind != "ident" or t2.text.lower() not in (
            "bernoulli", "system",
        ):
            raise ParseError("TABLESAMPLE BERNOULLI|SYSTEM (p)")
        method = t2.text.lower()
        self.expect_op("(")
        pct = self.next()
        if pct.kind != "number":
            raise ParseError("TABLESAMPLE percentage must be a number")
        self.expect_op(")")
        return (method, float(pct.text))

    def relation_primary(self) -> ast.Node:
        t = self.peek()
        if (t.kind in ("ident", "kw") and t.text.lower() == "table"
                and self.peek(1).kind == "op" and self.peek(1).text == "("):
            return self._table_function()
        if (t.kind == "ident" and t.text.lower() == "unnest"
                and self.peek(1).kind == "op" and self.peek(1).text == "("):
            self.next()
            self.next()
            exprs = [self.expr()]
            while self.accept_op(","):
                exprs.append(self.expr())
            self.expect_op(")")
            ordinality = False
            if self.accept_kw("with"):
                if not self.accept_soft("ordinality"):
                    raise ParseError("expected ORDINALITY after WITH")
                ordinality = True
            alias = None
            cols = None
            if self.accept_kw("as"):
                alias = self.ident()
            elif self.peek().kind == "ident":
                alias = self.next().text
            if alias is not None and self.accept_op("("):
                cols = [self.ident()]
                while self.accept_op(","):
                    cols.append(self.ident())
                self.expect_op(")")
            return ast.UnnestRelation(
                tuple(exprs), alias, tuple(cols) if cols else None,
                ordinality,
            )
        if self.accept_op("("):
            # subquery or parenthesized join
            if self.at_kw("select", "with", "values"):
                q = self.parse_query()
                self.expect_op(")")
                alias = None
                cols = None
                if self.accept_kw("as"):
                    alias = self.ident()
                elif self.peek().kind == "ident":
                    alias = self.next().text
                if alias is not None and self.accept_op("("):
                    cols = [self.ident()]
                    while self.accept_op(","):
                        cols.append(self.ident())
                    self.expect_op(")")
                return ast.SubqueryRelation(q, alias, tuple(cols) if cols else None)
            rel = self.parse_relation()
            self.expect_op(")")
            return rel
        name = self.qualified_name()
        version = None
        if self.accept_kw("for"):
            # time travel: FOR VERSION AS OF n / FOR TIMESTAMP AS OF t
            if self.accept_soft("version"):
                kind = "version"
            elif self.accept_kw("timestamp"):
                kind = "timestamp"
            else:
                raise ParseError(
                    f"expected VERSION or TIMESTAMP after FOR "
                    f"at {self.peek()!r}"
                )
            self.expect_kw("as")
            if not self.accept_soft("of"):
                raise ParseError(
                    f"expected OF after {kind.upper()} AS "
                    f"at {self.peek()!r}"
                )
            version = (kind, self.expr())
        sample = None
        if self.accept_soft("tablesample"):
            sample = self._sample_clause()
        if (self.peek().kind == "ident"
                and self.peek().text.lower() == "match_recognize"):
            self.next()
            return self._match_recognize(
                ast.Table(name, None, sample, version)
            )
        alias = None
        if self.accept_kw("as"):
            alias = self.ident()
        elif (self.peek().kind == "ident"
              and self.peek().text.lower() != "tablesample"):
            alias = self.next().text
        if sample is None and self.accept_soft("tablesample"):
            # grammar-conformant order: alias before TABLESAMPLE
            sample = self._sample_clause()
        return ast.Table(name, alias, sample, version)

    def qualified_name(self) -> Tuple[str, ...]:
        parts = [self.ident()]
        while (
            self.peek().kind == "op"
            and self.peek().text == "."
            and self.peek(1).kind in ("ident", "kw")
        ):
            self.next()
            parts.append(self.ident())
        return tuple(parts)

    # --- expressions (Pratt) -------------------------------------------
    def expr(self) -> ast.Node:
        return self.or_expr()

    def or_expr(self) -> ast.Node:
        terms = [self.and_expr()]
        while self.accept_kw("or"):
            terms.append(self.and_expr())
        return terms[0] if len(terms) == 1 else ast.LogicalOp("or", tuple(terms))

    def and_expr(self) -> ast.Node:
        terms = [self.not_expr()]
        while self.accept_kw("and"):
            terms.append(self.not_expr())
        return terms[0] if len(terms) == 1 else ast.LogicalOp("and", tuple(terms))

    def not_expr(self) -> ast.Node:
        if self.accept_kw("not"):
            return ast.NotOp(self.not_expr())
        return self.predicate()

    def predicate(self) -> ast.Node:
        left = self.additive()
        while True:
            t = self.peek()
            if t.kind == "op" and t.text in ("=", "<>", "!=", "<", "<=", ">", ">="):
                self.next()
                right = self.additive()
                left = ast.ComparisonOp(
                    "<>" if t.text == "!=" else t.text, left, right
                )
                continue
            negate = False
            save = self.i
            if self.accept_kw("not"):
                negate = True
            if self.accept_kw("between"):
                lo = self.additive()
                self.expect_kw("and")
                hi = self.additive()
                left = ast.BetweenOp(left, lo, hi, negate)
                continue
            if self.accept_kw("in"):
                self.expect_op("(")
                if self.at_kw("select", "with"):
                    q = self.parse_query()
                    self.expect_op(")")
                    left = ast.InSubquery(left, q, negate)
                else:
                    items = [self.expr()]
                    while self.accept_op(","):
                        items.append(self.expr())
                    self.expect_op(")")
                    left = ast.InList(left, tuple(items), negate)
                continue
            if self.accept_kw("like"):
                pat = self.additive()
                esc = None
                if self.accept_kw("escape"):
                    esc = self.additive()
                left = ast.LikeOp(left, pat, esc, negate)
                continue
            if negate:
                self.i = save
                break
            if self.accept_kw("is"):
                neg = self.accept_kw("not")
                if self.accept_kw("null"):
                    left = ast.IsNullOp(left, neg)
                elif self.accept_kw("distinct"):
                    self.expect_kw("from")
                    right = self.additive()
                    cmp = ast.ComparisonOp("is_distinct", left, right)
                    left = ast.NotOp(cmp) if neg else cmp
                else:
                    raise ParseError(f"IS what? at {self.peek()!r}")
                continue
            break
        return left

    def additive(self) -> ast.Node:
        left = self.multiplicative()
        while True:
            t = self.peek()
            if t.kind == "op" and t.text in ("+", "-", "||"):
                self.next()
                left = ast.BinaryOp(t.text, left, self.multiplicative())
            else:
                return left

    def multiplicative(self) -> ast.Node:
        left = self.unary()
        while True:
            t = self.peek()
            if t.kind == "op" and t.text in ("*", "/", "%"):
                self.next()
                left = ast.BinaryOp(t.text, left, self.unary())
            else:
                return left

    def unary(self) -> ast.Node:
        if self.accept_op("-"):
            return ast.UnaryOp("-", self.unary())
        if self.accept_op("+"):
            return self.unary()
        return self.postfix()

    def postfix(self) -> ast.Node:
        e = self.primary()
        while self.accept_op("["):
            # subscript: a[i] == element_at(a, i) (SqlBase.g4 subscript)
            idx = self.expr()
            self.expect_op("]")
            e = ast.FunctionCall("element_at", (e, idx))
        return e

    def primary(self) -> ast.Node:
        t = self.peek()
        # lambda: x -> body | (x, y) -> body
        if (t.kind == "ident" and self.peek(1).kind == "op"
                and self.peek(1).text == "->"):
            name = self.next().text
            self.next()  # ->
            return ast.Lambda((name,), self.expr())
        if (t.kind == "op" and t.text == "("
                and self.peek(1).kind == "ident"
                and self.peek(2).kind == "op"
                and self.peek(2).text in (",", ")")):
            # possible multi-param lambda: scan for ') ->'
            save = self.i
            self.next()
            params = [self.peek().text]
            if self.peek().kind == "ident":
                self.next()
                while self.accept_op(","):
                    if self.peek().kind != "ident":
                        params = None
                        break
                    params.append(self.next().text)
                if (params is not None and self.accept_op(")")
                        and self.accept_op("->")):
                    return ast.Lambda(tuple(params), self.expr())
            self.i = save
        if t.kind == "ident" and t.text.lower() in (
            "current_date", "current_timestamp", "localtimestamp",
        ) and not (self.peek(1).kind == "op" and self.peek(1).text == "("):
            self.next()
            return ast.FunctionCall(t.text.lower(), ())
        if (t.kind == "ident" and t.text.lower() == "array"
                and self.peek(1).kind == "op" and self.peek(1).text == "["):
            self.next()
            self.next()
            items: List[ast.Node] = []
            if not self.accept_op("]"):
                items.append(self.expr())
                while self.accept_op(","):
                    items.append(self.expr())
                self.expect_op("]")
            return ast.ArrayLiteral(tuple(items))
        if t.kind == "op" and t.text == "?":
            self.next()
            p = ast.Parameter(self._param_count)
            self._param_count += 1
            return p
        if t.kind == "number":
            self.next()
            if "." in t.text or "e" in t.text.lower():
                if "e" in t.text.lower():
                    return ast.Literal("double", float(t.text))
                return ast.Literal("decimal", t.text)
            return ast.Literal("integer", int(t.text))
        if t.kind == "string":
            self.next()
            return ast.Literal("string", t.text[1:-1].replace("''", "'"))
        if t.kind == "op" and t.text == "(":
            self.next()
            if self.at_kw("select", "with"):
                q = self.parse_query()
                self.expect_op(")")
                return ast.ScalarSubquery(q)
            e = self.expr()
            self.expect_op(")")
            return e
        if t.kind == "kw":
            if self.accept_kw("null"):
                return ast.Literal("null", None)
            if self.accept_kw("true"):
                return ast.Literal("boolean", True)
            if self.accept_kw("false"):
                return ast.Literal("boolean", False)
            if self.accept_kw("exists"):
                self.expect_op("(")
                q = self.parse_query()
                self.expect_op(")")
                return ast.Exists(q, False)
            if self.accept_kw("cast") or (
                t.text == "try_cast" and self.accept_kw("try_cast")
            ):
                safe = t.text == "try_cast"
                self.expect_op("(")
                e = self.expr()
                self.expect_kw("as")
                tn = self.type_name()
                self.expect_op(")")
                return ast.CastOp(e, tn, safe)
            if self.accept_kw("extract"):
                self.expect_op("(")
                field = self.next().text.lower()
                self.expect_kw("from")
                e = self.expr()
                self.expect_op(")")
                return ast.ExtractOp(field, e)
            if self.accept_kw("case"):
                operand = None
                if not self.at_kw("when"):
                    operand = self.expr()
                whens = []
                while self.accept_kw("when"):
                    c = self.expr()
                    self.expect_kw("then")
                    r = self.expr()
                    whens.append(ast.WhenClause(c, r))
                default = None
                if self.accept_kw("else"):
                    default = self.expr()
                self.expect_kw("end")
                return ast.CaseExpr(operand, tuple(whens), default)
            if self.at_kw("date", "timestamp") and self.peek(1).kind == "string":
                kind = self.next().text
                v = self.next().text
                return ast.TypedLiteral(kind, v[1:-1])
            if (
                self.at_kw("date", "timestamp")
                and self.peek(1).kind == "op"
                and self.peek(1).text == "("
            ):
                # date('1994-01-01') function form -> typed literal / cast
                kind = self.next().text
                self.next()
                e = self.expr()
                self.expect_op(")")
                if isinstance(e, ast.Literal) and e.kind == "string":
                    return ast.TypedLiteral(kind, e.value)
                return ast.CastOp(e, kind)
            if self.accept_kw("interval"):
                sign = -1 if self.accept_op("-") else 1
                v = self.next()
                if v.kind not in ("string", "number"):
                    raise ParseError(f"INTERVAL expects a value, got {v!r}")
                txt = v.text[1:-1] if v.kind == "string" else v.text
                u = self.peek()
                units = ("year", "month", "day", "hour", "minute", "second")
                if not (u.kind == "kw" and u.text.rstrip("s") in units) and not (
                    u.kind == "ident" and u.text.lower().rstrip("s") in units
                ):
                    raise ParseError(f"INTERVAL expects a unit, got {u!r}")
                unit = self.next().text.lower()
                if sign < 0:
                    txt = "-" + txt
                return ast.TypedLiteral("interval", txt, unit)
            if self.accept_kw("substring"):
                self.expect_op("(")
                e = self.expr()
                if self.accept_kw("from"):
                    start = self.expr()
                    length = None
                    if self.accept_kw("for"):
                        length = self.expr()
                else:
                    self.expect_op(",")
                    start = self.expr()
                    length = None
                    if self.accept_op(","):
                        length = self.expr()
                self.expect_op(")")
                args = (e, start) + ((length,) if length is not None else ())
                return ast.FunctionCall("substring", args)
        # identifier or function call (soft keywords allowed via ident())
        try:
            name = self.ident()
        except ParseError:
            raise ParseError(f"unexpected token {t!r}")
        if self.peek().kind == "op" and self.peek().text == "(":
            self.next()
            distinct = False
            is_star = False
            args: List[ast.Node] = []
            if self.accept_op("*"):
                is_star = True
            elif not (self.peek().kind == "op" and self.peek().text == ")"):
                distinct = self.accept_kw("distinct")
                self.accept_kw("all")
                args.append(self.expr())
                while self.accept_op(","):
                    args.append(self.expr())
            self.expect_op(")")
            window = None
            if self.accept_kw("over"):
                window = self.window_spec()
            return ast.FunctionCall(
                name.lower(), tuple(args), distinct, is_star, window
            )
        parts = [name]
        while (
            self.peek().kind == "op"
            and self.peek().text == "."
            and self.peek(1).kind in ("ident", "kw")
        ):
            self.next()
            parts.append(self.ident())
        return ast.Identifier(tuple(parts))

    def window_spec(self) -> ast.WindowSpec:
        """OVER ( [PARTITION BY e,..] [ORDER BY s,..] [frame] )
        (SqlBase.g4 windowSpecification)."""
        self.expect_op("(")
        partition: List[ast.Node] = []
        if self.accept_kw("partition"):
            self.expect_kw("by")
            partition.append(self.expr())
            while self.accept_op(","):
                partition.append(self.expr())
        order: List[ast.SortItem] = []
        if self.accept_kw("order"):
            self.expect_kw("by")
            order.append(self.sort_item())
            while self.accept_op(","):
                order.append(self.sort_item())
        frame = None
        if self.at_kw("rows", "range", "groups"):
            unit = self.next().text
            if self.accept_kw("between"):
                start = self.frame_bound()
                self.expect_kw("and")
                end = self.frame_bound()
            else:
                start = self.frame_bound()
                end = ast.FrameBound("current")
            frame = ast.WindowFrame(unit, start, end)
        self.expect_op(")")
        return ast.WindowSpec(tuple(partition), tuple(order), frame)

    def frame_bound(self) -> ast.FrameBound:
        if self.accept_kw("unbounded"):
            if self.accept_kw("preceding"):
                return ast.FrameBound("unbounded_preceding")
            self.expect_kw("following")
            return ast.FrameBound("unbounded_following")
        if self.accept_kw("current"):
            self.expect_kw("row")
            return ast.FrameBound("current")
        v = self.expr()
        if self.accept_kw("preceding"):
            return ast.FrameBound("preceding", v)
        self.expect_kw("following")
        return ast.FrameBound("following", v)

    def type_name(self) -> str:
        base = self.next().text.lower()
        if self.accept_op("("):
            inner = [self.next().text]
            while self.accept_op(","):
                inner.append(self.next().text)
            self.expect_op(")")
            return f"{base}({','.join(inner)})"
        return base


def parse(sql: str) -> ast.Node:
    """Parse one SQL statement (SqlParser.createStatement analog)."""
    return Parser(sql).parse_statement()
