"""Coordinator HTTP server: statement protocol + introspection endpoints.

Reference parity:
  - POST /v1/statement + GET /v1/statement/executing/{id}/{slug}/{token}
    (dispatcher/QueuedStatementResource.java:158,
     server/protocol/ExecutingStatementResource.java:154)
  - DELETE cancel (:283), /v1/info, /v1/status, /v1/query list
    (ServerInfoResource, StatusResource, QueryResource)
  - query lifecycle states mirror QueryState.java:21
    (QUEUED -> PLANNING -> RUNNING -> FINISHED/FAILED)
  - dispatch/queue/track roles of DispatchManager.java:67 + QueryTracker

Implementation: stdlib ThreadingHTTPServer; queries execute on a worker
thread pool; results paged to the client in fixed-size chunks via nextUri
tokens (the long-poll pull loop of StatementClientV1.advance()).
"""
from __future__ import annotations

import json
import secrets
import threading
import time
import traceback
import urllib.request
import uuid
from concurrent.futures import ThreadPoolExecutor
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from ..memory import ClusterMemoryManager, MemoryAdmissionController, create_killer
from ..page import Page
from ..session import Session
from ..sql import ast
from ..sql.parser import parse
from ..utils.memory import ExceededMemoryLimitError
from ..utils.metrics import REGISTRY
from ..utils.tracing import TRACER
from . import protocol
from . import recovery as _recovery
from .discovery import HeartbeatFailureDetector, NodeManager
from .resource_groups import QueryQueueFullError, ResourceGroupManager

PAGE_ROWS = 4096
# retry-policy=query backoff (QueryRetryPolicy / RetryingQueryRunner role):
# first retry waits BASE, doubling per attempt — long enough for the
# failure detector (~0.5-0.75s EMA decay) to drop a dead worker from the
# alive set before placement is re-chosen
QUERY_RETRY_BASE_S = 1.0
QUERY_RETRY_ATTEMPTS = 2


class QueryExecution:
    """One tracked query (QueryStateMachine + QueryTracker entry)."""

    def __init__(self, query_id: str, sql: str, user: str = "user"):
        self.query_id = query_id
        self.slug = secrets.token_hex(8)
        self.sql = sql
        self.user = user
        self.group = None  # resource group holding our slot
        self.state = "QUEUED"
        self.error: Optional[str] = None
        self.retry_count = 0  # whole-query re-runs under retry_policy=query
        self.adaptive_actions: list = []  # FTE mid-query replan records
        self.task_stats: list = []  # per-task stats docs (TaskInfo rollup)
        self.timeline: Optional[dict] = None  # merged operator timeline
        self.diagnosis: Optional[dict] = None  # doctor's finalize verdict
        self.straggler_flags: list = []  # dispersion-detector verdicts
        self.session_executed = False  # ran via session.execute (history
        #                                already recorded there)
        self.tenant = ""  # top-level resource group (serving observatory)
        self.plan_signature = ""  # canonical plan digest (census key)
        self.kernel_families: tuple = ()  # per-fragment family digests
        self.result_cache_hit: Optional[bool] = None  # None = not keyed
        self.result_cache_stored = False
        self.recovered = False  # re-registered from the WAL after restart
        self.resume_event_id: Optional[int] = None  # QUERY_RESUMED citation
        self.orphan_event_id: Optional[int] = None  # QUERY_ORPHANED citation
        self.page: Optional[Page] = None
        self.types = None
        self.created = time.time()
        self.finished: Optional[float] = None
        self.lock = threading.Lock()

    def uri(self, token: int) -> str:
        return f"/v1/statement/executing/{self.query_id}/{self.slug}/{token}"


class Coordinator:
    def __init__(
        self,
        session: Session,
        workers: int = 4,
        distributed: bool = False,
        resource_groups: Optional[dict] = None,
        authenticator=None,
        fault_injection: Optional[dict] = None,
    ):
        self.session = session
        # admission control (InternalResourceGroupManager)
        self.resource_groups = ResourceGroupManager(resource_groups)
        # optional PasswordAuthenticator (security.py); None = open access
        self.authenticator = authenticator
        self.queries: Dict[str, QueryExecution] = {}
        # dispatch pool: sized above the typical resource-group
        # hard_concurrency_limit so the groups, not this executor, are
        # the concurrency authority (idle threads are cheap; a 4-thread
        # pool under a 10-slot group would silently serialize dispatch)
        self.pool = ThreadPoolExecutor(max_workers=max(workers, 16))
        self.node_id = f"coordinator-{uuid.uuid4().hex[:8]}"
        self.started = time.time()
        self.distributed = distributed
        self.node_manager = (
            NodeManager(
                gone_grace=float(
                    session.properties.get("node_gone_grace_s") or 10.0
                )
            )
            if distributed
            else None
        )
        self.failure_detector = (
            HeartbeatFailureDetector(self.node_manager).start()
            if distributed
            else None
        )
        # cluster memory view + OOM arbitration (ClusterMemoryManager
        # analog), fed by announcement-piggybacked pool snapshots and
        # the coordinator-local session manager
        self.cluster_memory = ClusterMemoryManager(
            killer=create_killer(
                session.properties.get("low_memory_killer_policy")
            )
        )
        session.cluster_memory = self.cluster_memory
        # system.runtime.nodes reads announced node + device health
        # through the session (coordinator_only system scans)
        session.node_manager = self.node_manager
        # multi-host cluster shape: host-sized units announce their
        # slice of the global mesh here (distributed/topology.py)
        from ..distributed import ClusterTopology

        self.cluster_topology = ClusterTopology()
        # memory admission gate (resource-group softMemoryLimit role):
        # queries wait in QUEUED until their estimated peak fits; tenant
        # shares cap how much of the budget one tenant's admitted
        # reservations may hold
        self.admission = MemoryAdmissionController(
            self._memory_capacity,
            tenant_share_fn=self.resource_groups.tenant_memory_share,
        )
        # system.runtime.resource_groups reads live group state through
        # the session (coordinator_only system scans)
        session.resource_group_manager = self.resource_groups
        # a session-wide default queue deadline applies to root groups
        # that don't configure their own (0 = queue forever)
        default_deadline = float(
            session.properties.get("resource_group_queue_deadline_s")
            or 0.0
        )
        if default_deadline > 0:
            for g in self.resource_groups.groups.values():
                if g.parent is None and g.queue_deadline_s <= 0:
                    g.queue_deadline_s = default_deadline
        # elasticity control loop (enable_autoscaler wires the harness's
        # scale-out hook); ticked from the enforcement loop
        self.autoscaler = None
        # live straggler detector fed by announcement-piggybacked task
        # rollups (obs/opstats); one summary per task id, ever
        from ..obs.opstats import StragglerDetector

        self.straggler_detector = StragglerDetector(
            factor=float(
                session.properties.get("straggler_dispersion_factor")
                or 2.0
            ),
            min_s=float(
                session.properties.get("fte_speculation_min_s") or 0.75
            ),
        )
        self._opstats_seen: set = set()
        self._opstats_by_stage: Dict[tuple, list] = {}
        self._opstats_lock = threading.Lock()
        if self.node_manager is not None:
            # node-death fan-out: memory-pool eviction + opstats ghost
            # retirement the moment a node is declared GONE
            self.node_manager.add_gone_listener(self._on_node_gone)
        # -- coordinator crash recovery (server/recovery.py) ------------
        # WAL + restart-time resume; the chaos injector arming the
        # seeded coordinator_death site is only ever passed by
        # coordinator_main (a subprocess coordinator) — an in-process
        # coordinator firing os._exit would take the test runner down,
        # exactly the worker_death containment rule
        self.recovered_queries = 0
        self.orphaned_queries = 0
        self.wal = None
        self.recovery = None
        recovery_dir = str(
            session.properties.get("coordinator_recovery_dir") or ""
        )
        if recovery_dir and distributed:
            from ..utils.faults import FaultInjector

            injector = (
                FaultInjector.from_spec(fault_injection)
                if fault_injection
                else None
            )
            self.wal = _recovery.CoordinatorWAL(
                recovery_dir, injector=injector
            )
            self.recovery = _recovery.RecoveryManager(
                self, recovery_dir,
                window_s=float(
                    session.properties.get("coordinator_recovery_window_s")
                    or 10.0
                ),
            )
            # synchronous: every non-terminal WAL query is back in the
            # tracker (same id, same slug) before the HTTP server ever
            # answers a poll; the resume/orphan pass runs in background
            # once discovery re-announcements rebuild the worker set
            self.recovery.register()
            threading.Thread(
                target=self.recovery.run, daemon=True
            ).start()
        # -- serving observatory (obs/serving_observatory.py) -----------
        # per-signature census + cache-affinity map + per-tenant SLO
        # burn monitor; persisted census when serving_observatory_dir is
        # set, backfilled from whatever query history survived restarts
        self.serving = self._configure_serving_observatory(
            resource_groups
        )
        self._stop_enforcement = threading.Event()
        if distributed:
            threading.Thread(
                target=self._enforcement_loop, daemon=True
            ).start()
        elif any(
            g.queue_deadline_s > 0
            for g in self.resource_groups.groups.values()
        ):
            # coordinator-only clusters with queue deadlines still need
            # a ticker: an idle queue must shed on time, not on the next
            # submit
            threading.Thread(target=self._shed_loop, daemon=True).start()

    def _configure_serving_observatory(self, resource_groups):
        """Boot the process-global serving observatory from session
        properties, declare per-tenant SLO objectives from the raw
        resource-group spec (``sloLatencyTargetS``/``sloErrorBudget``
        on top-level groups), and backfill the signature census from
        whatever persisted query history survived earlier processes."""
        from ..obs import serving_observatory as _so
        from ..obs.history import get_store

        props = self.session.properties

        def _num(key, default):
            try:
                return float(props.get(key) or default)
            except (TypeError, ValueError):
                return default

        obs = _so.configure(
            props.get("serving_observatory_dir") or None,
            max_bytes=props.get("serving_observatory_max_bytes"),
            max_signatures=int(
                props.get("signature_census_max")
                or _so.DEFAULT_MAX_SIGNATURES
            ),
            slo={
                "latency_target_s": _num(
                    "slo_latency_target_s", _so.DEFAULT_LATENCY_TARGET_S
                ),
                "error_budget": _num(
                    "slo_error_budget", _so.DEFAULT_ERROR_BUDGET
                ),
                "fast_window_s": _num(
                    "slo_fast_window_s", _so.DEFAULT_FAST_WINDOW_S
                ),
                "slow_window_s": _num(
                    "slo_slow_window_s", _so.DEFAULT_SLOW_WINDOW_S
                ),
                "burn_threshold": _num(
                    "slo_burn_threshold", _so.DEFAULT_BURN_THRESHOLD
                ),
            },
        )
        # tenants are top-level groups OR the direct children of one
        # (InternalResourceGroup.tenant), so SLO spec keys are honored
        # on both levels of the tree
        def _declare(spec):
            if not isinstance(spec, dict) or not spec.get("name"):
                return
            target = spec.get("sloLatencyTargetS")
            budget = spec.get("sloErrorBudget")
            if target is not None or budget is not None:
                obs.slo.set_objective(
                    str(spec["name"]),
                    latency_target_s=target,
                    error_budget=budget,
                )

        for spec in (resource_groups or {}).get("groups") or []:
            _declare(spec)
            for sub in (
                spec.get("subGroups") or ()
                if isinstance(spec, dict)
                else ()
            ):
                _declare(sub)
        try:
            store = get_store(
                props.get("query_history_dir") or None,
                max_bytes=int(
                    props.get("query_history_max_bytes") or (1 << 20)
                ),
            )
            obs.backfill_from_history(store.entries())
        except Exception:  # noqa: BLE001 — backfill is best-effort
            pass
        # system-table scans run through the session; the affinity map
        # needs to know which node id "this process" is
        self.session.serving_node_id = self.node_id
        return obs

    def _stash_plan_telemetry(self, q: QueryExecution, plan) -> None:
        """Stamp the query with its canonical plan signature and the
        per-fragment kernel-family digests (the same
        ``stable_key_digest(("family", fragment_fingerprint))`` the
        executors' compile ledger records, so the affinity map can join
        census rows against worker compile announcements)."""
        try:
            from ..cache.compile_cache import stable_key_digest
            from ..cache.signature import (
                fragment_fingerprint,
                plan_signature,
            )
            from ..plan.fragment import fragment_plan

            q.plan_signature = plan_signature(plan).digest
            q.kernel_families = tuple(sorted({
                stable_key_digest(
                    ("family", fragment_fingerprint(f.root))
                )[:12]
                for f in fragment_plan(plan)
            }))
        except Exception:  # noqa: BLE001 — telemetry must not fail planning
            pass

    def enable_autoscaler(self, scale_out=None, **overrides):
        """Attach the elasticity control loop.  ``scale_out`` is the
        add-a-worker hook (DistributedQueryRunner.add_subprocess_worker
        in the harness); knobs default from session properties."""
        from .autoscaler import Autoscaler

        props = self.session.properties
        kw = {
            "min_workers": int(
                props.get("autoscale_min_workers") or 1
            ),
            "max_workers": int(
                props.get("autoscale_max_workers") or 4
            ),
            "backlog_high": int(
                props.get("autoscale_backlog_high") or 4
            ),
            "cooldown_s": float(
                props.get("autoscale_cooldown_s") or 2.0
            ),
            "idle_grace_s": float(
                props.get("autoscale_idle_grace_s") or 1.5
            ),
        }
        kw.update(overrides)
        self.autoscaler = Autoscaler(self, scale_out=scale_out, **kw)
        return self.autoscaler

    def _memory_capacity(self) -> int:
        """Admission budget: announced host pools, or the coordinator's
        own manager when no worker has announced yet."""
        total = 0
        for node in self.cluster_memory.nodes_view():
            pools = node.get("pools") or {}
            for name in ("general", "reserved"):
                total += int((pools.get(name) or {}).get("size", 0))
        if total:
            return total
        mm = self.session.memory_manager
        return mm.general.size + mm.reserved.size

    def _enforcement_loop(self):
        while not self._stop_enforcement.wait(0.1):
            try:
                self.check_cluster_memory()
            except Exception:
                pass
            try:
                self.resource_groups.shed_expired()
            except Exception:
                pass
            if self.autoscaler is not None:
                try:
                    self.autoscaler.tick()
                except Exception:
                    pass

    def _shed_loop(self):
        """Deadline-shed ticker for coordinator-only clusters (the
        distributed enforcement loop already covers this)."""
        while not self._stop_enforcement.wait(0.1):
            try:
                self.resource_groups.shed_expired()
            except Exception:
                pass

    def check_cluster_memory(self):
        """One enforcement pass: refresh the cluster view from the
        latest heartbeats, then enforce query_max_total_memory_bytes and
        the low-memory killer.  Returns the query ids killed."""
        cm = self.cluster_memory
        if self.node_manager is not None:
            for n in self.node_manager.all_nodes():
                # a GONE node's last snapshot must not resurrect the
                # eviction done by _on_node_gone
                if n.memory and n.state != "GONE":
                    cm.update_node(n.node_id, n.memory)
        cm.update_node(
            self.node_id, self.session.memory_manager.snapshot()
        )
        running = [
            q.query_id for q in self.queries.values()
            if q.state in ("QUEUED", "PLANNING", "RUNNING")
        ]
        limit = int(
            self.session.properties.get("query_max_total_memory_bytes")
            or 0
        )
        return cm.process(
            self.kill_query, total_limit=limit or None, running=running
        )

    def kill_query(self, query_id: str, reason: str):
        """Fail a query with a structured OOM reason and wake any of its
        blocked reservations on every node (killer verdict fan-out)."""
        q = self.queries.get(query_id)
        if q is None:
            raise KeyError(query_id)
        with q.lock:
            if q.state in ("FINISHED", "FAILED"):
                raise RuntimeError(f"query {query_id} already done")
            q.error = reason
            q.state = "FAILED"
            q.finished = time.time()
        self.session.memory_manager.kill(query_id, reason)
        if self.node_manager is not None:
            for _node_id, uri in self.node_manager.alive():
                try:
                    req = urllib.request.Request(
                        f"{uri}/v1/memory/kill",
                        data=json.dumps({
                            "queryId": query_id, "reason": reason,
                        }).encode(),
                        headers={"Content-Type": "application/json"},
                    )
                    urllib.request.urlopen(req, timeout=2.0).read()
                except Exception:
                    pass

    # -- lifecycle ------------------------------------------------------
    def submit(self, sql: str, user: str = "user",
               source: str = "") -> QueryExecution:
        q = QueryExecution(f"q_{uuid.uuid4().hex[:16]}", sql, user)
        self.queries[q.query_id] = q
        REGISTRY.counter(
            "trino_tpu_query_submitted_total", "Queries accepted for dispatch"
        ).inc()
        group = self.resource_groups.select(user, source)
        q.group = group
        # the tenant survives on the query even after shed/queue-full
        # paths null q.group — finalize charges the SLO monitor by it
        q.tenant = group.tenant
        self.cluster_memory.note_query_tenant(q.query_id, group.tenant)
        if self.wal is not None:
            # intent first: the query durably exists (sql + slug + group
            # + retry policy) before any dispatch — a crash from here on
            # is recoverable, and the recorded slug keeps the client's
            # nextUri valid across the restart
            self.wal.record(
                _recovery.QUERY_SUBMITTED, q.query_id,
                sql=sql, user=user, source=source, slug=q.slug,
                resourceGroup=getattr(group, "name", ""),
                retryPolicy=str(
                    self.session.properties.get("retry_policy") or ""
                ),
            )

        def on_shed(err):
            # queue-deadline shed: structured, retryable, and journaled
            # (the group emitted query_shed before calling us)
            with q.lock:
                if q.state in ("FINISHED", "FAILED"):
                    return
                q.state = "FAILED"
                q.error = f"{getattr(err, 'error_code', 'ADMISSION_TIMEOUT')}: {err}"
                q.finished = time.time()
                q.group = None
            REGISTRY.counter(
                "trino_tpu_query_failed_total",
                "Queries that reached FAILED",
            ).inc()
            try:
                self._finalize_query(q)
            except Exception:
                pass

        try:
            group.submit(
                lambda: self.pool.submit(self._run, q),
                query_id=q.query_id,
                on_shed=on_shed,
            )
        except QueryQueueFullError as e:
            with q.lock:
                q.state = "FAILED"
                q.error = f"QUERY_QUEUE_FULL: {e}"
                q.finished = time.time()
                q.group = None
            try:
                self._finalize_query(q)
            except Exception:
                pass
        return q

    def _estimated_peak_bytes(self, sql: str) -> int:
        """Static estimate of a query's peak reservation for admission
        (estimate_program_bytes over the optimized plan); 0 for utility
        statements and anything that fails to plan — those carry no scan
        working set and must not wait behind the gate."""
        try:
            stmt = parse(sql)
            if not isinstance(stmt, ast.Query):
                return 0
            plan = self.session._plan_stmt(stmt)
            from ..exec.streaming import estimate_program_bytes

            ex = self.session._executor()
            return int(estimate_program_bytes(ex, plan))
        except Exception:
            return 0

    def _run(self, q: QueryExecution):
        cancelled_group = None
        with q.lock:
            if q.state == "FAILED":  # cancelled while queued
                cancelled_group, q.group = q.group, None
                cancelled = True
            else:
                cancelled = False
        if cancelled:
            if cancelled_group is not None:
                # the dequeue charged a running slot before cancel won
                # the race: release it or the group leaks capacity
                cancelled_group.finish()
            # cancelled-while-queued queries died before the normal
            # finally-block finalize: persist them with an errorCode too,
            # or system.runtime.completed_queries never shows them
            try:
                self._finalize_query(q)
            except Exception:
                pass
            return
        admitted = False
        try:
            est = self._estimated_peak_bytes(q.sql)
            q.estimated_memory_bytes = est
            if est > 0:
                # stays QUEUED while waiting for memory headroom
                self.admission.acquire(
                    q.query_id, est,
                    timeout_s=float(
                        self.session.properties.get(
                            "memory_admission_timeout_s"
                        ) or 60.0
                    ),
                    tenant=q.group.tenant if q.group is not None else "",
                )
                admitted = True
                if q.group is not None:
                    q.group.add_memory_usage(est)
            with q.lock:
                if q.state == "FAILED":  # cancelled/killed while queued
                    return
                q.state = "PLANNING"
            page = self._execute(q)
            with q.lock:
                if q.state == "FAILED":  # killed mid-flight (OOM killer)
                    return
                q.page = page
                q.types = [c.type for c in page.columns]
                q.state = "FINISHED"
                q.finished = time.time()
            REGISTRY.counter(
                "trino_tpu_query_finished_total", "Queries that reached FINISHED"
            ).inc()
        except Exception as e:  # surfaced via the protocol error field
            with q.lock:
                if q.error is None:  # keep a killer's structured reason
                    q.error = f"{type(e).__name__}: {e}"
                q.state = "FAILED"
                q.finished = time.time()
            REGISTRY.counter(
                "trino_tpu_query_failed_total", "Queries that reached FAILED"
            ).inc()
            try:
                from ..obs import doctor, journal

                journal.emit(
                    journal.QUERY_FAILED, query_id=q.query_id,
                    severity=journal.ERROR, error=str(q.error)[:400],
                    errorCode=doctor.classify_error(q.error),
                )
            except Exception:  # noqa: BLE001 — journaling is best-effort
                pass
        finally:
            if admitted:
                self.admission.release(q.query_id)
                if q.group is not None:
                    q.group.add_memory_usage(
                        -getattr(q, "estimated_memory_bytes", 0)
                    )
            # drop any leftover local reservations/kill marks (worker
            # managers clean up in their executors' finally blocks)
            self.session.memory_manager.free_query(q.query_id)
            REGISTRY.histogram(
                "trino_tpu_query_wall_seconds", "End-to-end query wall time"
            ).observe((q.finished or time.time()) - q.created)
            try:
                self._finalize_query(q)
            except Exception:
                pass  # observability must never fail the query
            if q.group is not None:
                # decayed CPU/slot cost: a flooding tenant's charge
                # rises with every second it burns, sinking its
                # weighted-fair arbitration until the decay forgives it
                q.group.charge_cpu(
                    (q.finished or time.time()) - q.created
                )
                q.group.finish()

    def _on_node_gone(self, node_id: str, uri: str) -> None:
        """GONE fan-out: the dead node's pool snapshot leaves the cluster
        memory view (its phantom reservations would otherwise skew
        admission and the low-memory killer forever) and its in-flight
        operator-stats tasks are marked terminal so the timeline and the
        live straggler detector stop waiting on a ghost."""
        from ..obs.opstats import mark_node_tasks_terminal

        try:
            self.cluster_memory.remove_node(node_id)
        except Exception:
            pass
        try:
            # a dead host-sized unit takes its device slice with it:
            # the cluster topology stops counting its mesh share
            self.cluster_topology.forget(node_id)
        except Exception:
            pass
        try:
            with self._opstats_lock:
                retired = mark_node_tasks_terminal(
                    self._opstats_by_stage, node_id
                )
            self.straggler_detector.observe_node_gone(node_id, retired)
        except Exception:
            pass

    def ingest_compiles(self, node_id: str, snapshot) -> None:
        """Announcement piggyback: merge one worker's compile-
        observatory snapshot (per-cause counts, census sketch, new
        ledger events) into the coordinator's engine-wide view."""
        from ..obs import compile_observatory as _co

        try:
            _co.get_observatory().ingest(node_id, snapshot)
        except Exception:  # noqa: BLE001 — telemetry must not fail announce
            pass

    def ingest_opstats(self, node_id: str, summaries) -> None:
        """Heartbeat piggyback: each worker announce carries its recent
        per-task rollups.  New task ids are grouped by stage and replayed
        through the live straggler detector so dispersion flags exist
        while the query still runs (not just at the terminal merge)."""
        from ..obs.opstats import _stage_of

        changed = {}
        with self._opstats_lock:
            for s in summaries or ():
                tid = s.get("taskId")
                if not tid or tid in self._opstats_seen:
                    continue
                self._opstats_seen.add(tid)
                entry = dict(s)
                entry["nodeId"] = node_id
                stage = _stage_of(tid)
                self._opstats_by_stage.setdefault(stage, []).append(entry)
                changed[stage] = list(self._opstats_by_stage[stage])
        for stage, entries in changed.items():
            self.straggler_detector.observe_stage(stage, entries)

    def _finalize_query(self, q: QueryExecution) -> None:
        """Terminal observability: merge per-task operator rollups into
        the query timeline (QueryStats.operatorSummaries analog) and
        persist the completed query into the crash-safe history store."""
        from ..obs import opstats as _opstats
        from ..obs.history import get_store

        if self.wal is not None and q.state in ("FINISHED", "FAILED"):
            # terminal WAL record: replay after a later restart must not
            # try to resume (or orphan) a query that already completed
            self.wal.record(
                _recovery.QUERY_FINISHED
                if q.state == "FINISHED"
                else _recovery.QUERY_FAILED,
                q.query_id,
                state=q.state,
                error=str(q.error)[:400] if q.error else None,
            )
        tasks = getattr(q, "task_stats", None) or []
        if tasks and q.timeline is None:
            # fresh detector per merge so the timeline's straggler list
            # reflects this query alone (the live detector accumulates
            # across queries for metrics/announce flags)
            det = _opstats.StragglerDetector(
                factor=self.straggler_detector.factor,
                min_s=self.straggler_detector.min_s,
            )
            q.timeline = _opstats.timeline_from_tasks(tasks, detector=det)
            q.straggler_flags = list(q.straggler_flags or []) + det.flags
        from ..obs import doctor

        if not q.session_executed:
            store = get_store(
                self.session.properties.get("query_history_dir") or None,
                max_bytes=int(
                    self.session.properties.get("query_history_max_bytes")
                    or (1 << 20)
                ),
            )
            store.put({
                "query_id": q.query_id,
                "state": q.state,
                "sql": q.sql,
                "user": q.user,
                "created": q.created,
                "finished": q.finished,
                "rows": int(q.page.count) if q.page is not None else 0,
                "wall_s": (q.finished or time.time()) - q.created,
                "error": q.error,
                "error_code": doctor.classify_error(q.error),
                "tenant": getattr(q, "tenant", "") or "",
                "plan_signature":
                    getattr(q, "plan_signature", "") or "",
                "operators": (q.timeline or {}).get("operators") or None,
            })
        try:
            self._observe_serving(q)
        except Exception:
            pass  # observability must never fail the query
        # the doctor's finalize pass: failed AND finished queries get a
        # verdict (HEALTHY is itself a signal), served by
        # GET /v1/query/{id}/diagnosis and system.runtime.diagnoses
        if self.session.properties.get("query_doctor"):
            finished = q.finished or time.time()
            diag = doctor.diagnose_query(
                q.query_id,
                window=(q.created, finished),
                timeline=q.timeline,
                error=q.error,
                wall_s=finished - q.created,
            )
            doctor.record_diagnosis(diag)
            q.diagnosis = diag
        self.cluster_memory.forget_query_tenant(q.query_id)

    def _observe_serving(self, q: QueryExecution) -> None:
        """Feed one terminal query into the serving observatory: the
        signature census (latency/cost/drift/cache rollup keyed by the
        canonical plan digest) and the tenant's SLO burn windows.
        Guarded: shed paths and the dispatch finally block may both
        finalize the same query."""
        if getattr(q, "_serving_observed", False):
            return
        q._serving_observed = True
        from ..obs import serving_observatory as _so

        finished = q.finished or time.time()
        device_wall = host_wall = 0.0
        drift = None
        for frame in (q.timeline or {}).get("operators") or []:
            device_wall += float(frame.get("deviceWallS") or 0.0)
            host_wall += float(frame.get("hostWallS") or 0.0)
            est = float(frame.get("estimatedRows", 0.0) or 0.0)
            obs = float(frame.get("outputRows", 0.0) or 0.0)
            if est > 0 and obs > 0:
                ratio = max(est / obs, obs / est)
                drift = max(drift or 0.0, ratio)
        _so.get_observatory().observe_query(
            signature=getattr(q, "plan_signature", "") or "",
            tenant=getattr(q, "tenant", "") or "",
            query_id=q.query_id,
            latency_s=finished - q.created,
            ok=q.state == "FINISHED",
            device_wall_s=device_wall,
            host_wall_s=host_wall,
            drift_ratio=drift,
            cache_hit=getattr(q, "result_cache_hit", None),
            cache_stored=getattr(q, "result_cache_stored", False),
            families=getattr(q, "kernel_families", ()) or (),
            node_id=self.node_id,
            ts=finished,
        )

    def _plan_is_coordinator_only(self, plan) -> bool:
        """True when the plan scans a connector marked coordinator_only
        (the system catalog): those tables read live engine state from
        this process and are never mounted on workers."""
        from ..plan import nodes as P

        found = []

        def check(node, _depth):
            if isinstance(node, P.TableScan):
                try:
                    conn = self.session.catalogs.get(node.catalog)
                except Exception:
                    return
                if getattr(conn, "coordinator_only", False):
                    found.append(node.catalog)

        P.visit_plan(plan, check)
        return bool(found)

    def _execute(self, q: QueryExecution) -> Page:
        """Distributed mode routes plain queries through the fragment
        scheduler over announced workers (SqlQueryExecution.planDistribution
        -> PipelinedQueryScheduler); utility statements and worker-less
        clusters run in-process (coordinator-only execution)."""
        if self.distributed:
            stmt = parse(q.sql)
            if isinstance(stmt, ast.Analyze):
                # ANALYZE's synthesized aggregations ride the distributed
                # fragment scheduler like any query: per-worker partial
                # sketches (HLL/KMV) merge at the final stage exactly as
                # planStatisticsAggregation's partial/final split does
                workers = self.node_manager.alive()
                if workers:
                    from .scheduler import DistributedScheduler

                    props = self.session.properties
                    seq = [0]

                    def dispatch(plan):
                        seq[0] += 1
                        sched = DistributedScheduler(
                            self.session.catalogs, workers,
                            {"group_capacity": props.get("group_capacity")},
                            memory_view=self.cluster_memory,
                        )
                        return sched.run(
                            plan, f"{q.query_id}_analyze{seq[0]}"
                        )

                    with q.lock:
                        q.state = "RUNNING"
                    return self.session.execute_analyze(
                        stmt, execute_plan=dispatch
                    )
            if isinstance(stmt, ast.Query):
                from .scheduler import DistributedScheduler, SchedulerError

                plan = self.session._plan_stmt(stmt)
                self._stash_plan_telemetry(q, plan)
                if self._plan_is_coordinator_only(plan):
                    # system-catalog scans snapshot THIS process's live
                    # state (node manager, query history, metrics
                    # registry); workers don't mount the system catalog
                    # (SystemTable Distribution.SINGLE_COORDINATOR)
                    page = self.session.execute(q.sql, user=q.user)
                    q.kernel_profile = getattr(
                        self.session, "last_kernel_profile", None
                    )
                    q.session_executed = True
                    return page
                workers = self.node_manager.alive()
                if not workers:
                    raise SchedulerError(
                        "NO_NODES_AVAILABLE: no alive workers to schedule on"
                    )
                if self.wal is not None:
                    # the fragment-graph digest is the resume sanity
                    # check: replayed SQL must re-plan to this shape
                    # before any committed spool is trusted
                    self.wal.record(
                        _recovery.QUERY_PLANNED, q.query_id,
                        planDigest=_recovery.plan_digest(plan),
                    )
                # fragment result cache: a warm deterministic plan skips
                # scheduling entirely (the coordinator-side tier — workers
                # never see the query)
                rkey, hit = self.session.cached_result(plan)
                if rkey is not None:
                    q.result_cache_hit = hit is not None
                if hit is not None:
                    return hit
                with q.lock:
                    q.state = "RUNNING"
                props = self.session.properties
                task_props = self._task_properties()
                try:
                    # the query span parents every scheduler dispatch made
                    # on this thread (traceparent rides the task POSTs), so
                    # worker task spans join this trace
                    with TRACER.span("query", query_id=q.query_id):
                        if props.get("retry_policy") == "task":
                            page = self._run_fte(q, plan)
                        elif props.get("retry_policy") == "query":
                            page = self._run_with_query_retries(
                                q, plan, workers, task_props, props
                            )
                        else:
                            sched = DistributedScheduler(
                                self.session.catalogs, workers, task_props,
                                memory_view=self.cluster_memory,
                                node_manager=self.node_manager,
                            )
                            page = sched.run(plan, q.query_id)
                            # per-task stats rollup (TaskStats -> QueryStats)
                            q.task_stats = getattr(
                                sched, "last_task_stats", []
                            )
                finally:
                    TRACER.flush()
                self.session.store_result(rkey, page, plan)
                q.result_cache_stored = rkey is not None
                return page
        page = self.session.execute(q.sql, user=q.user)
        # in-process execution: the session-side executor's kernel profile
        # feeds /v1/query/{id}/profile for coordinator-only clusters
        q.kernel_profile = getattr(self.session, "last_kernel_profile", None)
        q.session_executed = True
        return page

    def _task_properties(self) -> dict:
        """Session properties forwarded to every remote task (the
        SystemSessionProperties subset workers act on)."""
        props = self.session.properties
        return {
            "group_capacity": props.get("group_capacity"),
            "memory_limit_bytes":
                props.get("query_max_memory_bytes"),
            "spill_enabled": props.get("spill_enabled"),
            "dynamic_filtering": props.get("dynamic_filtering"),
            "speculative_execution":
                props.get("speculative_execution"),
            "fte_max_attempts": props.get("fte_max_attempts"),
            "fte_task_timeout_s": props.get("fte_task_timeout_s"),
            "fte_speculation_factor":
                props.get("fte_speculation_factor"),
            "fte_speculation_min_s":
                props.get("fte_speculation_min_s"),
            "fault_injection": props.get("fault_injection"),
            "memory_blocked_timeout_s":
                props.get("memory_blocked_timeout_s"),
            "exchange_retry_attempts":
                props.get("exchange_retry_attempts"),
            "exchange_retry_budget_s":
                props.get("exchange_retry_budget_s"),
            # adaptive replanning: estimate-vs-observed divergence
            # threshold + the broadcast cutoff the flip re-checks
            "statistics_enabled": props.get("statistics_enabled"),
            "adaptive_replan_factor":
                props.get("adaptive_replan_factor"),
            "broadcast_join_threshold_rows":
                props.get("broadcast_join_threshold_rows"),
            # device-fault supervision (runtime/supervisor.py)
            "device_fault_max_strikes":
                props.get("device_fault_max_strikes"),
            "device_probe_backoff_s":
                props.get("device_probe_backoff_s"),
            "device_watchdog_timeout_s":
                props.get("device_watchdog_timeout_s"),
            "device_cpu_fallback":
                props.get("device_cpu_fallback"),
            # per-operator timeline (obs/opstats): workers run
            # eager with node stats and roll frames into TaskInfo
            "operator_stats": props.get("operator_stats"),
            "straggler_dispersion_factor":
                props.get("straggler_dispersion_factor"),
            # multi-host: workers with >1 local device run eligible
            # fragments as per-host slices of the global mesh
            "cross_host_mesh": props.get("cross_host_mesh"),
        }

    def _run_fte(
        self,
        q: QueryExecution,
        plan,
        qid: Optional[str] = None,
        precommitted=None,
        wal_qid: Optional[str] = None,
    ) -> Page:
        """One FTE (retry_policy=task) run with WAL intent hooks bound
        to ``wal_qid`` — always the ORIGINAL query id, so that a resumed
        run (which spools under an epoch-suffixed ``qid``) keeps
        journaling against the same replay key and a second crash
        resumes from the union of both epochs' committed records."""
        from .fte import FaultTolerantScheduler

        wal_key = wal_qid or q.query_id
        on_dispatch = on_commit = None
        if self.wal is not None:
            def on_dispatch(task_id, uri):
                self.wal.record(
                    _recovery.TASK_DISPATCHED, wal_key,
                    taskId=task_id, uri=uri,
                )

            def on_commit(sig, task_index, path):
                self.wal.record(
                    _recovery.TASK_COMMITTED, wal_key,
                    fragmentSig=sig, taskIndex=task_index,
                    spoolPath=path,
                )

        fte = FaultTolerantScheduler(
            self.session.catalogs, self.node_manager,
            properties=self._task_properties(),
            metadata=self.session.metadata,
            precommitted=precommitted,
            on_dispatch=on_dispatch, on_commit=on_commit,
        )
        page = fte.run(plan, qid or q.query_id)
        q.adaptive_actions = fte.adaptive_actions
        q.task_stats = getattr(fte, "task_stats", [])
        q.straggler_flags = list(
            getattr(getattr(fte, "straggler", None), "flags", ())
        )
        return page

    def _run_with_query_retries(
        self, q: QueryExecution, plan, workers, task_props, props
    ) -> Page:
        """retry-policy=QUERY (the pre-Tardigrade fault tolerance level):
        the pipelined scheduler streams between live tasks with no spool,
        so any mid-flight failure poisons the whole run — recovery is a
        bounded whole-query re-dispatch against a REFRESHED alive-worker
        set, with backoff long enough for the failure detector to retire
        the dead node first.  Each attempt runs under a suffixed query id
        so worker task state from the doomed attempt can never collide
        with (or be idempotently returned to) the retry."""
        from ..exec.exchange_client import RemoteTaskError
        from ..serde import PageIntegrityError
        from .scheduler import DistributedScheduler, SchedulerError

        max_retries = int(
            props.get("query_retry_attempts") or QUERY_RETRY_ATTEMPTS
        )
        last_error: Optional[Exception] = None
        for attempt in range(max_retries + 1):
            if attempt:
                q.retry_count = attempt
                REGISTRY.counter(
                    "trino_tpu_query_retry_total",
                    "Whole-query re-dispatches under retry_policy=query",
                ).inc()
                time.sleep(QUERY_RETRY_BASE_S * (2 ** (attempt - 1)))
                # re-resolve placement: the failed worker must be gone
                # from (or back in) the alive set before we re-dispatch
                deadline = time.time() + 10.0
                while time.time() < deadline:
                    workers = self.node_manager.alive()
                    if workers:
                        break
                    time.sleep(0.1)
                if not workers:
                    raise SchedulerError(
                        "NO_NODES_AVAILABLE: no alive workers for "
                        f"query retry {attempt}"
                    )
            qid = (
                q.query_id if attempt == 0 else f"{q.query_id}-r{attempt}"
            )
            try:
                sched = DistributedScheduler(
                    self.session.catalogs, workers, task_props,
                    node_manager=self.node_manager,
                )
                page = sched.run(plan, qid)
                q.task_stats = getattr(sched, "last_task_stats", [])
                return page
            except (
                SchedulerError, RemoteTaskError, PageIntegrityError,
                OSError,  # URLError/ConnectionError: task POST hit a
                          # dead worker before any error translation ran
            ) as e:
                last_error = e
        raise SchedulerError(
            f"query failed after {max_retries} whole-query retries: "
            f"{last_error}"
        )

    def query_profile(self, q: QueryExecution) -> dict:
        """Per-query TPU kernel profile (GET /v1/query/{id}/profile):
        per-kernel compile wall, recompiles, padding ratio, transfer
        bytes — rolled up from worker task stats in distributed mode, or
        taken from the in-process executor otherwise."""
        tasks = []
        kernels = []
        bandwidth = []
        summaries = []
        for t in getattr(q, "task_stats", []) or []:
            prof = t.get("kernelProfile")
            if not prof:
                continue
            tasks.append({
                "taskId": t.get("taskId"),
                "uri": t.get("uri"),
                "profile": prof,
            })
            kernels.extend(prof.get("kernels") or [])
            bandwidth.extend(prof.get("bandwidth") or [])
            if prof.get("summary"):
                summaries.append(prof["summary"])
        local = getattr(q, "kernel_profile", None)
        if not tasks and local:
            kernels = list(local.get("kernels") or [])
            bandwidth = list(local.get("bandwidth") or [])
            if local.get("summary"):
                summaries.append(local["summary"])
        summary = {}
        if summaries:
            actual = sum(s.get("actualRows", 0) for s in summaries)
            padded = sum(s.get("paddedRows", 0) for s in summaries)
            by_cause: Dict[str, int] = {}
            for s in summaries:
                for c, n in (s.get("compilesByCause") or {}).items():
                    by_cause[c] = by_cause.get(c, 0) + int(n)
            summary = {
                "kernels": sum(s.get("kernels", 0) for s in summaries),
                "compiles": sum(s.get("compiles", 0) for s in summaries),
                "recompiles": sum(s.get("recompiles", 0) for s in summaries),
                "compilesByCause": by_cause,
                "cacheHits": sum(s.get("cacheHits", 0) for s in summaries),
                "compileWallS": sum(
                    s.get("compileWallS", 0.0) for s in summaries
                ),
                "actualRows": actual,
                "paddedRows": padded,
                "paddingRatio": (padded / actual) if actual else 1.0,
                "h2dBytes": sum(s.get("h2dBytes", 0) for s in summaries),
                "d2hBytes": sum(s.get("d2hBytes", 0) for s in summaries),
            }
            # HBM bandwidth ledger rollup: cluster-wide effective GB/s
            # over the summed per-task byte/wall accounting
            led_bytes = sum(s.get("ledgerBytes", 0) for s in summaries)
            led_wall = sum(s.get("deviceWallS", 0.0) for s in summaries)
            if led_bytes or led_wall:
                summary["ledgerBytes"] = led_bytes
                summary["deviceWallS"] = led_wall
                summary["effectiveGbps"] = (
                    led_bytes / led_wall / 1e9 if led_wall > 0 else 0.0
                )
        bandwidth.sort(key=lambda e: e.get("totalBytes", 0), reverse=True)
        return {
            "queryId": q.query_id,
            "state": q.state,
            "kernels": kernels,
            "bandwidth": bandwidth,
            "summary": summary,
            "tasks": tasks,
        }

    def in_recovery_window(self) -> bool:
        """True while a restarted coordinator may still be replaying its
        WAL: polls for query ids we don't know yet answer 503 +
        Retry-After (the client waits) instead of 404 (the client would
        fail).  Closed as soon as the recovery pass finishes — after
        that an unknown id is genuinely unknown."""
        r = self.recovery
        if r is None or r.done.is_set():
            return False
        return time.time() < self.started + r.window_s

    def cancel(self, query_id: str):
        q = self.queries.get(query_id)
        if q:
            with q.lock:
                if q.state not in ("FINISHED", "FAILED"):
                    q.state = "FAILED"
                    q.error = "Query was canceled"
                    q.finished = time.time()

    # -- protocol documents ---------------------------------------------
    def results_doc(self, q: QueryExecution, token: int) -> dict:
        with q.lock:
            state = q.state
            if state in ("QUEUED", "PLANNING", "RUNNING"):
                return protocol.query_results(
                    q.query_id, state, next_uri=q.uri(token)
                )
            if state == "FAILED":
                return protocol.query_results(
                    q.query_id, "FAILED", error=q.error
                )
            # FINISHED: page out rows in chunks
            page = q.page
            page_rows = int(
                self.session.properties.get("client_page_rows")
                or PAGE_ROWS
            )
            start = token * page_rows
            end = min(start + page_rows, page.count)
            chunk = Page(
                [c.__class__(c.type, c.values[start:end],
                             None if c.validity is None else c.validity[start:end],
                             c.dictionary)
                 for c in page.columns],
                end - start,
                page.names,
            )
            next_uri = q.uri(token + 1) if end < page.count else None
            return protocol.query_results(
                q.query_id, "FINISHED", chunk, q.types, next_uri,
                stats={
                    "elapsedTimeMillis": int(
                        ((q.finished or time.time()) - q.created) * 1000
                    ),
                    "processedRows": page.count,
                },
            )


class _Handler(BaseHTTPRequestHandler):
    coordinator: Coordinator = None  # set by serve()

    def log_message(self, fmt, *args):  # quiet
        pass

    def _json(self, code: int, doc: dict,
              headers: Optional[dict] = None):
        body = json.dumps(doc).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(body)

    def _authenticate(self) -> Optional[str]:
        """Returns the authenticated/declared user, or None after sending a
        401 (PasswordAuthenticator + X-Trino-User header handling)."""
        user = self.headers.get("X-Trino-User") or "user"
        auth = self.coordinator.authenticator
        if auth is None:
            return user
        import base64

        header = self.headers.get("Authorization", "")
        if header.startswith("Basic ") and hasattr(auth, "authenticate"):
            try:
                decoded = base64.b64decode(header[6:]).decode()
                u, _, pw = decoded.partition(":")
                auth.authenticate(u, pw)
                return u
            except Exception:
                pass
        if header.startswith("Bearer ") and hasattr(
            auth, "authenticate_token"
        ):
            try:
                return auth.authenticate_token(header[7:]).user
            except Exception:
                pass
        scheme = (
            "Bearer" if hasattr(auth, "authenticate_token") else "Basic"
        )
        self.send_response(401)
        self.send_header(
            "WWW-Authenticate", f'{scheme} realm="trino-tpu"'
        )
        self.send_header("Content-Length", "0")
        self.end_headers()
        return None

    def do_POST(self):
        if self.path == "/v1/statement":
            user = self._authenticate()
            if user is None:
                return
            n = int(self.headers.get("Content-Length", 0))
            sql = self.rfile.read(n).decode()
            source = self.headers.get("X-Trino-Source", "")
            q = self.coordinator.submit(sql, user, source)
            self._json(200, self.coordinator.results_doc(q, 0))
        elif self.path == "/v1/announcement":
            n = int(self.headers.get("Content-Length", 0))
            doc = json.loads(self.rfile.read(n))
            if self.coordinator.node_manager is not None:
                self.coordinator.node_manager.announce(
                    doc["nodeId"], doc["uri"], memory=doc.get("memory"),
                    device=doc.get("device"), state=doc.get("state"),
                    topology=doc.get("topology"),
                )
                if doc.get("topology"):
                    # host-sized units register their mesh slice in the
                    # coordinator's cluster topology (distributed/)
                    self.coordinator.cluster_topology.register(
                        doc["nodeId"], doc["uri"], doc.get("topology")
                    )
                if doc.get("memory"):
                    self.coordinator.cluster_memory.update_node(
                        doc["nodeId"], doc["memory"]
                    )
                if doc.get("opstats"):
                    # heartbeat-piggybacked per-task rollups feed the
                    # live straggler detector
                    self.coordinator.ingest_opstats(
                        doc["nodeId"], doc["opstats"]
                    )
                if doc.get("compiles"):
                    # compile-observatory piggyback: worker per-cause
                    # counts, census sketches, and ledger events merge
                    # into the coordinator's engine-wide observatory
                    self.coordinator.ingest_compiles(
                        doc["nodeId"], doc["compiles"]
                    )
            self._json(202, {})
        else:
            self._json(404, {"error": "not found"})

    def do_GET(self):
        parts = self.path.strip("/").split("/")
        co = self.coordinator
        # Query texts/errors/results are sensitive: the monitor UI, the
        # query inspection endpoints, and result fetches all require
        # authentication whenever an authenticator is configured, like
        # POST /v1/statement (the slug stays as a second factor).
        if (len(parts) >= 2 and parts[:2] == ["v1", "query"]) or (
            len(parts) >= 3 and parts[:3] == ["v1", "statement", "executing"]
        ):
            if self._authenticate() is None:
                return
        if self.path in ("/", "/ui", "/ui/"):
            # the page itself is constant HTML (data endpoints are gated
            # above); challenge only under Basic auth, where the 401 pops
            # the browser's credential dialog and so makes the page's
            # same-origin fetches work — a Bearer-only 401 would just
            # brick the monitor (browsers can't supply a token)
            auth = co.authenticator
            if (
                auth is not None
                and hasattr(auth, "authenticate")
                and self._authenticate() is None
            ):
                return
            # query monitor (webapp/ React UI analog, single static page)
            from .webui import UI_HTML

            body = UI_HTML.encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/html; charset=utf-8")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if self.path == "/metrics":
            body = REGISTRY.render_prometheus().encode()
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if self.path == "/v1/info":
            self._json(200, {
                "nodeId": co.node_id,
                "nodeVersion": {"version": "trino-tpu 0.1"},
                "environment": "tpu",
                "coordinator": True,
                # the coordinator's in-process executor dispatches through
                # the session supervisor; report its device health too
                "device": co.session.device_supervisor.snapshot(),
                "uptime": f"{time.time() - co.started:.0f}s",
            })
            return
        if self.path == "/v1/status":
            nm = co.node_manager
            self._json(200, {
                "nodeId": co.node_id,
                "activeQueries": sum(
                    1 for q in co.queries.values()
                    if q.state in ("QUEUED", "PLANNING", "RUNNING")
                ),
                "totalQueries": len(co.queries),
                # the /ui header reads these (coordinator itself counts
                # as the one executing node when no workers announced)
                "activeWorkers": len(nm.alive()) if nm is not None else 1,
                "uptimeSeconds": time.time() - co.started,
                # restart-recovery outcome (0/0 when no WAL configured)
                "recoveredQueries": co.recovered_queries,
                "orphanedQueries": co.orphaned_queries,
            })
            return
        if self.path == "/v1/memory":
            # cluster memory view (server/MemoryResource analog): pool
            # snapshots per node, per-query totals, killer verdicts
            doc = co.cluster_memory.info()
            doc["localManager"] = co.session.memory_manager.snapshot()
            doc["admission"] = co.admission.stats()
            self._json(200, doc)
            return
        if self.path == "/v1/resourceGroupState":
            self._json(200, co.resource_groups.info())
            return
        if self.path == "/v1/compiles":
            # the engine-wide compile observatory: ledger tail, per-
            # cause totals (local + ingested worker piggybacks), and
            # the shape census (HTTP face of system.runtime.compiles /
            # system.runtime.shape_census)
            from ..obs import compile_observatory as _co

            obs = _co.get_observatory()
            self._json(200, {
                "summary": obs.rollup(),
                "compiles": obs.tail(256),
                "census": obs.merged_census().snapshot(),
            })
            return
        if self.path == "/v1/signatures":
            # the signature census (HTTP face of
            # system.runtime.plan_signatures), busiest shapes first,
            # each annotated with its warmest node
            from ..obs import serving_observatory as _so

            obs = _so.get_observatory()
            self._json(200, {
                "signatures": obs.signature_rows(),
                "top": obs.top_signatures(10, local_node_id=co.node_id),
            })
            return
        if self.path == "/v1/affinity":
            # per-node warmth per signature (HTTP face of
            # system.runtime.signature_affinity) — the locality-aware
            # dispatcher's input table
            from ..obs import serving_observatory as _so

            self._json(200, {
                "affinity": _so.get_observatory().affinity_rows(
                    local_node_id=co.node_id
                ),
            })
            return
        if self.path == "/v1/slo":
            # per-tenant objectives + live multi-window burn rates
            # (HTTP face of system.runtime.slos)
            from ..obs import serving_observatory as _so

            self._json(200, {
                "slos": _so.get_observatory().slo_rows(),
            })
            return
        if self.path == "/v1/cache":
            # per-tier cache stats (the HTTP face of system.runtime.caches)
            mgr = getattr(co.session, "caches", None)
            self._json(200, {
                "caches": mgr.snapshot() if mgr is not None else [],
            })
            return
        if (
            len(parts) == 4
            and parts[:2] == ["v1", "query"]
            and parts[3] == "profile"
        ):
            q = co.queries.get(parts[2])
            if q is None:
                self._json(404, {"error": "query not found"})
                return
            self._json(200, co.query_profile(q))
            return
        if (
            len(parts) == 4
            and parts[:2] == ["v1", "query"]
            and parts[3] == "events"
        ):
            # journal events correlated with this query (tagged + ambient
            # within its wall-clock window)
            q = co.queries.get(parts[2])
            if q is None:
                self._json(404, {"error": "query not found"})
                return
            from ..obs import doctor

            events = doctor.events_for_query(
                q.query_id,
                window=(q.created, q.finished or time.time()),
            )
            self._json(200, {"queryId": q.query_id, "events": events})
            return
        if (
            len(parts) == 4
            and parts[:2] == ["v1", "query"]
            and parts[3] == "diagnosis"
        ):
            q = co.queries.get(parts[2])
            if q is None:
                self._json(404, {"error": "query not found"})
                return
            self._json(200, {
                "queryId": q.query_id,
                "diagnosis": q.diagnosis,
            })
            return
        if len(parts) == 3 and parts[:2] == ["v1", "query"]:
            q = co.queries.get(parts[2])
            if q is None:
                self._json(404, {"error": "query not found"})
                return
            with q.lock:
                self._json(200, {
                    "queryId": q.query_id,
                    "state": q.state,
                    "query": q.sql,
                    "user": q.user,
                    "error": q.error,
                    "elapsedMillis": int(
                        ((q.finished or time.time()) - q.created) * 1000
                    ),
                    "outputRows": q.page.count if q.page else None,
                    # whole-query re-dispatches under retry_policy=query
                    "retryCount": q.retry_count,
                    # per-task rollup (OperatorStats->TaskStats->QueryStats
                    # hierarchy analog): totals + the per-task detail
                    "stats": {
                        "scanBytes": sum(
                            t.get("scanBytes", 0)
                            for t in getattr(q, "task_stats", [])
                        ),
                        "dynamicFilterRowsPruned": sum(
                            t.get("dynamicFilterRowsPruned", 0)
                            for t in getattr(q, "task_stats", [])
                        ),
                        "tasks": getattr(q, "task_stats", []),
                    },
                    # merged per-operator timeline (stage -> task -> op)
                    # with dispersion-detector straggler verdicts
                    "timeline": getattr(q, "timeline", None),
                    "stragglers": getattr(q, "straggler_flags", []),
                })
            return
        if self.path == "/v1/query":
            self._json(200, [
                {
                    "queryId": q.query_id,
                    "state": q.state,
                    "query": q.sql[:200],
                    "error": q.error,
                }
                for q in co.queries.values()
            ])
            return
        if (
            len(parts) == 6
            and parts[:3] == ["v1", "statement", "executing"]
        ):
            _, _, _, qid, slug, token = parts
            q = co.queries.get(qid)
            if q is None and co.in_recovery_window():
                # restart transparency: this id may still be sitting in
                # the WAL scan — tell the client to wait, not to die
                self._json(
                    503,
                    {"error": "coordinator recovering; retry shortly",
                     "retryable": True},
                    headers={"Retry-After": "1"},
                )
                return
            if q is None or q.slug != slug:
                self._json(404, {"error": "query not found"})
                return
            # long-poll: wait briefly for progress (StatementClient advance)
            deadline = time.time() + 1.0
            while time.time() < deadline and q.state in (
                "QUEUED", "PLANNING", "RUNNING",
            ):
                time.sleep(0.02)
            self._json(200, co.results_doc(q, int(token)))
            return
        self._json(404, {"error": "not found"})

    def do_DELETE(self):
        parts = self.path.strip("/").split("/")
        if len(parts) >= 4 and parts[:3] == ["v1", "statement", "executing"]:
            if self._authenticate() is None:
                return
            # the cancel URI is the nextUri path (includes the slug
            # capability token); the slug is MANDATORY, like GET
            q = self.coordinator.queries.get(parts[3])
            if q is None or len(parts) < 5 or parts[4] != q.slug:
                self._json(404, {"error": "query not found"})
                return
            self.coordinator.cancel(parts[3])
            self._json(204, {})
        else:
            self._json(404, {"error": "not found"})


class CoordinatorServer:
    """In-process server handle (TestingTrinoServer analog)."""

    def __init__(self, session: Session, port: int = 0,
                 distributed: bool = False,
                 resource_groups: Optional[dict] = None,
                 authenticator=None,
                 fault_injection: Optional[dict] = None):
        self.coordinator = Coordinator(
            session, distributed=distributed,
            resource_groups=resource_groups, authenticator=authenticator,
            fault_injection=fault_injection,
        )
        handler = type("Handler", (_Handler,), {"coordinator": self.coordinator})
        # serving posture: the stdlib default listen backlog of 5 resets
        # connections the moment a few dozen sessions POST at once
        server_cls = type(
            "CoordinatorHTTPServer", (ThreadingHTTPServer,),
            {"request_queue_size": 128},
        )
        self.httpd = server_cls(("127.0.0.1", port), handler)
        self.port = self.httpd.server_address[1]
        self.thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)

    def start(self) -> "CoordinatorServer":
        self.thread.start()
        return self

    def stop(self):
        self.httpd.shutdown()
        self.coordinator._stop_enforcement.set()
        if self.coordinator.failure_detector is not None:
            self.coordinator.failure_detector.stop()

    @property
    def uri(self) -> str:
        return f"http://127.0.0.1:{self.port}"
