"""Distributed query scheduler (pipelined mode).

Reference parity: execution/scheduler/PipelinedQueryScheduler.java:157 —
all stages scheduled at once; split placement over alive workers mirrors
NodeScheduler/UniformNodeSelector round-robin; per-(stage,node) remote tasks
are created via the task API (HttpRemoteTask.sendUpdate:722 POST
/v1/task/{taskId} with fragment+splits+outputBuffers); the root stage's
output is pulled back through the exchange client (server/protocol/Query
pulling from the root OutputBuffer).

Task/buffer wiring:
  - SOURCE fragments: one task per alive worker, splits round-robin
  - HASH fragments: one task per alive worker (FIXED_HASH_DISTRIBUTION)
  - SINGLE fragments (and the root): one task on one worker
  - producer buffer count = consumer task count when output is hash;
    broadcast/single producers expose one buffer (0) that every consumer
    (or the single consumer) reads — BroadcastOutputBuffer semantics
"""
from __future__ import annotations

import json
import time
import urllib.request
import uuid
from typing import Dict, List, Optional, Tuple

from ..catalog import CatalogManager
from ..exec.exchange_client import ExchangeClient, RemoteTaskError
from ..exec.partitioner import concat_pages
from ..page import Page
from ..plan import nodes as P
from ..plan.fragment import (
    ARBITRARY,
    BROADCAST,
    HASH,
    SINGLE,
    SOURCE,
    PlanFragment,
    fragment_plan,
)
from ..serde import encode_value, plan_to_json
from ..utils.metrics import REGISTRY
from ..utils.tracing import TRACER

SPLITS_PER_NODE = 4


class SchedulerError(RuntimeError):
    pass


class TaskHandle:
    def __init__(self, task_id: str, uri: str):
        self.task_id = task_id
        self.uri = uri


def _post_json(url: str, doc: dict, timeout: float = 30.0):
    headers = {"Content-Type": "application/json"}
    # propagate the caller's trace context (W3C Trace Context): the worker
    # parents its task span under the coordinator's query span
    tp = TRACER.current_traceparent()
    if tp:
        headers["traceparent"] = tp
    req = urllib.request.Request(
        url, data=json.dumps(doc).encode(), headers=headers
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return json.loads(resp.read() or b"{}")


def assign_splits(
    catalogs: CatalogManager, f: PlanFragment, ntasks: int
) -> List[Dict[int, list]]:
    """Round-robin split placement over a stage's tasks (NodeScheduler /
    UniformNodeSelector role); single-task fragments scan everything.
    Shared by the pipelined and fault-tolerant schedulers."""
    per_task: List[Dict[int, list]] = [dict() for _ in range(ntasks)]
    for scan_idx, (catalog, table, constraint) in f.scan_tables.items():
        conn = catalogs.get(catalog)
        if f.partitioning == SOURCE:
            desired = max(ntasks * SPLITS_PER_NODE, 1)
            splits = conn.split_manager().get_splits(
                table, desired, constraint
            )
            for i, sp in enumerate(splits):
                per_task[i % ntasks].setdefault(scan_idx, []).append(sp)
        else:
            splits = conn.split_manager().get_splits(table, 1, constraint)
            per_task[0].setdefault(scan_idx, []).extend(splits)
    return per_task


def source_buffer_index(src_frag: PlanFragment, task_index: int) -> int:
    """Which producer buffer a consumer task reads: its own index for
    hash/arbitrary repartitioning, buffer 0 for single/broadcast."""
    return (
        task_index
        if src_frag.output_partitioning in (HASH, ARBITRARY)
        else 0
    )


class DistributedScheduler:
    """Schedules one query's fragments onto the alive workers."""

    def __init__(
        self,
        catalogs: CatalogManager,
        workers: List[Tuple[str, str]],
        properties: Optional[dict] = None,
        memory_view=None,
        node_manager=None,
    ):
        if not workers:
            raise SchedulerError("no alive workers")
        self.catalogs = catalogs
        self.workers = workers
        self.properties = properties or {}
        # optional ClusterMemoryManager: single-task placement prefers
        # the node with the most free pool bytes and avoids blocked
        # nodes (NodeScheduler memory-aware selection)
        self.memory_view = memory_view
        # optional NodeManager: announced device-health snapshots route
        # work away from DEGRADED nodes and off QUARANTINED ones
        self.node_manager = node_manager

    def _device_states(self) -> Dict[str, dict]:
        if self.node_manager is None:
            return {}
        try:
            return self.node_manager.device_states()
        except Exception:
            return {}

    @staticmethod
    def _health_rank(device: Optional[dict]) -> int:
        """ACTIVE/unknown=2 > DEGRADED=1 > QUARANTINED=0.  Unknown ranks
        with ACTIVE: a node that never announced device health predates
        the supervisor and is presumed fine."""
        state = (device or {}).get("state", "ACTIVE")
        return {"QUARANTINED": 0, "DEGRADED": 1}.get(state, 2)

    def _lifecycle_states(self) -> Dict[str, str]:
        if self.node_manager is None:
            return {}
        fn = getattr(self.node_manager, "lifecycle_states", None)
        if fn is None:
            return {}
        try:
            return fn()
        except Exception:
            return {}

    def _schedulable_workers(self) -> List[Tuple[str, str]]:
        """Workers eligible for placement, re-resolved per stage from the
        node manager when one is attached: DRAINING/DRAINED/SUSPECT/GONE
        nodes drop out mid-query and LATE JOINERS become schedulable for
        new stages without a restart.  QUARANTINED devices (all devices
        out, no CPU fallback) are excluded too.  There is NO silent
        fallback — when every node is excluded the query fails with a
        structured error naming each exclusion, instead of quietly
        re-admitting nodes known to be unhealthy."""
        pool = list(self.workers)
        alive_fn = getattr(self.node_manager, "alive", None)
        if alive_fn is not None:
            try:
                pool = list(alive_fn())
            except Exception:
                pool = list(self.workers)
        lifecycle = self._lifecycle_states()
        device = self._device_states()
        ok: List[Tuple[str, str]] = []
        excluded: List[str] = []
        for w in pool:
            state = lifecycle.get(w[0], "ACTIVE")
            if state != "ACTIVE":
                excluded.append(f"{w[0]}={state}")
            elif self._health_rank(device.get(w[0])) <= 0:
                excluded.append(f"{w[0]}=QUARANTINED")
            else:
                ok.append(w)
        seen = {w[0] for w in pool}
        for w in self.workers:
            if w[0] not in seen:
                excluded.append(
                    f"{w[0]}={lifecycle.get(w[0], 'NOT_ALIVE')}"
                )
        if not ok:
            raise SchedulerError(
                "NO_NODES_AVAILABLE: every worker is unschedulable "
                f"(excluded: {', '.join(sorted(excluded)) or 'none known'})"
            )
        return ok

    def _pick_single_worker(self, query_id: str) -> Tuple[str, str]:
        pool = self._schedulable_workers()
        device = self._device_states()
        nodes: Dict[str, dict] = {}
        if self.memory_view is not None:
            try:
                nodes = {
                    n.get("nodeId"): n
                    for n in self.memory_view.nodes_view()
                }
            except Exception:
                nodes = {}

        def headroom(w: Tuple[str, str]) -> int:
            snap = nodes.get(w[0])
            if not snap:
                return -1  # no snapshot yet: only if nothing better
            if snap.get("blocked"):
                return -2  # a blocked node can't host new work
            return sum(
                int(p.get("free", 0))
                for p in (snap.get("pools") or {}).values()
            )

        # device health dominates memory headroom: a DEGRADED node (CPU
        # fallback) ranks below ANY ACTIVE node regardless of free bytes;
        # unschedulable nodes never reach this ranking at all
        best = max(
            (self._health_rank(device.get(w[0])), headroom(w))
            for w in pool
        )
        if best[1] < 0 and len(pool) == len(self.workers) and best[0] >= 2:
            # no memory signal and no health signal: hash-spread pick
            return pool[hash(query_id) % len(pool)]
        candidates = [
            w for w in pool
            if (self._health_rank(device.get(w[0])), headroom(w)) == best
        ]
        return candidates[hash(query_id) % len(candidates)]

    # ------------------------------------------------------------------
    def run(self, plan: P.Output, query_id: Optional[str] = None) -> Page:
        query_id = query_id or f"q_{uuid.uuid4().hex[:12]}"
        fragments = fragment_plan(plan)
        by_id = {f.id: f for f in fragments}
        consumer: Dict[int, int] = {}
        for f in fragments:
            for sf in f.source_fragments:
                consumer[sf] = f.id

        # task placement (stage width)
        ntasks: Dict[int, int] = {}
        placement: Dict[int, List[Tuple[str, str]]] = {}
        for f in fragments:
            if f.partitioning in (SOURCE, HASH, ARBITRARY):
                placement[f.id] = self._schedulable_workers()
            else:  # SINGLE; memory-aware pick, hash spread as fallback
                placement[f.id] = [self._pick_single_worker(query_id)]
            ntasks[f.id] = len(placement[f.id])

        # buffer counts: hash output -> one buffer per consumer task
        nbuffers: Dict[int, int] = {}
        for f in fragments:
            if f.output_partitioning in (HASH, ARBITRARY):
                nbuffers[f.id] = ntasks[consumer[f.id]]
            else:
                nbuffers[f.id] = 1

        tasks: Dict[int, List[TaskHandle]] = {}
        created: List[TaskHandle] = []
        try:
            # children before consumers: cuts happen bottom-up, so source
            # fragments always have smaller ids; root (0) is scheduled last
            order = sorted((f for f in fragments if f.id != 0),
                           key=lambda f: f.id) + [by_id[0]]
            for f in order:
                tasks[f.id] = self._schedule_fragment(
                    query_id, f, placement[f.id], nbuffers[f.id], tasks,
                    by_id,
                )
                created.extend(tasks[f.id])
            root_task = tasks[0][0]
            client = ExchangeClient(traceparent=TRACER.current_traceparent())
            pages = client.fetch_sources(
                {0: [{"uri": root_task.uri, "task": root_task.task_id,
                      "buffer": 0}]}
            )[0]
            if not pages:
                raise SchedulerError("root task produced no pages")
            # per-task stats rollup (OperatorStats -> TaskStats ->
            # QueryStats hierarchy analog) surfaced at /v1/query/{id}
            self.last_task_stats = []
            for t in created:
                try:
                    with urllib.request.urlopen(
                        f"{t.uri}/v1/task/{t.task_id}", timeout=5.0
                    ) as resp:
                        info = json.loads(resp.read())
                    self.last_task_stats.append(
                        {"taskId": t.task_id, "uri": t.uri,
                         **(info.get("stats") or {})}
                    )
                except Exception:
                    pass
            return concat_pages(pages)
        finally:
            for t in created:
                try:
                    req = urllib.request.Request(
                        f"{t.uri}/v1/task/{t.task_id}", method="DELETE"
                    )
                    urllib.request.urlopen(req, timeout=5.0).read()
                except Exception:
                    pass

    # ------------------------------------------------------------------
    def _schedule_fragment(
        self,
        query_id: str,
        f: PlanFragment,
        workers: List[Tuple[str, str]],
        out_buffers: int,
        tasks: Dict[int, List[TaskHandle]],
        by_id: Dict[int, PlanFragment],
    ) -> List[TaskHandle]:
        n = len(workers)
        splits_per_task = assign_splits(self.catalogs, f, n)

        frag_json = plan_to_json(f.root)
        handles: List[TaskHandle] = []
        for i, (node_id, uri) in enumerate(workers):
            task_id = f"{query_id}.{f.id}.{i}"
            sources: Dict[str, list] = {}
            for sf in f.source_fragments:
                src_frag = by_id[sf]
                locs = [
                    {
                        "uri": up.uri,
                        "task": up.task_id,
                        "buffer": source_buffer_index(src_frag, i),
                    }
                    for up in tasks[sf]
                ]
                sources[str(sf)] = locs
            doc = {
                "fragment": frag_json,
                "splits": {
                    str(k): [encode_value(s) for s in v]
                    for k, v in splits_per_task[i].items()
                },
                "output": {
                    "partitioning": f.output_partitioning,
                    "keys": list(f.output_keys),
                    "nbuffers": out_buffers,
                },
                "sources": sources,
                "properties": self.properties,
            }
            _post_json(f"{uri}/v1/task/{task_id}", doc)
            REGISTRY.counter(
                "trino_tpu_scheduler_dispatch_total",
                "Remote task creations dispatched to workers",
            ).inc()
            handles.append(TaskHandle(task_id, uri))
        return handles
