"""Coordinator web UI: a single-file query monitor.

Reference parity: core/trino-main/src/main/resources/webapp/ — the React
cluster/query UI served by the coordinator.  This engine serves one
dependency-free HTML page at /ui that polls the same REST endpoints the
reference UI uses (/v1/status, /v1/query, /v1/query/{id}) and renders the
cluster summary, the query list, per-query task statistics, the
per-tenant SLO panel (/v1/slo), and the top-10 plan signatures with
their warmest node (/v1/signatures).
"""

UI_HTML = """<!doctype html>
<html>
<head>
<meta charset="utf-8">
<title>trino-tpu</title>
<style>
  body { font-family: ui-sans-serif, system-ui, sans-serif; margin: 0;
         background: #0f1318; color: #e6e9ed; }
  header { padding: 14px 24px; background: #161c24;
           border-bottom: 1px solid #2a3340; display: flex; gap: 24px;
           align-items: baseline; }
  h1 { font-size: 16px; margin: 0; color: #7fd1b9; }
  .stat { font-size: 13px; color: #9aa7b4; }
  .stat b { color: #e6e9ed; }
  main { padding: 18px 24px; }
  table { border-collapse: collapse; width: 100%; font-size: 13px; }
  th, td { text-align: left; padding: 6px 10px;
           border-bottom: 1px solid #222b36; }
  th { color: #9aa7b4; font-weight: 600; }
  tr.q { cursor: pointer; }
  tr.q:hover { background: #19212b; }
  .FINISHED { color: #7fd1b9; } .FAILED { color: #e0707a; }
  .RUNNING, .PLANNING, .QUEUED { color: #e3c567; }
  #detail { margin-top: 18px; padding: 12px; background: #161c24;
            border: 1px solid #2a3340; border-radius: 6px;
            white-space: pre-wrap; font-family: ui-monospace, monospace;
            font-size: 12px; display: none; }
  h2 { font-size: 13px; color: #9aa7b4; margin: 22px 0 6px; }
  .burn-ok { color: #7fd1b9; } .burn-hot { color: #e0707a; }
</style>
</head>
<body>
<header>
  <h1>trino-tpu</h1>
  <span class="stat">workers <b id="workers">–</b></span>
  <span class="stat">queries <b id="nqueries">–</b></span>
  <span class="stat">uptime <b id="uptime">–</b></span>
</header>
<main>
  <table>
    <thead><tr><th>query id</th><th>state</th><th>query</th>
               <th>error</th></tr></thead>
    <tbody id="rows"></tbody>
  </table>
  <div id="detail"></div>
  <h2>tenant SLOs</h2>
  <table>
    <thead><tr><th>tenant</th><th>target</th><th>budget</th>
               <th>fast burn</th><th>slow burn</th><th>violations</th>
               <th>burn events</th><th>p99</th></tr></thead>
    <tbody id="slorows"></tbody>
  </table>
  <h2>top signatures</h2>
  <table>
    <thead><tr><th>signature</th><th>tenant</th><th>count</th>
               <th>rate/s</th><th>p99</th><th>drift</th>
               <th>cache h/m</th><th>warmest node</th></tr></thead>
    <tbody id="sigrows"></tbody>
  </table>
</main>
<script>
async function j(u) { const r = await fetch(u); return r.json(); }
async function refresh() {
  try {
    const st = await j('/v1/status');
    document.getElementById('workers').textContent =
      st.activeWorkers ?? st.workers ?? '–';
    document.getElementById('uptime').textContent =
      st.uptimeSeconds ? st.uptimeSeconds.toFixed(0) + 's' : '–';
    const qs = await j('/v1/query');
    document.getElementById('nqueries').textContent = qs.length;
    const tbody = document.getElementById('rows');
    tbody.innerHTML = '';
    for (const q of qs.slice().reverse()) {
      const tr = document.createElement('tr');
      tr.className = 'q';
      for (const [text, cls] of [[q.queryId, null], [q.state, q.state],
                                 [q.query || '', null], [q.error || '', null]]) {
        const td = document.createElement('td');
        td.textContent = text;
        if (cls) td.className = cls;
        tr.appendChild(td);
      }
      tr.onclick = async () => {
        const d = await j('/v1/query/' + q.queryId);
        let prof = null;
        try { prof = await j('/v1/query/' + q.queryId + '/profile'); }
        catch (e) { /* profile unavailable */ }
        const el = document.getElementById('detail');
        el.style.display = 'block';
        let text = JSON.stringify(d, null, 2);
        const s = prof && prof.summary;
        if (s && Object.keys(s).length) {
          text += '\\n\\nTPU kernel profile:' +
            '\\n  compile wall: ' + (s.compileWallS * 1000).toFixed(2) + 'ms' +
            '\\n  compiles: ' + s.compiles + ' (recompiles ' + s.recompiles +
            ', cache hits ' + s.cacheHits + ')' +
            (s.compilesByCause && Object.keys(s.compilesByCause).length
              ? '\\n  by cause: ' + Object.entries(s.compilesByCause)
                  .filter(([, n]) => n > 0)
                  .map(([c, n]) => c + '=' + n).join(', ')
              : '') +
            '\\n  padding ratio: ' + s.paddingRatio.toFixed(2) + 'x (' +
            s.actualRows + ' -> ' + s.paddedRows + ' rows)' +
            '\\n  transfers: ~' + s.h2dBytes + 'B h2d, ~' + s.d2hBytes + 'B d2h';
        }
        el.textContent = text;
      };
      tbody.appendChild(tr);
    }
  } catch (e) { /* coordinator restarting */ }
  try {
    const slo = await j('/v1/slo');
    const sb = document.getElementById('slorows');
    sb.innerHTML = '';
    for (const t of slo.slos || []) {
      const tr = document.createElement('tr');
      const burn = (v) => {
        const td = document.createElement('td');
        td.textContent = v.toFixed(2) + 'x';
        td.className = v > 1.0 ? 'burn-hot' : 'burn-ok';
        return td;
      };
      const cell = (v) => {
        const td = document.createElement('td');
        td.textContent = v;
        return td;
      };
      tr.appendChild(cell(t.tenant));
      tr.appendChild(cell(t.latencyTargetS.toFixed(2) + 's'));
      tr.appendChild(cell((t.errorBudget * 100).toFixed(0) + '%'));
      tr.appendChild(burn(t.fastBurnRate));
      tr.appendChild(burn(t.slowBurnRate));
      tr.appendChild(cell(t.violationsTotal + '/' + t.observedTotal));
      tr.appendChild(cell(t.burnEvents));
      tr.appendChild(cell((t.p99S * 1000).toFixed(0) + 'ms'));
      sb.appendChild(tr);
    }
    const sigs = await j('/v1/signatures');
    const gb = document.getElementById('sigrows');
    gb.innerHTML = '';
    for (const s of sigs.top || []) {
      const tr = document.createElement('tr');
      for (const v of [s.signature.slice(0, 12), s.tenant, s.count,
                       s.ratePerS.toFixed(2), (s.p99S * 1000).toFixed(0) + 'ms',
                       s.driftRatio.toFixed(1) + 'x',
                       s.cacheHits + '/' + s.cacheMisses,
                       s.warmestNode || '–']) {
        const td = document.createElement('td');
        td.textContent = v;
        tr.appendChild(td);
      }
      gb.appendChild(tr);
    }
  } catch (e) { /* observatory not up yet */ }
}
refresh(); setInterval(refresh, 2000);
</script>
</body>
</html>
"""
