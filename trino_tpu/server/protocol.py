"""Client protocol types: query results JSON model.

Reference parity: client/trino-client (QueryResults/QueryData JSON model,
StatementClientV1.java:69) and server/protocol/
(QueuedStatementResource.java:105, ExecutingStatementResource.java:71).

The wire format is a compatible subset of the reference's /v1/statement
protocol: POST returns a QueryResults document; paging via nextUri; column
type names use the reference's spelling (bigint, decimal(p,s), varchar,
date, double).
"""
from __future__ import annotations

from typing import List, Optional

from .. import types as T
from ..page import Page


def type_name(t: T.Type) -> str:
    return str(t)


def columns_json(page: Page, types: List[T.Type]) -> list:
    return [
        {
            "name": name,
            "type": type_name(t),
            "typeSignature": {"rawType": t.name, "arguments": []},
        }
        for name, t in zip(page.names, types)
    ]


def data_json(page: Page) -> list:
    from decimal import Decimal

    # wide decimals decode to decimal.Decimal; the wire sends them as
    # strings (StatementClientV1 decimal representation)
    return [
        [str(v) if isinstance(v, Decimal) else v for v in row]
        for row in page.to_pylist()
    ]


def query_results(
    query_id: str,
    state: str,
    page: Optional[Page] = None,
    types: Optional[List[T.Type]] = None,
    next_uri: Optional[str] = None,
    error: Optional[str] = None,
    stats: Optional[dict] = None,
) -> dict:
    doc = {
        "id": query_id,
        "infoUri": f"/ui/query/{query_id}",
        "stats": {
            "state": state,
            "queued": state == "QUEUED",
            "scheduled": state not in ("QUEUED",),
            **(stats or {}),
        },
    }
    if next_uri:
        doc["nextUri"] = next_uri
    if page is not None:
        doc["columns"] = columns_json(page, types)
        doc["data"] = data_json(page)
    if error:
        doc["error"] = error_json(error)
    return doc


# error strings carrying one of these structured prefixes ("CODE: ...")
# render as named retryable errors (EXTERNAL, like the reference's
# REMOTE_HOST_GONE retry class) instead of GENERIC_INTERNAL_ERROR —
# clients re-submit instead of surfacing a failure
RETRYABLE_ERROR_CODES = {
    "COORDINATOR_RESTART": 65544,
}


def error_json(error: str) -> dict:
    code, sep, _rest = str(error).partition(":")
    code = code.strip()
    if sep and code in RETRYABLE_ERROR_CODES:
        return {
            "message": error,
            "errorCode": RETRYABLE_ERROR_CODES[code],
            "errorName": code,
            "errorType": "EXTERNAL",
            "retriable": True,
        }
    return {
        "message": error,
        "errorCode": 1,
        "errorName": "GENERIC_INTERNAL_ERROR",
        "errorType": "INTERNAL_ERROR",
    }
