"""Standalone coordinator entrypoint:
``python -m trino_tpu.server.coordinator_main``.

The coordinator-crash chaos harness (tests/test_recovery.py,
``bench.py --chaos-coordinator``) needs a coordinator the OS can actually
kill — an in-process CoordinatorServer shares its fate with the test
runner, so kill -9 semantics (query state machine vaporized mid-flight,
clients' sockets refuse instantly, only the mmap'd WAL survives) are only
reachable with a real child process.  This entrypoint boots one
distributed CoordinatorServer, prints a single JSON line
``{"nodeId": ..., "uri": ..., "port": ...}`` on stdout so the parent can
target it, and sleeps until killed.  Restarting it on the SAME port with
the same ``coordinator_recovery_dir`` exercises the full recovery path:
surviving workers re-announce to the fixed URI within one heartbeat, the
WAL replays, and in-flight FTE queries resume from committed spools.

Coordinator-level fault injection (``--fault-injection``) arms the
seeded ``coordinator_death`` site — ``os._exit(137)`` immediately after
a chosen WAL transition lands in the mmap'd segment — which the
in-process runner must never fire.
"""
from __future__ import annotations

import argparse
import json
import signal
import sys
import time

DEFAULT_CATALOGS = [["tpch", "tpch", {"tpch.scale-factor": 0.01}]]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="run one trino_tpu coordinator process"
    )
    p.add_argument(
        "--catalogs", default=None,
        help="JSON [[name, connector, config], ...]; default: tpch sf0.01",
    )
    p.add_argument(
        "--properties", default=None,
        help="JSON session properties (coordinator_recovery_dir, "
        "retry_policy, event_journal_dir, ...)",
    )
    p.add_argument(
        "--port", type=int, default=0,
        help="bind port; a restart MUST reuse the crashed coordinator's "
        "port so surviving workers and polling clients reconnect",
    )
    p.add_argument(
        "--fault-injection", default=None,
        help="coordinator-level FaultInjector spec (JSON) arming the "
        "coordinator_death site at a chosen WAL transition",
    )
    args = p.parse_args(argv)

    # parity with the in-process topology: conftest/force_cpu enable
    # x64 everywhere else, and a coordinator stuck on int32 overflows
    # on wide aggregates
    from .. import enable_x64

    enable_x64()

    from ..session import Session
    from .coordinator import CoordinatorServer

    spec = json.loads(args.catalogs) if args.catalogs else DEFAULT_CATALOGS
    props = json.loads(args.properties) if args.properties else {}
    fault_injection = (
        json.loads(args.fault_injection) if args.fault_injection else None
    )
    session = Session(config=props)
    for name, connector, config in spec:
        session.create_catalog(name, connector, config)
    server = CoordinatorServer(
        session, port=args.port, distributed=True,
        fault_injection=fault_injection,
    ).start()
    print(json.dumps({
        "nodeId": server.coordinator.node_id,
        "uri": server.uri,
        "port": server.port,
    }), flush=True)

    # SIGTERM is the graceful stop (tests use it for clean teardown);
    # only SIGKILL is the crash under test
    stopping = {"flag": False}

    def _on_sigterm(_sig, _frame):
        stopping["flag"] = True

    signal.signal(signal.SIGTERM, _on_sigterm)
    try:
        while not stopping["flag"]:
            time.sleep(0.2)
    except KeyboardInterrupt:
        pass
    server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
