"""Discovery, heartbeat failure detection, and node lifecycle (coordinator).

Reference parity: airlift discovery announcements maintained by
DiscoveryNodeManager plus active HTTP heartbeats with an exponentially
decayed failure ratio in failuredetector/HeartbeatFailureDetector.java:76
(ping:344, failureRatio:377 vs threshold), composed with the NodeState.java
lifecycle (ACTIVE / SHUTTING_DOWN / DRAINING / DRAINED / INACTIVE).  Here
every announced worker walks an explicit state machine:

    ACTIVE ----(missed beats / failed pings)----> SUSPECT --(silence
        past the gone grace)--> GONE
    ACTIVE --(worker announces DRAINING via PUT /v1/info/state)-->
        DRAINING --(worker drains, announces DRAINED)--> DRAINED
        --(operator terminates the process, silence)--> GONE

SUSPECT is the missed-beat suspicion window: the node is unschedulable
but NOT declared dead, so a GC pause or dropped announcement round can
recover back to ACTIVE instead of triggering task reassignment.  GONE is
the terminal verdict that fans out to the schedulers (FTE reassigns the
node's unfinished tasks) and the coordinator cleanup listeners (memory
pool eviction, opstats ghost retirement).  A GONE node that announces
again rejoins as ACTIVE — late joiners and restarts become schedulable
for new stages without a coordinator restart.
"""
from __future__ import annotations

import threading
import time
import urllib.request
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..utils.metrics import REGISTRY

ANNOUNCEMENT_TTL = 5.0
NODE_EXPIRY = 30.0  # forget nodes silent this long (restart churn cleanup)
FAILURE_RATIO_THRESHOLD = 0.5
DECAY = 0.7  # EMA weight of history per heartbeat
# continuous silence (no successful ping OR announcement) before a
# SUSPECT/DRAINING/DRAINED node is declared GONE; overridable per
# coordinator via the node_gone_grace_s session property
GONE_GRACE = 10.0

ACTIVE = "ACTIVE"
SUSPECT = "SUSPECT"
DRAINING = "DRAINING"
DRAINED = "DRAINED"
GONE = "GONE"
LIFECYCLE_STATES = (ACTIVE, SUSPECT, DRAINING, DRAINED, GONE)

# wire schema of one nodes_snapshot() entry (system.runtime.nodes and the
# monitor consume it); field naming linted by scripts/check_metric_names.py
NODE_FIELDS = (
    "nodeId",
    "uri",
    "state",
    "stateSince",
    "failureRatio",
    "device",
    "host",
    "processIndex",
    "localDevices",
)


class NodeState:
    def __init__(self, node_id: str, uri: str):
        self.node_id = node_id
        self.uri = uri
        now = time.time()
        self.last_announced = now
        # last successful contact of ANY kind (announcement or ping):
        # the gone-grace silence clock measures from here
        self.last_ok = now
        self.failure_ratio = 0.0
        self.last_ping_ok = True
        self.state = ACTIVE
        self.state_since = now
        # latest pool snapshot piggybacked on the announcement (consumed
        # by the coordinator-side ClusterMemoryManager)
        self.memory: Optional[dict] = None
        # latest device-health snapshot (runtime/supervisor.py): node
        # state ACTIVE/DEGRADED/QUARANTINED + per-device strike counts,
        # consumed by scheduler placement and system.runtime.nodes
        self.device: Optional[dict] = None
        # announced multi-host topology (distributed/topology.py
        # TOPOLOGY_FIELDS: host, processIndex, localDevices, ...): a node
        # carrying one is a host-sized capacity unit — losing it fires
        # HOST_GONE in addition to NODE_GONE
        self.topology: Optional[dict] = None


class NodeManager:
    """Tracks announced workers, their health, and their lifecycle."""

    def __init__(self, gone_grace: float = GONE_GRACE):
        self.nodes: Dict[str, NodeState] = {}
        self.lock = threading.Lock()
        self.gone_grace = float(gone_grace)
        # fired (node_id, uri) OUTSIDE the lock on every transition to
        # GONE: coordinator cleanup (memory eviction, opstats ghosts) and
        # anything else that must react to a node death exactly once
        self._gone_listeners: List[Callable[[str, str], None]] = []

    def add_gone_listener(self, cb: Callable[[str, str], None]):
        self._gone_listeners.append(cb)

    # -- state machine --------------------------------------------------
    def _set_state(self, n: NodeState, state: str, now: float):
        """Transition one node (caller holds the lock); returns the
        (node_id, uri, prev, new) event or None when it's a no-op."""
        if n.state == state:
            return None
        prev, n.state, n.state_since = n.state, state, now
        REGISTRY.gauge(
            "trino_tpu_node_lifecycle_state",
            "Node lifecycle ordinal (ACTIVE=0 SUSPECT=1 DRAINING=2 "
            "DRAINED=3 GONE=4)",
        ).set(LIFECYCLE_STATES.index(state), node=n.node_id)
        if state == DRAINED:
            REGISTRY.counter(
                "trino_tpu_node_drained_total",
                "Nodes that completed a graceful drain",
            ).inc()
        if state == GONE:
            REGISTRY.counter(
                "trino_tpu_node_gone_total",
                "Nodes declared GONE after the suspicion window",
            ).inc()
        from ..obs import journal

        if state == ACTIVE:
            if prev == GONE:
                journal.emit(journal.NODE_REJOIN, node_id=n.node_id)
        else:
            journal.emit(
                {
                    SUSPECT: journal.NODE_SUSPECT,
                    DRAINING: journal.NODE_DRAINING,
                    DRAINED: journal.NODE_DRAINED,
                    GONE: journal.NODE_GONE,
                }[state],
                node_id=n.node_id,
                severity=journal.ERROR if state == GONE
                else journal.WARN if state == SUSPECT else journal.INFO,
                prev=prev,
            )
            if state == GONE and n.topology:
                # host-sized unit lost: its whole device slice left the
                # mesh at once — a distinct event so the doctor can rank
                # a host loss above single-node churn
                journal.emit(
                    journal.HOST_GONE,
                    node_id=n.node_id,
                    severity=journal.ERROR,
                    host=n.topology.get("host", ""),
                    processIndex=n.topology.get("processIndex", 0),
                    localDevices=n.topology.get("localDevices", 0),
                )
                REGISTRY.counter(
                    "trino_tpu_host_gone_total",
                    "Host-sized capacity units declared GONE",
                ).inc()
                # the GLOBAL logical mesh shrank: every device in the
                # dead process's slice left at once (the cluster-level
                # analog of a quarantined device dropping out of the
                # local SPMD mesh)
                journal.emit(
                    journal.MESH_SHRINK,
                    node_id=n.node_id,
                    severity=journal.WARN,
                    host=n.topology.get("host", ""),
                    devicesLost=n.topology.get("localDevices", 0),
                )
        return (n.node_id, n.uri, prev, state)

    def _fire(self, events):
        for ev in events or ():
            if ev is None:
                continue
            node_id, uri, _prev, state = ev
            if state != GONE:
                continue
            for cb in self._gone_listeners:
                try:
                    cb(node_id, uri)
                except Exception:
                    pass

    def tick(self, now: Optional[float] = None):
        """Apply time-driven transitions: ACTIVE nodes with stale
        announcements or a tripped failure ratio become SUSPECT; any
        unreachable node (SUSPECT, or DRAINING/DRAINED whose process was
        terminated) silent past the gone grace becomes GONE."""
        now = time.time() if now is None else now
        events = []
        with self.lock:
            for n in self.nodes.values():
                if n.state == GONE:
                    continue
                unhealthy = (
                    now - n.last_announced > ANNOUNCEMENT_TTL
                    or n.failure_ratio >= FAILURE_RATIO_THRESHOLD
                )
                if n.state == ACTIVE and unhealthy:
                    events.append(self._set_state(n, SUSPECT, now))
                if (
                    n.state in (SUSPECT, DRAINING, DRAINED)
                    and now - n.last_ok > self.gone_grace
                ):
                    events.append(self._set_state(n, GONE, now))
        self._fire(events)

    # -- inputs ---------------------------------------------------------
    def announce(self, node_id: str, uri: str,
                 memory: Optional[dict] = None,
                 device: Optional[dict] = None,
                 state: Optional[str] = None,
                 topology: Optional[dict] = None):
        now = time.time()
        events = []
        with self.lock:
            n = self.nodes.get(node_id)
            if n is None:
                n = NodeState(node_id, uri)
                self.nodes[node_id] = n
            n.uri = uri
            n.last_announced = now
            n.last_ok = now
            if memory is not None:
                n.memory = memory
            if device is not None:
                n.device = device
            if topology is not None:
                n.topology = topology
            announced = state or ACTIVE
            if announced == "SHUTTING_DOWN":
                # legacy full-shutdown drain maps onto DRAINING: it also
                # refuses new work and finishes running tasks, it just
                # stops the process itself afterwards
                announced = DRAINING
            if announced in (DRAINING, DRAINED):
                events.append(self._set_state(n, announced, now))
            else:
                if n.state == GONE:
                    REGISTRY.counter(
                        "trino_tpu_node_rejoin_total",
                        "GONE nodes that announced again and rejoined",
                    ).inc()
                # new node, flap recovery, rejoin, or a drain cancelled
                # by a worker restart: the worker's word wins
                events.append(self._set_state(n, ACTIVE, now))
        self._fire(events)

    def record_ping(self, node_id: str, ok: bool):
        now = time.time()
        events = []
        with self.lock:
            n = self.nodes.get(node_id)
            if n is not None:
                n.failure_ratio = DECAY * n.failure_ratio + (1 - DECAY) * (
                    0.0 if ok else 1.0
                )
                n.last_ping_ok = ok
                if ok:
                    n.last_ok = now
                    if (
                        n.state == SUSPECT
                        and n.failure_ratio < FAILURE_RATIO_THRESHOLD
                        and now - n.last_announced < ANNOUNCEMENT_TTL
                    ):
                        # flap tolerance: the suspicion window closed
                        # without the node dying — a GC pause, not a death
                        events.append(self._set_state(n, ACTIVE, now))
        self._fire(events)

    # -- views ----------------------------------------------------------
    def alive(self) -> List[Tuple[str, str]]:
        """(node_id, uri) of schedulable workers (lifecycle ACTIVE),
        stable order.  DRAINING/DRAINED/SUSPECT/GONE nodes never appear:
        zero new placements land on a node leaving the cluster."""
        self.tick()
        with self.lock:
            out = [
                (n.node_id, n.uri)
                for n in self.nodes.values()
                if n.state == ACTIVE
            ]
        return sorted(out)

    def await_alive(
        self, min_nodes: int = 1, timeout: float = 10.0
    ) -> List[Tuple[str, str]]:
        """Block until at least ``min_nodes`` workers are ACTIVE or the
        timeout lapses; returns the alive view either way.  A restarted
        coordinator uses this to re-adopt the surviving worker set from
        discovery re-announcements (workers target the fixed coordinator
        URI, so survivors re-announce within one heartbeat interval)
        before dispatching any resumed work."""
        deadline = time.time() + max(float(timeout), 0.0)
        while True:
            alive = self.alive()
            if len(alive) >= int(min_nodes) or time.time() >= deadline:
                return alive
            time.sleep(0.05)

    def lifecycle_states(self) -> Dict[str, str]:
        """node_id -> lifecycle state (the scheduler's exclusion map)."""
        self.tick()
        with self.lock:
            return {n.node_id: n.state for n in self.nodes.values()}

    def gone_uris(self) -> Set[str]:
        """URIs of GONE nodes: FTE fails attempts on these immediately
        instead of burning the poll-failure tolerance."""
        self.tick()
        with self.lock:
            return {n.uri for n in self.nodes.values() if n.state == GONE}

    def nodes_snapshot(self) -> List[dict]:
        """One NODE_FIELDS record per known node (system.runtime.nodes)."""
        self.tick()
        with self.lock:
            return [
                {
                    "nodeId": n.node_id,
                    "uri": n.uri,
                    "state": n.state,
                    "stateSince": n.state_since,
                    "failureRatio": round(n.failure_ratio, 4),
                    "device": n.device,
                    "host": (n.topology or {}).get("host"),
                    "processIndex": (n.topology or {}).get("processIndex"),
                    "localDevices": (n.topology or {}).get("localDevices"),
                }
                for n in self.nodes.values()
            ]

    def device_states(self) -> Dict[str, dict]:
        """node_id -> latest announced device-health snapshot (nodes
        that predate the supervisor, or haven't announced one, are
        absent — callers treat missing as healthy)."""
        with self.lock:
            return {
                n.node_id: n.device
                for n in self.nodes.values()
                if n.device is not None
            }

    def all_nodes(self) -> List[NodeState]:
        """Live view for the heartbeat loop; prunes long-dead entries so
        restart churn (fresh node ids per restart) doesn't accumulate."""
        self.tick()
        now = time.time()
        with self.lock:
            dead = [
                nid
                for nid, n in self.nodes.items()
                if now - n.last_ok > NODE_EXPIRY
            ]
            for nid in dead:
                del self.nodes[nid]
            return list(self.nodes.values())


class HeartbeatFailureDetector:
    """Actively pings every announced worker's /v1/info."""

    def __init__(self, nodes: NodeManager, interval: float = 0.25):
        self.nodes = nodes
        self.interval = interval
        self._stop = threading.Event()
        self.thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> "HeartbeatFailureDetector":
        self.thread.start()
        return self

    def stop(self):
        self._stop.set()

    def _loop(self):
        while not self._stop.is_set():
            for n in self.nodes.all_nodes():
                if n.state == GONE:
                    # a GONE node must re-ANNOUNCE to rejoin; pinging a
                    # corpse (or its reused port) proves nothing
                    continue
                ok = True
                try:
                    with urllib.request.urlopen(
                        f"{n.uri}/v1/info", timeout=1.0
                    ) as resp:
                        ok = resp.status == 200
                except Exception:
                    ok = False
                self.nodes.record_ping(n.node_id, ok)
            self._stop.wait(self.interval)
