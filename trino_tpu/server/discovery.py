"""Discovery + heartbeat failure detection (coordinator side).

Reference parity: airlift discovery announcements maintained by
DiscoveryNodeManager plus active HTTP heartbeats with an exponentially
decayed failure ratio in failuredetector/HeartbeatFailureDetector.java:76
(ping:344, failureRatio:377 vs threshold) — failed nodes are removed from
scheduling until they recover.
"""
from __future__ import annotations

import threading
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

ANNOUNCEMENT_TTL = 5.0
NODE_EXPIRY = 30.0  # forget nodes silent this long (restart churn cleanup)
FAILURE_RATIO_THRESHOLD = 0.5
DECAY = 0.7  # EMA weight of history per heartbeat


class NodeState:
    def __init__(self, node_id: str, uri: str):
        self.node_id = node_id
        self.uri = uri
        self.last_announced = time.time()
        self.failure_ratio = 0.0
        self.last_ping_ok = True
        # latest pool snapshot piggybacked on the announcement (consumed
        # by the coordinator-side ClusterMemoryManager)
        self.memory: Optional[dict] = None
        # latest device-health snapshot (runtime/supervisor.py): node
        # state ACTIVE/DEGRADED/QUARANTINED + per-device strike counts,
        # consumed by scheduler placement and system.runtime.nodes
        self.device: Optional[dict] = None


class NodeManager:
    """Tracks announced workers and their health."""

    def __init__(self):
        self.nodes: Dict[str, NodeState] = {}
        self.lock = threading.Lock()

    def announce(self, node_id: str, uri: str,
                 memory: Optional[dict] = None,
                 device: Optional[dict] = None):
        with self.lock:
            n = self.nodes.get(node_id)
            if n is None:
                n = NodeState(node_id, uri)
                self.nodes[node_id] = n
            n.uri = uri
            n.last_announced = time.time()
            if memory is not None:
                n.memory = memory
            if device is not None:
                n.device = device

    def record_ping(self, node_id: str, ok: bool):
        with self.lock:
            n = self.nodes.get(node_id)
            if n is not None:
                n.failure_ratio = DECAY * n.failure_ratio + (1 - DECAY) * (
                    0.0 if ok else 1.0
                )
                n.last_ping_ok = ok

    def alive(self) -> List[Tuple[str, str]]:
        """(node_id, uri) of schedulable workers, stable order."""
        now = time.time()
        with self.lock:
            out = [
                (n.node_id, n.uri)
                for n in self.nodes.values()
                if now - n.last_announced < ANNOUNCEMENT_TTL
                and n.failure_ratio < FAILURE_RATIO_THRESHOLD
            ]
        return sorted(out)

    def device_states(self) -> Dict[str, dict]:
        """node_id -> latest announced device-health snapshot (nodes
        that predate the supervisor, or haven't announced one, are
        absent — callers treat missing as healthy)."""
        with self.lock:
            return {
                n.node_id: n.device
                for n in self.nodes.values()
                if n.device is not None
            }

    def all_nodes(self) -> List[NodeState]:
        """Live view for the heartbeat loop; prunes long-dead entries so
        restart churn (fresh node ids per restart) doesn't accumulate."""
        now = time.time()
        with self.lock:
            dead = [
                nid
                for nid, n in self.nodes.items()
                if now - n.last_announced > NODE_EXPIRY
            ]
            for nid in dead:
                del self.nodes[nid]
            return list(self.nodes.values())


class HeartbeatFailureDetector:
    """Actively pings every announced worker's /v1/info."""

    def __init__(self, nodes: NodeManager, interval: float = 0.25):
        self.nodes = nodes
        self.interval = interval
        self._stop = threading.Event()
        self.thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> "HeartbeatFailureDetector":
        self.thread.start()
        return self

    def stop(self):
        self._stop.set()

    def _loop(self):
        while not self._stop.is_set():
            for n in self.nodes.all_nodes():
                ok = True
                try:
                    with urllib.request.urlopen(
                        f"{n.uri}/v1/info", timeout=1.0
                    ) as resp:
                        ok = resp.status == 200
                except Exception:
                    ok = False
                self.nodes.record_ping(n.node_id, ok)
            self._stop.wait(self.interval)
