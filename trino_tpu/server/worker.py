"""Worker server: task API, fragment execution, output buffers, announcer.

Reference parity:
  - POST /v1/task/{taskId} create-or-update with fragment+splits+buffers
    (server/TaskResource.java:136 -> SqlTaskManager.updateTask:479)
  - GET  /v1/task/{taskId} task status (TaskState.java:21 states)
  - GET  /v1/task/{taskId}/results/{bufferId}/{token} page pull with
    long-poll + completion marker (TaskResource:  getResults; served from
    OutputBuffer variants — here: per-buffer lists of serialized frames)
  - DELETE /v1/task/{taskId} abort
  - worker announcement to the coordinator's discovery endpoint
    (airlift discovery "trino" service announcements, DiscoveryNodeManager)
  - fault injection (execution/FailureInjector.java:39,61 wired into
    TaskResource.injectFailure:183): the seeded FaultInjector
    (utils/faults.py) drives every chaos site — task_run/task_stall at
    task start, exchange_fetch/spool_read inside the exchange client,
    spool_write_corrupt on the FTE spool write, heartbeat in the
    announcer.  Tasks carrying a ``fault_injection`` property share one
    injector per spec (per worker), so nth-call rules count across a
    query; POST /v1/task/{taskId}/fail keeps its taskId-addressed
    one-shot semantics through the same harness.

Execution: each task runs on its own thread; the fragment compiles/executes
as one XLA program (exec/fragment_exec.py); output pages are hash/broadcast
partitioned into buffers (exec/partitioner.py) and served as binary frames.
"""
from __future__ import annotations

import json
import os
import threading
import time
import traceback
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from ..catalog import CatalogManager
from ..exec.exchange_client import ExchangeClient, RemoteTaskError
from ..exec.fragment_exec import FragmentExecutor
from ..exec.partitioner import (
    chunk_page,
    partition_page,
    partition_page_round_robin,
)
from ..page import Page
from ..serde import decode_value, plan_from_json, serialize_page
from ..spi import Split
from ..utils.faults import FaultInjector
from ..utils.metrics import REGISTRY
from ..utils.tracing import TRACER

TASK_STATES = (
    "PLANNED", "RUNNING", "FLUSHING", "FINISHED", "CANCELED", "ABORTED",
    "FAILED",
)


class TaskExecution:
    """One task: fragment + splits + output buffers (SqlTask analog)."""

    def __init__(self, task_id: str, doc: dict):
        self.task_id = task_id
        self.doc = doc
        self.state = "PLANNED"
        self.error: Optional[str] = None
        # buffer id -> list of serialized page frames
        self.buffers: Dict[int, List[bytes]] = {}
        self.complete = False
        self.lock = threading.Lock()
        self.created = time.time()
        self.stats: Dict[str, int] = {}


class TaskManager:
    """Executes tasks against this worker's catalogs (SqlTaskManager)."""

    def __init__(
        self,
        catalogs: CatalogManager,
        memory_manager=None,
        supervisor=None,
    ):
        self.catalogs = catalogs
        self.memory_manager = memory_manager
        # node-level device supervisor: every fragment on this worker
        # dispatches through it, so quarantine outlives any one task
        self.supervisor = supervisor
        self.tasks: Dict[str, TaskExecution] = {}
        # set by WorkerServer once its listen port is bound: exchange
        # fetches targeting any other URI count as cross-host traffic
        self.own_uri = ""
        # cumulative placements: /v1/task DELETEs pop finished tasks out
        # of ``tasks``, so "did this node ever get work" needs a counter
        # that survives cleanup (drain + late-joiner assertions key on it)
        self.tasks_created = 0
        self.lock = threading.Lock()
        # worker-level injector: serves the /v1/task/{id}/fail endpoint's
        # taskId-addressed modes and operator-configured sites (heartbeat)
        self.fault_injector = FaultInjector()
        # per-spec injectors for tasks shipping a fault_injection
        # property: all tasks of a query share the spec, hence the
        # injector, hence one deterministic call counter per worker
        self._injectors: Dict[str, FaultInjector] = {}
        # bounded ring of completed-task OperatorStats summaries: rides
        # the announce loop to the coordinator's live straggler detector
        from collections import deque

        self.recent_opstats: deque = deque(maxlen=64)

    def create_or_update(self, task_id: str, doc: dict) -> TaskExecution:
        with self.lock:
            t = self.tasks.get(task_id)
            if t is not None:
                return t  # idempotent re-POST (HttpRemoteTask retries)
            t = TaskExecution(task_id, doc)
            self.tasks[task_id] = t
            self.tasks_created += 1
        threading.Thread(target=self._run, args=(t,), daemon=True).start()
        return t

    def inject_failure(self, task_id: str, mode: str):
        self.fault_injector.set_task_mode(task_id, mode)

    def _injector_for(self, spec) -> FaultInjector:
        if not spec:
            return self.fault_injector
        key = spec if isinstance(spec, str) else json.dumps(
            spec, sort_keys=True
        )
        with self.lock:
            inj = self._injectors.get(key)
            if inj is None:
                inj = self._injectors[key] = FaultInjector.from_spec(spec)
            return inj

    def abort(self, task_id: str):
        t = self.tasks.get(task_id)
        if t:
            with t.lock:
                if t.state not in ("FINISHED", "FAILED"):
                    t.state = "ABORTED"
                    t.error = "task aborted"

    def delete(self, task_id: str):
        self.abort(task_id)
        with self.lock:
            self.tasks.pop(task_id, None)

    def _make_executor(self, plan, config, splits_by_scan, remote_pages,
                       dfs):
        """Pick the fragment executor.  Cross-host mode: when the
        session asks for it (``cross_host_mesh``) and this worker owns
        more than one local device, eligible fragments run through the
        mesh slice executor — a per-host shard_map over the local device
        slice whose repartition/partial-aggregate merges then travel the
        network exchange instead of in-XLA collectives.  Ineligible
        fragments (final-step merges, anything past the slice grammar)
        take the scalar FragmentExecutor on the same worker, so a mixed
        plan is still one query."""
        want = config.get("cross_host_mesh", False)
        if isinstance(want, str):
            want = want.strip().lower() not in ("false", "0", "no", "off", "")
        if want:
            from ..parallel.mesh_executor import (
                CrossHostFragmentExecutor,
                slice_eligible,
            )
            import jax

            if len(jax.devices()) > 1 and slice_eligible(plan):
                return CrossHostFragmentExecutor(
                    self.catalogs, config, splits_by_scan, remote_pages,
                    dfs,
                )
        return FragmentExecutor(
            self.catalogs, config, splits_by_scan, remote_pages, dfs
        )

    # ------------------------------------------------------------------
    def _run(self, t: TaskExecution):
        with t.lock:
            if t.state != "PLANNED":
                return
            t.state = "RUNNING"
        REGISTRY.counter(
            "trino_tpu_task_created_total", "Tasks accepted by this worker"
        ).inc()
        # the coordinator's traceparent rides the task doc: this thread
        # has no local span stack, so the task span joins the query trace
        # through the W3C context (the Dapper cross-process edge)
        traceparent = t.doc.get("traceparent")
        try:
            with TRACER.span(
                "task", traceparent=traceparent, task_id=t.task_id
            ) as task_span:
                self._run_inner(t, task_span)
        finally:
            # export (when an exporter is attached) without waiting for the
            # coordinator: worker spans must survive task teardown
            TRACER.flush()

    def _run_inner(self, t: TaskExecution, task_span):
        try:
            doc = t.doc
            config = dict(doc.get("properties") or {})
            inj = self._injector_for(config.get("fault_injection"))
            mode = self.fault_injector.take_task_mode(t.task_id)
            if mode is not None:
                if mode.startswith("STALL"):
                    # straggler injection (FailureInjector TASK_MANAGEMENT
                    # _TIMEOUT analog): sleep, then run normally — the
                    # speculative scheduler should win with a backup attempt
                    import time as _time

                    _time.sleep(float(mode.split(":", 1)[1]))
                else:
                    raise RuntimeError(f"injected task failure ({mode})")
            if inj.fires("task_run", key=t.task_id):
                raise RuntimeError(
                    "injected task failure "
                    f"(fault_injection site task_run, task {t.task_id})"
                )
            inj.stall("task_stall", key=t.task_id)
            # worker-LEVEL chaos (constructor fault_injection, not the
            # session-shipped spec): node-churn sites that only make
            # sense scoped to one victim process
            winj = self.fault_injector
            if winj.fires("worker_death", key=t.task_id):
                # seeded kill -9 analog: vanish mid-task with no
                # cleanup, no spool flush, no FAILED state — the
                # coordinator must discover the death via heartbeats
                os._exit(137)
            winj.stall("task_stall", key=t.task_id)
            plan = plan_from_json(doc["fragment"])
            splits_by_scan: Dict[int, List[Split]] = {}
            for k, sps in (doc.get("splits") or {}).items():
                splits_by_scan[int(k)] = [decode_value(s) for s in sps]
            sources = doc.get("sources") or {}
            client = ExchangeClient(
                retries=config.get("exchange_retry_attempts"),
                retry_budget_s=config.get("exchange_retry_budget_s"),
                fault_injector=inj if inj.enabled() else None,
                traceparent=task_span.traceparent,
                own_uri=self.own_uri,
            )
            remote_pages = client.fetch_sources(
                {int(fid): list(locs) for fid, locs in sources.items()}
            )
            with t.lock:
                if t.state == "ABORTED":
                    return
            dfs = None
            if config.get("dynamic_filtering", True):
                from ..exec.dynamic_filter import collect_dynamic_filters

                dfs = collect_dynamic_filters(plan, remote_pages)
            if self.memory_manager is not None:
                # node-level arbitration: fragments of every query on
                # this worker reserve host + HBM bytes from one manager,
                # tagged by query id (task ids are {query}.{frag}.{i})
                config["memory_manager"] = self.memory_manager
                config["query_id"] = t.task_id.rsplit(".", 2)[0]
                if inj.enabled():
                    self.memory_manager.fault_injector = inj
            config["task_id"] = t.task_id
            if self.supervisor is not None:
                # same sharing rule as memory: one supervisor per node,
                # configured by whichever task runs next (session props
                # are uniform across a query's tasks)
                self.supervisor.configure(config)
                fb = config.get("device_cpu_fallback", True)
                if isinstance(fb, str):
                    fb = fb.strip().lower() not in (
                        "false", "0", "no", "off", ""
                    )
                self.supervisor.cpu_fallback_enabled = bool(fb)
                if inj.enabled():
                    self.supervisor.fault_injector = inj
                config["device_supervisor"] = self.supervisor
            if config.get("operator_stats"):
                # per-operator timeline: forces the eager (non-jitted)
                # path so _TraceCtx can bracket every operator visit
                config["collect_node_stats"] = True
            ex = self._make_executor(
                plan, config, splits_by_scan, remote_pages, dfs
            )
            # blocked-on-exchange: the wall this task spent pulling its
            # remote source pages before any operator could run
            ex.blocked_exchange_s = float(
                getattr(client, "last_fetch_wall_s", 0.0)
            )
            import time as _time

            _t0 = _time.time()
            with TRACER.span("fragment_execute", task_id=t.task_id):
                page = ex.execute(plan)
            wall_s = _time.time() - _t0
            from ..obs import opstats as _opstats

            frames = (
                _opstats.frames_from_plan(
                    plan, ex.node_stats,
                    blocked_memory_s=ex.blocked_memory_s,
                    blocked_exchange_s=ex.blocked_exchange_s,
                )
                if ex.node_stats else []
            )
            op_rollup = _opstats.task_rollup(
                frames, wall_s=wall_s,
                blocked_memory_s=ex.blocked_memory_s,
                blocked_exchange_s=ex.blocked_exchange_s,
            )
            op_rollup["outputRows"] = page.count
            t.stats = {
                "dynamicFilterRowsPruned": ex.df_rows_pruned,
                "scanBytes": ex.scan_bytes,
                "outputRows": page.count,
                "wallMillis": int(wall_s * 1000),
                # per-kernel compile wall / recompiles / padding — rides
                # the existing stats rollup back to the coordinator
                "kernelProfile": getattr(ex, "kernel_profile", None),
                # pipeline -> task OperatorStats rollup (frames only when
                # operator_stats forced the instrumented eager path)
                "operatorStats": op_rollup,
            }
            # stage-rollup summaries piggyback on the next announcement
            # round (the coordinator's live straggler detector input)
            self.recent_opstats.append({
                "taskId": t.task_id,
                "wallS": wall_s,
                "outputRows": int(page.count),
                "blockedExchangeS": ex.blocked_exchange_s,
                "blockedMemoryS": ex.blocked_memory_s,
            })
            out = doc.get("output") or {}
            part = out.get("partitioning", "single")
            nbuffers = int(out.get("nbuffers", 1))
            keys = list(out.get("keys") or [])
            if part == "hash" and nbuffers > 1:
                parts = partition_page(page, keys, nbuffers)
            elif part == "arbitrary" and nbuffers > 1:
                # round-robin redistribution (RandomExchanger /
                # ArbitraryOutputBuffer): no key affinity, pure balance
                parts = partition_page_round_robin(page, nbuffers)
            else:
                # single and broadcast: everything in buffer 0 (broadcast
                # consumers all read buffer 0 — BroadcastOutputBuffer)
                parts = [page]
            with t.lock:
                if t.state == "ABORTED":
                    return
                t.state = "FLUSHING"
                for bid, p in enumerate(parts):
                    t.buffers[bid] = [
                        serialize_page(c) for c in chunk_page(p)
                    ]
                for bid in range(len(parts), nbuffers):
                    t.buffers[bid] = []
            spool_path = doc.get("spool_path")
            if spool_path:
                # FTE mode: durable spool instead of in-memory serving
                # (spi/exchange ExchangeSink; survives this worker's death).
                # Consumers read only the spool, so drop the RAM copy.
                from ..exchange.filesystem import SpoolHandle

                with t.lock:
                    bufs = t.buffers
                if inj.enabled():
                    # chaos site: flip a bit in a spool-bound frame AFTER
                    # serialization — the commit still happens, so the
                    # corruption is only detectable by the read-side CRC
                    bufs = {
                        bid: [
                            inj.corrupt(
                                "spool_write_corrupt", fr, key=t.task_id
                            )
                            for fr in frames
                        ]
                        for bid, frames in bufs.items()
                    }
                with TRACER.span("spool_write", path=spool_path):
                    SpoolHandle(spool_path).write_buffers(bufs)
                with t.lock:
                    t.buffers = {}
            with t.lock:
                if t.state == "ABORTED":
                    return
                t.complete = True
                t.state = "FINISHED"
        except Exception as e:  # propagated to consumers + coordinator
            REGISTRY.counter(
                "trino_tpu_task_failed_total", "Tasks that ended FAILED"
            ).inc()
            with t.lock:
                if t.state != "ABORTED":
                    t.state = "FAILED"
                    if isinstance(e, RemoteTaskError):
                        t.error = str(e)
                    else:
                        t.error = f"{type(e).__name__}: {e}"
                    t.traceback = traceback.format_exc()


class _WorkerHandler(BaseHTTPRequestHandler):
    worker: "WorkerServer" = None

    def log_message(self, fmt, *args):
        pass

    def _json(self, code: int, doc: dict, headers: Optional[dict] = None):
        body = json.dumps(doc).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _binary(self, code: int, body: bytes, headers: dict):
        self.send_response(code)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(len(body)))
        for k, v in headers.items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    # ------------------------------------------------------------------
    def do_POST(self):
        parts = self.path.strip("/").split("/")
        tm = self.worker.task_manager
        if len(parts) == 3 and parts[:2] == ["v1", "task"]:
            if self.worker.state != "ACTIVE":
                # drain the request body first or the connection wedges
                self.rfile.read(int(self.headers.get("Content-Length", 0)))
                self._json(
                    409,
                    {"error": "worker is not ACTIVE "
                              f"(state {self.worker.state})"},
                )
                return
            n = int(self.headers.get("Content-Length", 0))
            doc = json.loads(self.rfile.read(n))
            # W3C trace context arrives as an HTTP header (scheduler
            # dispatch); stash it on the doc for the task thread
            tp = self.headers.get("traceparent")
            if tp and "traceparent" not in doc:
                doc["traceparent"] = tp
            t = tm.create_or_update(parts[2], doc)
            self._json(200, {"taskId": t.task_id, "state": t.state})
            return
        if parts == ["v1", "memory", "kill"]:
            # coordinator low-memory-killer verdict: wake this node's
            # blocked reservations of the victim with QueryKilledError
            n = int(self.headers.get("Content-Length", 0))
            doc = json.loads(self.rfile.read(n) or b"{}")
            self.worker.memory_manager.kill(
                doc.get("queryId", ""), doc.get("reason", "killed")
            )
            self._json(200, {"killed": doc.get("queryId", "")})
            return
        if (
            len(parts) == 4
            and parts[:2] == ["v1", "task"]
            and parts[3] == "fail"
        ):
            n = int(self.headers.get("Content-Length", 0))
            doc = json.loads(self.rfile.read(n) or b"{}")
            tm.inject_failure(parts[2], doc.get("mode", "TASK_FAILURE"))
            self._json(200, {"injected": parts[2]})
            return
        self._json(404, {"error": "not found"})

    def do_PUT(self):
        if self.path == "/v1/info/state":
            n = int(self.headers.get("Content-Length", 0))
            want = json.loads(self.rfile.read(n) or b'""')
            if want == "DRAINING":
                # graceful decommission: refuse new tasks, finish running
                # ones (spools flush before a task FINISHES), then
                # announce DRAINED and stay up until terminated
                self.worker.start_drain()
                self._json(200, {"state": self.worker.state})
                return
            if want != "SHUTTING_DOWN":
                self._json(400, {"error": f"unsupported state {want}"})
                return
            # respond before initiating shutdown: the drain may stop the
            # HTTP server before this response flushes otherwise
            self.worker.state = "SHUTTING_DOWN"
            self._json(200, {"state": self.worker.state})
            self.wfile.flush()
            self.worker.start_graceful_shutdown()
            return
        self._json(404, {"error": "not found"})

    def do_GET(self):
        parts = self.path.strip("/").split("/")
        w = self.worker
        if self.path == "/metrics":
            body = REGISTRY.render_prometheus().encode()
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if self.path == "/v1/info":
            device = w.supervisor.snapshot()
            state = w.state
            if state == "ACTIVE" and device["state"] != "ACTIVE":
                # a sick device downgrades the advertised node state
                # (DEGRADED keeps serving via CPU; QUARANTINED refuses)
                state = device["state"]
            self._json(200, {
                "nodeId": w.node_id,
                "nodeVersion": {"version": "trino-tpu 0.1"},
                "environment": "tpu",
                "coordinator": False,
                "state": state,
                "device": device,
                "topology": w.topology,
                "uptime": f"{time.time() - w.started:.0f}s",
            })
            return
        if self.path == "/v1/memory":
            self._json(200, w.memory_manager.snapshot())
            return
        if self.path == "/v1/status":
            self._json(200, {
                "nodeId": w.node_id,
                "state": w.state,
                "activeTasks": sum(
                    1
                    for t in w.task_manager.tasks.values()
                    if t.state in ("PLANNED", "RUNNING", "FLUSHING")
                ),
                "totalTasks": len(w.task_manager.tasks),
                "lifetimeTasks": w.task_manager.tasks_created,
            })
            return
        if len(parts) == 3 and parts[:2] == ["v1", "task"]:
            t = w.task_manager.tasks.get(parts[2])
            if t is None:
                self._json(404, {"error": "no such task"})
                return
            self._json(200, {
                "taskId": t.task_id,
                "state": t.state,
                "error": t.error,
                "stats": t.stats,
            })
            return
        if len(parts) == 6 and parts[:2] == ["v1", "task"] and parts[3] == "results":
            self._serve_results(parts[2], int(parts[4]), int(parts[5]))
            return
        self._json(404, {"error": "not found"})

    def _serve_results(self, task_id: str, buffer_id: int, token: int):
        """Long-poll page pull (HttpPageBufferClient GET)."""
        w = self.worker
        deadline = time.time() + 1.0
        while True:
            t = w.task_manager.tasks.get(task_id)
            if t is None:
                self._json(404, {"error": "no such task"})
                return
            with t.lock:
                state = t.state
                if state == "FAILED" or state == "ABORTED":
                    err = (t.error or "task failed").encode()
                    self._binary(410, err, {"X-Task-State": state})
                    return
                if t.complete:
                    frames = t.buffers.get(buffer_id, [])
                    if token < len(frames):
                        body = frames[token]
                        last = token + 1 >= len(frames)
                        self._binary(200, body, {
                            "X-Task-State": state,
                            "X-Next-Token": str(token + 1),
                            "X-Buffer-Complete": "true" if last else "false",
                        })
                    else:
                        self._binary(200, b"", {
                            "X-Task-State": state,
                            "X-Buffer-Complete": "true",
                        })
                    return
            if time.time() > deadline:
                self._binary(204, b"", {"X-Task-State": state})
                return
            time.sleep(0.01)

    def do_DELETE(self):
        parts = self.path.strip("/").split("/")
        if len(parts) == 3 and parts[:2] == ["v1", "task"]:
            self.worker.task_manager.delete(parts[2])
            self._json(200, {})
            return
        self._json(404, {"error": "not found"})


class WorkerServer:
    """One worker node (TestingTrinoServer worker-role analog)."""

    def __init__(
        self,
        catalogs: CatalogManager,
        coordinator_uri: Optional[str] = None,
        port: int = 0,
        announce_interval: float = 0.25,
        fault_injection=None,
        memory_bytes: Optional[int] = None,
        device_memory_bytes: Optional[int] = None,
        host: Optional[str] = None,
        process_index: Optional[int] = None,
    ):
        from ..memory import LocalMemoryManager
        from ..memory.pools import detect_device_bytes

        self.node_id = f"worker-{uuid.uuid4().hex[:8]}"
        # multi-host identity is OPT-IN: only a worker explicitly placed
        # in a cluster topology (host id / process index from the
        # bootstrap harness) announces itself as a host-sized capacity
        # unit; plain workers never trip the HOST_GONE machinery
        self.topology: Optional[dict] = None
        if host is not None or process_index is not None:
            from ..distributed import local_topology

            self.topology = local_topology(
                host=host, process_index=process_index
            )
        self.memory_manager = LocalMemoryManager(
            memory_bytes if memory_bytes is not None else (8 << 30),
            device_bytes=(
                device_memory_bytes
                if device_memory_bytes is not None
                else detect_device_bytes()
            ),
            node_id=self.node_id,
        )
        from ..runtime import DeviceSupervisor

        self.supervisor = DeviceSupervisor(node_id=self.node_id)
        self.task_manager = TaskManager(
            catalogs,
            memory_manager=self.memory_manager,
            supervisor=self.supervisor,
        )
        if fault_injection:
            # operator-configured chaos (heartbeat drops etc.) rides the
            # worker-level injector, alongside the /fail endpoint modes
            self.task_manager.fault_injector = FaultInjector.from_spec(
                fault_injection
            )
        self.started = time.time()
        handler = type("Handler", (_WorkerHandler,), {"worker": self})
        # match the coordinator's raised listen backlog: a task fan-out
        # from many concurrent queries connects in bursts
        server_cls = type(
            "WorkerHTTPServer", (ThreadingHTTPServer,),
            {"request_queue_size": 128},
        )
        self.httpd = server_cls(("127.0.0.1", port), handler)
        self.port = self.httpd.server_address[1]
        self.task_manager.own_uri = self.uri
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self.coordinator_uri = coordinator_uri
        self.announce_interval = announce_interval
        self._stop = threading.Event()
        self.announcer = threading.Thread(target=self._announce_loop, daemon=True)
        # lifecycle (NodeState analog): ACTIVE -> DRAINING -> DRAINED
        # (graceful decommission, keeps announcing) or ACTIVE ->
        # SHUTTING_DOWN (GracefulShutdownHandler: drain then stop)
        self.state = "ACTIVE"

    @property
    def uri(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def start(self) -> "WorkerServer":
        self.thread.start()
        if self.coordinator_uri:
            self.announcer.start()
        return self

    def stop(self):
        self._stop.set()
        self.httpd.shutdown()

    def start_drain(self):
        """PUT /v1/info/state DRAINING (NodeState.DRAINING analog):
        refuse new tasks, keep ANNOUNCING (unlike SHUTTING_DOWN — the
        coordinator must watch this node walk DRAINING -> DRAINED), wait
        for running tasks to finish (each commits/flushes its spool
        before reaching FINISHED), then advertise DRAINED.  The process
        stays up serving spools/results until the operator terminates
        it; the coordinator escalates the ensuing silence to GONE."""
        if self.state != "ACTIVE":
            return
        self.state = "DRAINING"

        def drain():
            deadline = time.time() + 30.0
            while time.time() < deadline:
                active = sum(
                    1
                    for t in self.task_manager.tasks.values()
                    if t.state in ("PLANNED", "RUNNING", "FLUSHING")
                )
                if active == 0:
                    break
                time.sleep(0.05)
            # telemetry durability barrier: DRAINED is a promise that
            # this node's spans, dispatch ring, and incident journal are
            # on disk — the operator terminates the process right after
            self._flush_telemetry()
            self.state = "DRAINED"

        threading.Thread(target=drain, daemon=True).start()

    def _flush_telemetry(self):
        """Flush every telemetry sink before advertising DRAINED (or
        shutting the listener down): exporter-buffered spans, the
        flight-recorder mmap ring, and the incident-journal segments."""
        try:
            TRACER.flush()
        except Exception:  # noqa: BLE001 — flush must not block a drain
            pass
        try:
            rec = getattr(self.supervisor, "flight_recorder", None)
            if rec is not None:
                rec.sync()
        except Exception:  # noqa: BLE001
            pass
        try:
            from ..obs import journal

            journal.sync()
        except Exception:  # noqa: BLE001
            pass
        try:
            from ..obs import compile_observatory

            compile_observatory.sync()
        except Exception:  # noqa: BLE001
            pass

    def start_graceful_shutdown(self):
        """PUT /v1/info/state SHUTTING_DOWN: drain then stop (the
        reference's GracefulShutdownHandler)."""
        self.state = "SHUTTING_DOWN"
        self._stop.set()  # stop announcing: scheduler drops this node

        def drain():
            time.sleep(0.2)  # let in-flight responses flush
            deadline = time.time() + 30.0
            while time.time() < deadline:
                active = sum(
                    1
                    for t in self.task_manager.tasks.values()
                    if t.state in ("PLANNED", "RUNNING", "FLUSHING")
                )
                if active == 0:
                    break
                time.sleep(0.05)
            self._flush_telemetry()
            self.httpd.shutdown()

        threading.Thread(target=drain, daemon=True).start()

    # ------------------------------------------------------------------
    def _compile_snapshot(self):
        """This node's compile-observatory piggyback for one
        announcement round (None on any failure: announcing must never
        die on a telemetry bug)."""
        try:
            from ..obs import compile_observatory

            return compile_observatory.get_observatory().announce_snapshot()
        except Exception:  # noqa: BLE001
            return None

    def _announce_loop(self):
        while not self._stop.is_set():
            winj = self.task_manager.fault_injector
            if winj.fires("heartbeat", key=self.node_id) or winj.fires(
                "announce_drop", key=self.node_id
            ):
                # injected missed announcement: the coordinator's
                # failure detector sees this node go silent (node-churn
                # chaos uses announce_drop to model loss WITHOUT death —
                # pings keep succeeding, so SUSPECT must recover)
                self._stop.wait(self.announce_interval)
                continue
            try:
                # quarantined devices re-probe on the announcer cadence:
                # recovery is discovered even while no tasks arrive
                self.supervisor.maybe_probe()
                # rebuilt every round: the announcement piggybacks this
                # node's live pool snapshot for the coordinator-side
                # ClusterMemoryManager (heartbeat memory view) and the
                # device-health snapshot for scheduler routing
                body = json.dumps({
                    "nodeId": self.node_id,
                    "uri": self.uri,
                    # lifecycle announcements drive the coordinator's
                    # node state machine (DRAINING/DRAINED visibility)
                    "state": self.state,
                    "memory": self.memory_manager.snapshot(),
                    "device": self.supervisor.snapshot(),
                    # completed-task wall/row rollups for the
                    # coordinator's live straggler detector
                    "opstats": list(self.task_manager.recent_opstats),
                    # compile-observatory piggyback: per-cause counts,
                    # shape-census sketch, and new ledger events since
                    # the last round (coordinator merges engine-wide)
                    "compiles": self._compile_snapshot(),
                    # multi-host slice identity (None for plain workers)
                    "topology": self.topology,
                }).encode()
                req = urllib.request.Request(
                    f"{self.coordinator_uri}/v1/announcement",
                    data=body,
                    headers={"Content-Type": "application/json"},
                )
                urllib.request.urlopen(req, timeout=2.0).read()
            except Exception:
                pass  # coordinator not up yet / transient
            self._stop.wait(self.announce_interval)
