"""Fault-tolerant (task-retry) query scheduler over the spooled exchange.

Reference parity: execution/scheduler/faulttolerant/
EventDrivenFaultTolerantQueryScheduler.java:199 — stage-by-stage execution
where every stage's output is spooled to durable storage (Exchange SPI /
trino-exchange-filesystem), failed task attempts are re-scheduled on other
alive workers, and consumers only ever read the spool paths of attempts the
scheduler committed (structural dedup of duplicate attempt output — the
DeduplicatingDirectExchangeBuffer / ExchangeSourceOutputSelector role).

Differences from the pipelined scheduler (scheduler.py): stages run with a
barrier between producer and consumer (no streaming overlap), so a worker
death or injected task failure only costs the retried task, never the query
(retry-policy=TASK).  Worker loss between stages is tolerated by re-picking
placement from the currently-alive node set per attempt.
"""
from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
import uuid
from typing import Dict, List, Optional, Tuple

from ..catalog import CatalogManager
from ..exchange.filesystem import FileSystemExchangeManager, read_spool_pages
from ..exec.partitioner import concat_pages
from ..page import Page
from ..plan import nodes as P
from ..plan.fragment import HASH, SINGLE, SOURCE, PlanFragment, fragment_plan
from ..serde import encode_value, plan_to_json
from .scheduler import (
    SchedulerError,
    _post_json,
    assign_splits,
    source_buffer_index,
)

MAX_ATTEMPTS = 4
POLL_INTERVAL = 0.02
TASK_TIMEOUT = 300.0
POLL_FAILURE_TOLERANCE = 3  # consecutive status-poll errors = worker lost
# speculative execution (EventDrivenFaultTolerantQueryScheduler SPECULATIVE
# class): a task running longer than SPECULATION_FACTOR x the median
# completed sibling (and at least SPECULATION_MIN_S) gets a backup attempt
# on another worker; first committed attempt wins
SPECULATION_FACTOR = 2.0
SPECULATION_MIN_S = 0.75
# failed attempts retry with exponentially grown memory
# (ExponentialGrowthPartitionMemoryEstimator)
MEMORY_GROWTH_FACTOR = 2


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    return s[len(s) // 2]


class FaultTolerantScheduler:
    """retry-policy=TASK execution of one query."""

    def __init__(
        self,
        catalogs: CatalogManager,
        node_manager,
        exchange: Optional[FileSystemExchangeManager] = None,
        properties: Optional[dict] = None,
    ):
        self.catalogs = catalogs
        self.node_manager = node_manager
        self.exchange = exchange or FileSystemExchangeManager()
        self.properties = properties or {}

    # ------------------------------------------------------------------
    def run(self, plan: P.Output, query_id: Optional[str] = None) -> Page:
        query_id = query_id or f"q_{uuid.uuid4().hex[:12]}"
        fragments = fragment_plan(plan)
        by_id = {f.id: f for f in fragments}
        consumer: Dict[int, int] = {}
        for f in fragments:
            for sf in f.source_fragments:
                consumer[sf] = f.id

        # stage width is fixed up-front (task count = buffer addressing),
        # but *placement* is re-chosen per attempt from the alive set
        width: Dict[int, int] = {}
        cluster = self.node_manager.alive()
        if not cluster:
            raise SchedulerError("NO_NODES_AVAILABLE: no alive workers")
        for f in fragments:
            width[f.id] = len(cluster) if f.partitioning in (SOURCE, HASH) else 1

        # committed spool dirs: fragment -> [task_index -> SpoolHandle path]
        committed: Dict[int, List[str]] = {}
        self._created_tasks: List[Tuple[str, str]] = []  # (uri, task_id)
        try:
            order = sorted(
                (f for f in fragments if f.id != 0), key=lambda f: f.id
            ) + [by_id[0]]
            for f in order:
                committed[f.id] = self._run_stage(
                    query_id, f, width, committed, by_id, consumer
                )
            from ..exchange.filesystem import SpoolHandle

            root_pages = read_spool_pages(
                SpoolHandle(committed[0][0]).buffer_file(0)
            )
            if not root_pages:
                raise SchedulerError("root stage produced no pages")
            return concat_pages(root_pages)
        finally:
            # abort + delete every attempt on the workers (frees task state;
            # abandoned attempts stop before re-creating spool dirs)
            for uri, task_id in self._created_tasks:
                try:
                    req = urllib.request.Request(
                        f"{uri}/v1/task/{task_id}", method="DELETE"
                    )
                    urllib.request.urlopen(req, timeout=5.0).read()
                except Exception:
                    pass
            self.exchange.cleanup_query(query_id)

    # ------------------------------------------------------------------
    def _sources_for(
        self,
        f: PlanFragment,
        task_index: int,
        committed: Dict[int, List[str]],
        by_id: Dict[int, PlanFragment],
    ) -> Dict[str, list]:
        """Spool-file locations of the committed upstream attempts (same
        buffer routing as the pipelined scheduler, different location shape)."""
        from ..exchange.filesystem import SpoolHandle

        sources: Dict[str, list] = {}
        for sf in f.source_fragments:
            src = by_id[sf]
            buf = source_buffer_index(src, task_index)
            sources[str(sf)] = [
                {"path": SpoolHandle(path).buffer_file(buf)}
                for path in committed[sf]
            ]
        return sources

    def _run_stage(
        self,
        query_id: str,
        f: PlanFragment,
        width: Dict[int, int],
        committed: Dict[int, List[str]],
        by_id: Dict[int, PlanFragment],
        consumer: Dict[int, int],
    ) -> List[str]:
        ntasks = width[f.id]
        out_buffers = (
            width[consumer[f.id]] if f.output_partitioning == HASH else 1
        )
        per_task_splits = assign_splits(self.catalogs, f, ntasks)
        frag_json = plan_to_json(f.root)
        from concurrent.futures import ThreadPoolExecutor

        sibling_times: List[float] = []  # completed task durations (stage)
        # backups may double the concurrent attempts of a stage
        with ThreadPoolExecutor(max_workers=max(2 * ntasks, 1)) as pool:
            futures = [
                pool.submit(
                    self._run_task_with_retries,
                    query_id, f, i, frag_json, per_task_splits[i],
                    out_buffers, committed, by_id, sibling_times, pool,
                )
                for i in range(ntasks)
            ]
            return [fut.result() for fut in futures]

    def _start_attempt(
        self, query_id, f, task_index, attempt, frag_json, splits,
        out_buffers, committed, by_id, worker_offset=0,
    ):
        """POST one attempt; returns (uri, task_id, sink)."""
        workers = self.node_manager.alive()
        if not workers:
            raise SchedulerError("NO_NODES_AVAILABLE during retry")
        node_id, uri = workers[
            (task_index + attempt + worker_offset) % len(workers)
        ]
        sink = self.exchange.sink(query_id, f.id, task_index, attempt)
        task_id = f"{query_id}.{f.id}.{task_index}.{attempt}"
        props = dict(self.properties)
        base_mem = props.get("query_max_memory_bytes")
        if base_mem and attempt:
            # re-try with exponentially grown memory
            # (ExponentialGrowthPartitionMemoryEstimator)
            props["query_max_memory_bytes"] = int(
                base_mem * (MEMORY_GROWTH_FACTOR ** attempt)
            )
        doc = {
            "fragment": frag_json,
            "splits": {
                str(k): [encode_value(s) for s in v]
                for k, v in splits.items()
            },
            "output": {
                "partitioning": f.output_partitioning,
                "keys": list(f.output_keys),
                "nbuffers": out_buffers,
            },
            "sources": self._sources_for(f, task_index, committed, by_id),
            "properties": props,
            "spool_path": sink.path,
        }
        _post_json(f"{uri}/v1/task/{task_id}", doc)
        self._created_tasks.append((uri, task_id))
        return uri, task_id, sink

    def _run_task_with_retries(
        self, query_id, f, task_index, frag_json, splits, out_buffers,
        committed, by_id, sibling_times=None, pool=None,
    ) -> str:
        last_error = None
        speculate = bool(self.properties.get("speculative_execution", True))
        attempt = 0
        while attempt < MAX_ATTEMPTS:
            try:
                uri, task_id, sink = self._start_attempt(
                    query_id, f, task_index, attempt, frag_json, splits,
                    out_buffers, committed, by_id,
                )
            except SchedulerError:
                raise
            except Exception as e:
                last_error = e
                attempt += 1
                continue
            backup = None  # (future, attempt_no)
            t0 = time.time()
            try:
                while True:
                    state = self._poll_task(uri, task_id)
                    if state == "FINISHED":
                        break
                    if state is not None:
                        raise SchedulerError(f"task {task_id} {state}")
                    if time.time() - t0 > TASK_TIMEOUT:
                        raise SchedulerError(f"task {task_id} timed out")
                    # straggler? launch ONE speculative backup attempt on
                    # another worker; first committed attempt wins
                    if (
                        speculate
                        and backup is None
                        and pool is not None
                        and attempt + 1 < MAX_ATTEMPTS
                        and sibling_times
                        and time.time() - t0
                        > max(
                            SPECULATION_MIN_S,
                            SPECULATION_FACTOR
                            * _median(sibling_times),
                        )
                    ):
                        backup = self._launch_backup(
                            pool, query_id, f, task_index, attempt + 1,
                            frag_json, splits, out_buffers, committed,
                            by_id,
                        )
                    if backup is not None and backup[0].done():
                        bpath = backup[0].result()
                        if bpath is not None:
                            if sibling_times is not None:
                                sibling_times.append(time.time() - t0)
                            return bpath
                        backup = None  # backup failed; keep waiting
                    time.sleep(POLL_INTERVAL)
                if not sink.committed:
                    raise SchedulerError(
                        f"task {task_id} finished without committing spool"
                    )
                if sibling_times is not None:
                    sibling_times.append(time.time() - t0)
                return sink.path
            except Exception as e:
                last_error = e
                # a running backup may still win before we retry
                if backup is not None:
                    bpath = backup[0].result()
                    if bpath is not None:
                        return bpath
                    attempt = max(attempt, backup[1])
                attempt += 1
                continue
        raise SchedulerError(
            f"task {query_id}.{f.id}.{task_index} failed after "
            f"{MAX_ATTEMPTS} attempts: {last_error}"
        )

    def _launch_backup(
        self, pool, query_id, f, task_index, attempt, frag_json, splits,
        out_buffers, committed, by_id,
    ):
        def run_backup():
            try:
                uri, task_id, sink = self._start_attempt(
                    query_id, f, task_index, attempt, frag_json, splits,
                    out_buffers, committed, by_id, worker_offset=1,
                )
                self._await_task(uri, task_id)
                return sink.path if sink.committed else None
            except Exception:
                return None

        return pool.submit(run_backup), attempt

    def _poll_task(self, uri: str, task_id: str) -> Optional[str]:
        """One status poll: None while running, 'FINISHED', or a failure
        state string."""
        try:
            with urllib.request.urlopen(
                f"{uri}/v1/task/{task_id}", timeout=5.0
            ) as resp:
                doc = json.loads(resp.read())
        except (urllib.error.URLError, ConnectionError, OSError):
            return None  # transient; outer timeout bounds us
        state = doc.get("state")
        if state == "FINISHED":
            return "FINISHED"
        if state in ("FAILED", "ABORTED", "CANCELED"):
            return f"{state}: {doc.get('error')}"
        return None

    def _await_task(self, uri: str, task_id: str):
        deadline = time.time() + TASK_TIMEOUT
        consecutive_failures = 0
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                    f"{uri}/v1/task/{task_id}", timeout=5.0
                ) as resp:
                    doc = json.loads(resp.read())
                consecutive_failures = 0
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                # tolerate transient poll blips (a stalled worker thread is
                # not a dead worker); ContinuousTaskStatusFetcher backoff
                consecutive_failures += 1
                if consecutive_failures >= POLL_FAILURE_TOLERANCE:
                    raise SchedulerError(f"worker {uri} lost: {e}")
                time.sleep(0.2)
                continue
            state = doc.get("state")
            if state == "FINISHED":
                return
            if state in ("FAILED", "ABORTED", "CANCELED"):
                raise SchedulerError(
                    f"task {task_id} {state}: {doc.get('error')}"
                )
            time.sleep(POLL_INTERVAL)
        raise SchedulerError(f"task {task_id} timed out")
