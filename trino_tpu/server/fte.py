"""Fault-tolerant (task-retry) query scheduler over the spooled exchange.

Reference parity: execution/scheduler/faulttolerant/
EventDrivenFaultTolerantQueryScheduler.java:199 — stage-by-stage execution
where every stage's output is spooled to durable storage (Exchange SPI /
trino-exchange-filesystem), failed task attempts are re-scheduled on other
alive workers, and consumers only ever read the spool paths of attempts the
scheduler committed (structural dedup of duplicate attempt output — the
DeduplicatingDirectExchangeBuffer / ExchangeSourceOutputSelector role).

Differences from the pipelined scheduler (scheduler.py): stages run with a
barrier between producer and consumer (no streaming overlap), so a worker
death or injected task failure only costs the retried task, never the query
(retry-policy=TASK).  Worker loss between stages is tolerated by re-picking
placement from the currently-alive node set per attempt.

Spool integrity: committed attempts are trusted only as far as their CRCs.
When a consumer task (or the root read) hits SpoolCorruptionError, the
scheduler treats the *producer's* committed attempt as a late task failure:
decommit the corrupt attempt, re-run that one producer task under a fresh
attempt number, splice the new spool path into the committed map, and let
the consumer retry — a flipped bit on the exchange costs one task re-run,
never the query (the Project-Tardigrade contract extended to data at rest).
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
import urllib.error
import urllib.request
import uuid
from typing import Dict, List, Optional, Tuple

from ..catalog import CatalogManager
from ..exchange.filesystem import (
    FileSystemExchangeManager,
    SpoolCorruptionError,
    SpoolHandle,
    read_spool_pages,
)
from ..exec.partitioner import concat_pages
from ..page import Page
from ..plan import nodes as P
from ..plan.fragment import (
    ARBITRARY,
    HASH,
    SINGLE,
    SOURCE,
    PlanFragment,
    fragment_plan,
)
from ..serde import encode_value, plan_to_json
from .scheduler import (
    SchedulerError,
    _post_json,
    assign_splits,
    source_buffer_index,
)

MAX_ATTEMPTS = 4
POLL_INTERVAL = 0.02
TASK_TIMEOUT = 300.0
POLL_FAILURE_TOLERANCE = 3  # consecutive status-poll errors = worker lost
# speculative execution (EventDrivenFaultTolerantQueryScheduler SPECULATIVE
# class): a task running longer than SPECULATION_FACTOR x the median
# completed sibling (and at least SPECULATION_MIN_S) gets a backup attempt
# on another worker; first committed attempt wins
SPECULATION_FACTOR = 2.0
SPECULATION_MIN_S = 0.75
# failed attempts retry with exponentially grown memory
# (ExponentialGrowthPartitionMemoryEstimator)
MEMORY_GROWTH_FACTOR = 2

# the quoted-path marker SpoolCorruptionError embeds in task error
# strings; parsing it back out is how a consumer's FAILED state names
# the corrupt PRODUCER attempt to heal
_SPOOL_ERR_RE = re.compile(r"spool corruption at '([^']+)'")


def _corrupt_spool_path(err) -> Optional[str]:
    m = _SPOOL_ERR_RE.search(str(err))
    return m.group(1) if m else None


def _count_scans(n: P.PlanNode) -> int:
    total = 1 if isinstance(n, P.TableScan) else 0
    for s in n.sources:
        total += _count_scans(s)
    return total


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    return s[len(s) // 2]


class FaultTolerantScheduler:
    """retry-policy=TASK execution of one query."""

    def __init__(
        self,
        catalogs: CatalogManager,
        node_manager,
        exchange: Optional[FileSystemExchangeManager] = None,
        properties: Optional[dict] = None,
        metadata=None,
        precommitted: Optional[Dict[str, List[Optional[str]]]] = None,
        on_dispatch=None,
        on_commit=None,
    ):
        self.catalogs = catalogs
        self.node_manager = node_manager
        self.exchange = exchange or FileSystemExchangeManager()
        self.properties = properties or {}
        # coordinator-restart resume (server/recovery.py): committed
        # spool paths replayed from the WAL, keyed by structural fragment
        # signature — stages whose signature matches reuse them verbatim
        # and only UNFINISHED work re-runs.  Entries are re-verified on
        # disk (every path present + _COMMIT intact) before seeding.
        self.precommitted = dict(precommitted or {})
        # WAL intent hooks: on_dispatch(task_id, uri) after an attempt is
        # POSTed, on_commit(fragment_sig, task_index, spool_path) once an
        # attempt's spool wins — the coordinator journals both so a crash
        # between stages resumes instead of re-running
        self.on_dispatch = on_dispatch
        self.on_commit = on_commit
        p = self.properties
        # table statistics for per-fragment output estimates (the
        # OutputStatsEstimator's *expected* side); None disables the
        # estimate-vs-observed replan entirely
        if metadata is not None and not p.get("statistics_enabled", True):
            from ..plan.cost import RowCountOnlyMetadata

            metadata = RowCountOnlyMetadata(metadata)
        self.metadata = metadata
        from ..utils.faults import FaultInjector

        self._injector = FaultInjector.from_spec(p.get("fault_injection"))
        self.max_attempts = int(p.get("fte_max_attempts") or MAX_ATTEMPTS)
        self.task_timeout = float(
            p.get("fte_task_timeout_s") or TASK_TIMEOUT
        )
        self.spec_factor = float(
            p.get("fte_speculation_factor") or SPECULATION_FACTOR
        )
        self.spec_min_s = float(
            p.get("fte_speculation_min_s") or SPECULATION_MIN_S
        )
        # dispersion-aware speculation (obs/opstats): a primary is hedged
        # when its elapsed wall sits `straggler_dispersion_factor` robust
        # deviations above the sibling median, replacing the fixed
        # spec_factor*median age rule (which ignored how tight the
        # sibling distribution actually was)
        from ..obs.opstats import StragglerDetector

        self.straggler = StragglerDetector(
            factor=float(
                p.get("straggler_dispersion_factor") or 2.0
            ),
            min_s=self.spec_min_s,
        )

    # ------------------------------------------------------------------
    def run(self, plan: P.Output, query_id: Optional[str] = None) -> Page:
        query_id = query_id or f"q_{uuid.uuid4().hex[:12]}"
        self._created_tasks: List[Tuple[str, str]] = []  # (uri, task_id)
        # frag spool dir -> everything needed to re-run one producer task
        # of that stage (heals survive an adaptive re-fragmentation, which
        # renumbers fragments — the spool path keeps the OLD id)
        self._heal_ctx: Dict[str, tuple] = {}
        self._heal_lock = threading.RLock()  # heals can nest across stages
        self.heal_actions: List[dict] = []  # observability for chaos tests
        # observed spool bytes/rows per completed fragment (the
        # OutputStatsEstimator role) + the adaptive actions taken from
        # them (surfaced for tests/observability)
        self.output_stats: Dict[int, int] = {}
        self.output_rows: Dict[int, int] = {}
        self.fragment_estimates: Dict[int, float] = {}
        self.adaptive_actions: List[dict] = []
        # winning-attempt TaskInfo stats (operator timeline merge input)
        self.task_stats: List[dict] = []
        # committed stages survive a replan when the new topology contains
        # a structurally identical fragment: spools are reused by signature
        committed_by_sig: Dict[str, List[str]] = {}
        stats_by_sig: Dict[str, Tuple[int, int]] = {}
        # restart resume: seed the signature map from WAL-replayed spool
        # paths, but only stages whose every attempt is still committed
        # on disk — a stale/evicted spool silently re-runs the stage
        # (width and buffer count are in the signature, so a changed
        # cluster size safely disables reuse instead of misrouting)
        for sig, paths in self.precommitted.items():
            if not paths or any(p is None for p in paths):
                continue
            if all(SpoolHandle(p).committed for p in paths):
                committed_by_sig[sig] = list(paths)
                stats_by_sig[sig] = self._spool_stats(list(paths))
        replans = 0
        try:
            while True:
                # post-replan stages spool under an epoch-suffixed query id
                # so renumbered fragments never collide with epoch-0 dirs
                epoch_qid = (
                    query_id if replans == 0 else f"{query_id}_r{replans}"
                )
                fragments = fragment_plan(plan)
                by_id = {f.id: f for f in fragments}
                consumer: Dict[int, int] = {}
                for f in fragments:
                    for sf in f.source_fragments:
                        consumer[sf] = f.id

                # stage width is fixed up-front (task count = buffer
                # addressing), but *placement* is re-chosen per attempt
                # from the alive set
                width: Dict[int, int] = {}
                cluster = self.node_manager.alive()
                if not cluster:
                    raise SchedulerError(
                        "NO_NODES_AVAILABLE: no alive workers"
                    )
                for f in fragments:
                    width[f.id] = (
                        len(cluster)
                        if f.partitioning in (SOURCE, HASH, ARBITRARY)
                        else 1
                    )

                sigs = self._fragment_signatures(fragments, width, consumer)
                est = self._estimate_fragments(fragments, by_id)
                self.fragment_estimates = dict(est)
                # committed spool dirs: frag -> [task_index -> spool path]
                committed: Dict[int, List[str]] = {}
                self.output_stats = {}
                self.output_rows = {}
                adaptive = bool(
                    self.properties.get("adaptive_replanning", True)
                )
                order = sorted(
                    (f for f in fragments if f.id != 0), key=lambda f: f.id
                ) + [by_id[0]]
                replanned = False
                for f in order:
                    reused = committed_by_sig.get(sigs[f.id])
                    if reused is not None:
                        committed[f.id] = reused
                        b, r = stats_by_sig.get(sigs[f.id], (0, 0))
                        self.output_stats[f.id] = b
                        self.output_rows[f.id] = r
                        continue
                    committed[f.id] = self._run_stage(
                        epoch_qid, f, width, committed, by_id, consumer,
                        sig=sigs[f.id],
                    )
                    committed_by_sig[sigs[f.id]] = committed[f.id]
                    if not adaptive:
                        continue
                    b, r = self._spool_stats(committed[f.id])
                    self.output_stats[f.id] = b
                    self.output_rows[f.id] = r
                    stats_by_sig[sigs[f.id]] = (b, r)
                    if replans == 0:
                        new_plan = self._maybe_replan(
                            plan, f, fragments, by_id, est, committed
                        )
                        if new_plan is not None:
                            plan = new_plan
                            replans += 1
                            replanned = True
                            break
                if not replanned:
                    break
            for _ in range(self.max_attempts):
                try:
                    root_pages = read_spool_pages(
                        SpoolHandle(committed[0][0]).buffer_file(0)
                    )
                    break
                except SpoolCorruptionError as e:
                    # the ROOT attempt itself is corrupt: heal it like any
                    # other producer (decommit + re-run) and re-read
                    if not self._heal_corrupt_spool(e.path):
                        raise
            else:
                raise SchedulerError(
                    "root spool still corrupt after "
                    f"{self.max_attempts} heal attempts"
                )
            if not root_pages:
                raise SchedulerError("root stage produced no pages")
            return concat_pages(root_pages)
        finally:
            # abort + delete every attempt on the workers (frees task state;
            # abandoned attempts stop before re-creating spool dirs)
            for uri, task_id in self._created_tasks:
                try:
                    req = urllib.request.Request(
                        f"{uri}/v1/task/{task_id}", method="DELETE"
                    )
                    urllib.request.urlopen(req, timeout=5.0).read()
                except Exception:
                    pass
            self.exchange.cleanup_query(query_id)
            for i in range(1, replans + 1):
                self.exchange.cleanup_query(f"{query_id}_r{i}")

    # ------------------------------------------------------------------
    def _sources_for(
        self,
        f: PlanFragment,
        task_index: int,
        committed: Dict[int, List[str]],
        by_id: Dict[int, PlanFragment],
    ) -> Dict[str, list]:
        """Spool-file locations of the committed upstream attempts (same
        buffer routing as the pipelined scheduler, different location shape)."""
        sources: Dict[str, list] = {}
        for sf in f.source_fragments:
            src = by_id[sf]
            buf = source_buffer_index(src, task_index)
            sources[str(sf)] = [
                {"path": SpoolHandle(path).buffer_file(buf)}
                for path in committed[sf]
            ]
        return sources

    def _run_stage(
        self,
        query_id: str,
        f: PlanFragment,
        width: Dict[int, int],
        committed: Dict[int, List[str]],
        by_id: Dict[int, PlanFragment],
        consumer: Dict[int, int],
        sig: Optional[str] = None,
    ) -> List[str]:
        ntasks = width[f.id]
        out_buffers = (
            width[consumer[f.id]]
            if f.output_partitioning in (HASH, ARBITRARY)
            else 1
        )
        per_task_splits = assign_splits(self.catalogs, f, ntasks)
        root = self._adapt_fragment(f)
        frag_json = plan_to_json(root)
        from concurrent.futures import ThreadPoolExecutor

        sibling_times: List[float] = []  # completed task durations (stage)
        with ThreadPoolExecutor(max_workers=max(ntasks, 1)) as pool:
            futures = [
                pool.submit(
                    self._run_task_with_retries,
                    query_id, f, i, frag_json, per_task_splits[i],
                    out_buffers, committed, by_id, sibling_times, pool,
                )
                for i in range(ntasks)
            ]
            paths = [fut.result() for fut in futures]
        if self.on_commit is not None and sig is not None:
            # journal every winning attempt's spool path BEFORE the next
            # stage consumes it: a coordinator crash from here on resumes
            # this stage by signature instead of re-running it
            for i, path in enumerate(paths):
                try:
                    self.on_commit(sig, i, path)
                except Exception:
                    pass
        # retained so a later-detected corrupt committed attempt can be
        # healed by re-running exactly one producer task of this stage;
        # keyed by the fragment's spool dir (stable across a replan's
        # fragment renumbering) and holding this epoch's committed/by_id
        # so the heal re-run resolves its sources against the topology it
        # actually ran under
        if paths:
            frag_dir = os.path.dirname(os.path.abspath(paths[0]))
            self._heal_ctx[frag_dir] = (
                f, frag_json, per_task_splits, out_buffers, paths,
                query_id, dict(committed), by_id,
            )
        return paths

    def _spool_stats(self, spool_dirs: List[str]) -> Tuple[int, int]:
        """(uncompressed bytes, rows) committed by a stage, read from the
        page-frame headers only (serde.pages_stats) — the observed side
        of the adaptive planner's estimate-vs-actual comparison.
        Compressed file sizes would misrank sides (zstd flattens monotone
        int columns ~10x)."""
        import os

        from ..serde import pages_stats

        total = 0
        rows = 0
        for d in spool_dirs:
            try:
                for base, _dirs, files in os.walk(d):
                    for name in files:
                        p = os.path.join(base, name)
                        try:
                            with open(p, "rb") as fh:
                                r, ub = pages_stats(fh.read())
                            total += ub
                            rows += r
                        except Exception:
                            total += os.path.getsize(p)
            except OSError:
                pass
        return total, rows

    def _adapt_fragment(self, f: PlanFragment) -> P.PlanNode:
        """Adaptive replanning between stages (AdaptivePlanner.java +
        OutputStatsEstimator, scoped to the structure-preserving action):
        a not-yet-run fragment's inner join whose sides read committed
        upstream spools is RE-ORIENTED when the observed bytes contradict
        the planner's static choice — the build (right) side must be the
        smaller input.  Exchange topology, task widths, and buffer
        addressing are untouched; the in-fragment executor re-derives
        kernel flags (dup self-checks make a wrong uniqueness guess a
        retry, not an error)."""
        if not self.output_stats or not bool(
            self.properties.get("adaptive_replanning", True)
        ):
            return f.root

        stats = self.output_stats

        def observed(n: P.PlanNode) -> Optional[int]:
            """Bytes entering this subtree: committed spool sizes for
            RemoteSources (the real observation), connector-stats
            estimates for fragment-local scans (rows x cols x 8 — both
            sides must be comparable even when only one crossed an
            exchange)."""
            total = 0
            found = False
            bad = False

            def walk(x):
                nonlocal total, found, bad
                if isinstance(x, P.RemoteSource):
                    found = True
                    if x.fragment_id in stats:
                        total += stats[x.fragment_id]
                    else:
                        bad = True
                    return
                if isinstance(x, P.TableScan):
                    found = True
                    try:
                        md = self.catalogs.get(x.catalog).metadata()
                        rows = md.get_table_statistics(x.table).row_count
                        total += int(rows) * 8 * max(
                            len(x.assignments), 1
                        )
                    except Exception:
                        bad = True
                    return
                for s in x.sources:
                    walk(s)

            walk(n)
            if not found or bad:
                return None
            return total

        import dataclasses as dc

        def adapt(n: P.PlanNode) -> P.PlanNode:
            srcs = tuple(adapt(s) for s in n.sources)
            if srcs and any(a is not b for a, b in zip(srcs, n.sources)):
                from ..plan.memo import _replace_sources

                n = _replace_sources(n, srcs)
            if not (
                isinstance(n, P.Join)
                and n.kind == "inner"
                and n.criteria
            ):
                return n
            lb = observed(n.left)
            rb = observed(n.right)
            if lb is None or rb is None or rb <= lb * 2:
                return n
            # swapping must not disturb the fragment's TableScan preorder:
            # split assignment and dynamic filters address scans by index
            # (fragment.scan_tables), so only swap when at most one side
            # holds scans
            if _count_scans(n.left) and _count_scans(n.right):
                return n
            self.adaptive_actions.append({
                "action": "swap_join_sides",
                "fragment": f.id,
                "observed_left_bytes": lb,
                "observed_right_bytes": rb,
            })
            return P.Join(
                "inner", n.right, n.left,
                tuple((r, l) for l, r in n.criteria),
                n.filter,
                expansion=False,  # runtime dup checks re-derive exactly
                distribution=n.distribution,
                compact_rows=n.compact_rows,
            )

        try:
            return adapt(f.root)
        except Exception:
            return f.root

    # -- adaptive replanning (topology-changing tier) -------------------
    def _fragment_signatures(
        self,
        fragments: List[PlanFragment],
        width: Dict[int, int],
        consumer: Dict[int, int],
    ) -> Dict[int, str]:
        """Structural identity of every fragment: its plan shape (with
        each RemoteSource replaced by the SOURCE fragment's signature,
        so ids don't matter), task width and output shape.  After an
        adaptive replan renumbers fragments, a new fragment carrying the
        same signature as an already-committed one reuses that stage's
        spools verbatim instead of re-running."""
        import dataclasses as dc
        import hashlib

        by_id = {f.id: f for f in fragments}
        sigs: Dict[int, str] = {}

        def node_sig(n: P.PlanNode, child: Dict[int, str]) -> str:
            parts = [type(n).__name__]
            for fld in dc.fields(n):
                v = getattr(n, fld.name)
                if isinstance(v, P.PlanNode):
                    continue
                if isinstance(v, tuple) and any(
                    isinstance(x, P.PlanNode) for x in v
                ):
                    continue
                if isinstance(n, P.RemoteSource) and fld.name == "fragment_id":
                    parts.append(child.get(v, str(v)))
                    continue
                parts.append(f"{fld.name}={v!r}")
            for s in n.sources:
                parts.append(node_sig(s, child))
            return hashlib.blake2b(
                "\x1f".join(parts).encode(), digest_size=16
            ).hexdigest()

        def frag_sig(fid: int) -> str:
            if fid in sigs:
                return sigs[fid]
            f = by_id[fid]
            child = {sf: frag_sig(sf) for sf in f.source_fragments}
            out_buffers = (
                width[consumer[fid]]
                if f.output_partitioning in (HASH, ARBITRARY)
                else 1
            )
            doc = "|".join([
                node_sig(f.root, child),
                f.output_partitioning or "",
                ",".join(f.output_keys),
                str(width[fid]),
                str(out_buffers),
            ])
            sigs[fid] = hashlib.blake2b(
                doc.encode(), digest_size=16
            ).hexdigest()
            return sigs[fid]

        for f in fragments:
            frag_sig(f.id)
        return sigs

    def _frag_stats(self, est_map: Dict[int, float], by_id):
        """StatsProvider whose RemoteSource estimates resolve to the
        source fragment's rows in `est_map` — the fragment-graph analog
        of the planner's per-node StatsCalculator."""
        from ..plan import cost as C

        class _FragmentStats(C.StatsProvider):
            def _estimate(sp, node):
                if isinstance(node, P.RemoteSource):
                    try:
                        w = C._width_of(node)
                    except Exception:
                        w = 8.0
                    return C.Estimate(
                        float(est_map.get(node.fragment_id, 1.0)), w
                    )
                return super(_FragmentStats, sp)._estimate(node)

        return _FragmentStats(self.metadata, 1)

    def _estimate_fragments(
        self, fragments: List[PlanFragment], by_id
    ) -> Dict[int, float]:
        """Static estimated output rows per fragment — the *expected*
        side of the adaptive-replan comparison (OutputStatsEstimator's
        counterpart).  The seeded chaos site ``stats_estimate`` divides a
        fragment's estimate by its rule ``factor``, deterministically
        forcing the divergence the replan tests need."""
        if self.metadata is None:
            return {}
        est: Dict[int, float] = {}
        for f in sorted(fragments, key=lambda f: (f.id == 0, f.id)):
            sp = self._frag_stats(est, by_id)
            try:
                est[f.id] = float(sp.estimate(f.root).rows)
            except Exception:
                continue
            rule = self._injector.rules.get("stats_estimate")
            if rule is not None and self._injector.fires(
                "stats_estimate", key=f"fragment.{f.id}"
            ):
                factor = float(rule.get("factor", 10.0)) or 1.0
                est[f.id] = est[f.id] / factor
        return est

    def _maybe_replan(
        self,
        plan: P.PlanNode,
        f: PlanFragment,
        fragments: List[PlanFragment],
        by_id,
        est: Dict[int, float],
        committed: Dict[int, List[str]],
    ) -> Optional[P.PlanNode]:
        """The topology-CHANGING adaptive tier (AdaptivePlanner's
        partitioned/broadcast rule): when the just-committed fragment's
        observed output rows diverge from the static estimate by more
        than adaptive_replan_factor (either direction), re-cost the
        not-yet-run remainder with every observation substituted for its
        estimate; if that flips a pending inner join's build side across
        broadcast_join_threshold_rows, rewrite the ORIGINAL plan with the
        corrected Join.distribution and hand it back for
        re-fragmentation (committed stages are reused by signature)."""
        try:
            factor = float(
                self.properties.get("adaptive_replan_factor") or 0.0
            )
        except (TypeError, ValueError):
            return None
        if factor <= 0 or self.metadata is None:
            return None
        e = est.get(f.id)
        if e is None or e <= 0:
            return None
        o = max(float(self.output_rows.get(f.id, 0)), 1.0)
        if max(o / e, e / o) < factor:
            return None
        from ..config import BROADCAST_JOIN_THRESHOLD_ROWS

        try:
            threshold = int(
                self.properties.get("broadcast_join_threshold_rows")
                or BROADCAST_JOIN_THRESHOLD_ROWS
            )
        except (TypeError, ValueError):
            threshold = BROADCAST_JOIN_THRESHOLD_ROWS
        # corrected fragment estimates: observed rows where we have them,
        # re-derived estimates (over the corrected inputs) elsewhere
        corrected: Dict[int, float] = {}
        for g in sorted(fragments, key=lambda x: (x.id == 0, x.id)):
            if g.id in committed and g.id in self.output_rows:
                corrected[g.id] = max(float(self.output_rows[g.id]), 1.0)
            else:
                try:
                    corrected[g.id] = float(
                        self._frag_stats(corrected, by_id)
                        .estimate(g.root).rows
                    )
                except Exception:
                    corrected[g.id] = est.get(g.id, 1.0)
        sp_old = self._frag_stats(est, by_id)
        sp_new = self._frag_stats(corrected, by_id)
        for g in fragments:
            if g.id in committed:
                continue
            found = self._find_distribution_flip(
                g.root, sp_old, sp_new, threshold
            )
            if found is None:
                continue
            join, new_dist, old_rows, new_rows = found
            new_plan = self._flip_join_distribution(plan, join, new_dist)
            if new_plan is None:
                continue
            from ..utils.metrics import counter

            counter("trino_tpu_stats_replan_total").inc()
            self.adaptive_actions.append({
                "action": "flip_join_distribution",
                "fragment": g.id,
                "diverged_fragment": f.id,
                "estimated_rows": e,
                "observed_rows": o,
                "from": join.distribution,
                "to": new_dist,
                "build_rows_estimated": old_rows,
                "build_rows_corrected": new_rows,
            })
            return new_plan
        return None

    def _find_distribution_flip(
        self, root: P.PlanNode, sp_old, sp_new, threshold: int
    ):
        """First join under `root` whose corrected build-side rows land
        on the other side of the broadcast threshold from its planned
        distribution: (join, new_dist, old_rows, new_rows) or None."""
        found = None

        def walk(n: P.PlanNode):
            nonlocal found
            if found is not None:
                return
            if (
                isinstance(n, P.Join)
                and n.criteria
                and n.kind in ("inner", "left")
                and n.distribution in ("broadcast", "partitioned")
            ):
                try:
                    old_r = float(sp_old.estimate(n.right).rows)
                    new_r = float(sp_new.estimate(n.right).rows)
                except Exception:
                    old_r = new_r = None
                if new_r is not None:
                    if n.distribution == "broadcast" and new_r > threshold:
                        found = (n, "partitioned", old_r, new_r)
                        return
                    if (
                        n.distribution == "partitioned"
                        and new_r <= threshold
                        and old_r > threshold
                    ):
                        found = (n, "broadcast", old_r, new_r)
                        return
            for s in n.sources:
                walk(s)

        walk(root)
        return found

    def _flip_join_distribution(
        self, plan: P.PlanNode, join: P.Join, dist: str
    ) -> Optional[P.PlanNode]:
        """Rewrite the first Join in the ORIGINAL plan matching the
        fragment's copy (same kind/criteria/distribution — fragmentation
        preserves symbols) with the corrected distribution."""
        import dataclasses as dc

        hit = False

        def walk(n: P.PlanNode) -> P.PlanNode:
            nonlocal hit
            srcs = tuple(walk(s) for s in n.sources)
            if srcs and any(a is not b for a, b in zip(srcs, n.sources)):
                from ..plan.memo import _replace_sources

                n = _replace_sources(n, srcs)
            if (
                not hit
                and isinstance(n, P.Join)
                and n.kind == join.kind
                and n.criteria == join.criteria
                and n.distribution == join.distribution
            ):
                hit = True
                n = dc.replace(n, distribution=dist)
            return n

        try:
            new_plan = walk(plan)
        except Exception:
            return None
        return new_plan if hit else None

    def _start_attempt(
        self, query_id, f, task_index, attempt, frag_json, splits,
        out_buffers, committed, by_id, exclude_uri=None,
    ):
        """POST one attempt; returns (uri, task_id, sink).  exclude_uri
        steers a backup away from the straggling primary's worker."""
        workers = self.node_manager.alive()
        if not workers:
            raise SchedulerError("NO_NODES_AVAILABLE during retry")
        # quarantined nodes (device out, no CPU fallback) are skipped;
        # DEGRADED nodes stay eligible for retries — slow beats failed
        try:
            device = self.node_manager.device_states()
        except Exception:
            device = {}
        healthy = [
            w for w in workers
            if (device.get(w[0]) or {}).get("state") != "QUARANTINED"
        ] or workers
        candidates = [w for w in healthy if w[1] != exclude_uri] or healthy
        node_id, uri = candidates[(task_index + attempt) % len(candidates)]
        sink = self.exchange.sink(query_id, f.id, task_index, attempt)
        task_id = f"{query_id}.{f.id}.{task_index}.{attempt}"
        props = dict(self.properties)
        base_mem = props.get("query_max_memory_bytes")
        if base_mem and attempt:
            # re-try with exponentially grown memory
            # (ExponentialGrowthPartitionMemoryEstimator)
            props["query_max_memory_bytes"] = int(
                base_mem * (MEMORY_GROWTH_FACTOR ** attempt)
            )
        doc = {
            "fragment": frag_json,
            "splits": {
                str(k): [encode_value(s) for s in v]
                for k, v in splits.items()
            },
            "output": {
                "partitioning": f.output_partitioning,
                "keys": list(f.output_keys),
                "nbuffers": out_buffers,
            },
            "sources": self._sources_for(f, task_index, committed, by_id),
            "properties": props,
            "spool_path": sink.path,
        }
        # register before the POST: a worker that dies mid-dispatch may
        # have created the task without ever flushing the response, and
        # end-of-query cleanup must cover that half-created task too
        self._created_tasks.append((uri, task_id))
        _post_json(f"{uri}/v1/task/{task_id}", doc)
        if self.on_dispatch is not None:
            # WAL intent: this attempt now exists on a worker — a crashed
            # coordinator's replay knows work was in flight even if no
            # commit record ever follows
            try:
                self.on_dispatch(task_id, uri)
            except Exception:
                pass
        from ..utils.metrics import REGISTRY

        REGISTRY.counter(
            "trino_tpu_scheduler_dispatch_total",
            "Remote task creations dispatched to workers",
        ).inc()
        if attempt > 0:
            REGISTRY.counter(
                "trino_tpu_scheduler_retry_total",
                "Task attempts beyond the first (failover, backup, heal)",
            ).inc()
            from ..obs import journal

            journal.emit(
                journal.FTE_REASSIGN, query_id=query_id,
                task_id=task_id, node_id=node_id,
                severity=journal.WARN,
                attempt=attempt, uri=uri,
                excludedUri=str(exclude_uri or ""),
            )
        return uri, task_id, sink

    def _abort_task(self, uri, task_id):
        try:
            req = urllib.request.Request(
                f"{uri}/v1/task/{task_id}", method="DELETE"
            )
            urllib.request.urlopen(req, timeout=5.0).read()
        except Exception:
            pass

    def _run_task_with_retries(
        self, query_id, f, task_index, frag_json, splits, out_buffers,
        committed, by_id, sibling_times=None, pool=None, attempt_base=0,
    ) -> str:
        """Primary attempts with failover + at most one speculative backup
        per primary attempt; FIRST COMMITTED ATTEMPT WINS, the loser is
        aborted.  Backups run on daemon threads so neither the stage pool
        nor the retry loop ever blocks on a slow backup.  attempt_base > 0
        is the heal path re-running a producer whose earlier attempts'
        numbers (task ids + spool dirs) must never be reused."""
        speculate = bool(self.properties.get("speculative_execution", True))
        last_error = None
        # Monotonic attempt allocator: EVERY launched attempt (primary or
        # backup, finished or not) consumes a number, so a task_id / spool
        # dir {task}.{attempt} is never reused — a timed-out-but-running
        # backup can never collide with a later primary.
        next_attempt = attempt_base
        max_attempt = attempt_base + self.max_attempts
        backups: List[dict] = []  # {'done','path','duration','uri','task'}

        def backup_winner():
            for b in backups:
                if b["done"] and b["path"] is not None:
                    return b
            return None

        # after a failed attempt the next primary steers off that worker
        # (a device-lost task would otherwise land on the same sick node)
        failed_uri = None
        while next_attempt < max_attempt:
            attempt = next_attempt
            next_attempt += 1
            try:
                uri, task_id, sink = self._start_attempt(
                    query_id, f, task_index, attempt, frag_json, splits,
                    out_buffers, committed, by_id,
                    exclude_uri=failed_uri,
                )
            except SchedulerError:
                raise
            except Exception as e:
                last_error = e
                continue
            launched_backup = False
            poll_failures = 0
            t0 = time.time()
            try:
                while True:
                    state, polled = self._poll_task(uri, task_id)
                    if polled:
                        poll_failures = 0
                    else:
                        poll_failures += 1
                        if self._uri_gone(uri):
                            raise SchedulerError(
                                f"worker {uri} GONE (node lifecycle): "
                                "reassigning task to a survivor"
                            )
                        if poll_failures >= POLL_FAILURE_TOLERANCE:
                            raise SchedulerError(
                                f"worker {uri} lost (status polls failing)"
                            )
                    if state == "FINISHED":
                        break
                    if state is not None and state != "RUNNING":
                        raise SchedulerError(f"task {task_id} {state}")
                    if time.time() - t0 > self.task_timeout:
                        raise SchedulerError(f"task {task_id} timed out")
                    win = backup_winner()
                    if win is not None:
                        self._abort_task(uri, task_id)
                        if sibling_times is not None:
                            sibling_times.append(win["duration"])
                        return win["path"]
                    if (
                        speculate
                        and not launched_backup
                        and next_attempt < max_attempt
                        and sibling_times
                        and self.straggler.should_hedge(
                            time.time() - t0, list(sibling_times)
                        )
                    ):
                        launched_backup = True
                        self.straggler.record_hedge(
                            f.id, task_id, uri,
                            time.time() - t0, list(sibling_times),
                        )
                        battempt = next_attempt
                        next_attempt += 1
                        b = {"done": False, "path": None, "duration": 0.0,
                             "uri": None, "task": None}
                        backups.append(b)

                        def run_backup(b=b, battempt=battempt,
                                       primary_uri=uri):
                            bt0 = time.time()
                            try:
                                buri, btid, bsink = self._start_attempt(
                                    query_id, f, task_index, battempt,
                                    frag_json, splits, out_buffers,
                                    committed, by_id,
                                    exclude_uri=primary_uri,
                                )
                                b["uri"], b["task"] = buri, btid
                                self._await_task(buri, btid)
                                if bsink.committed:
                                    b["path"] = bsink.path
                            except Exception:
                                pass
                            finally:
                                b["duration"] = time.time() - bt0
                                b["done"] = True

                        threading.Thread(
                            target=run_backup, daemon=True
                        ).start()
                    time.sleep(POLL_INTERVAL)
                if not sink.committed:
                    raise SchedulerError(
                        f"task {task_id} finished without committing spool"
                    )
                # winning attempt: pull its TaskInfo stats (operator
                # frames ride "operatorStats") for the query timeline
                try:
                    with urllib.request.urlopen(
                        f"{uri}/v1/task/{task_id}", timeout=5.0
                    ) as resp:
                        info = json.loads(resp.read())
                    self.task_stats.append({
                        "taskId": task_id, "uri": uri,
                        "stats": info.get("stats") or {},
                    })
                except Exception:
                    pass
                # primary won: abort any still-running backup (frees the
                # worker; the loser's spool dir is never read)
                for b in backups:
                    if not b["done"] and b["uri"]:
                        self._abort_task(b["uri"], b["task"])
                if sibling_times is not None:
                    sibling_times.append(time.time() - t0)
                return sink.path
            except Exception as e:
                last_error = e
                failed_uri = uri
                win = backup_winner()
                if win is not None:
                    return win["path"]
                corrupt = _corrupt_spool_path(e)
                if corrupt is not None:
                    # an UPSTREAM committed attempt failed its CRCs: this
                    # consumer cannot succeed until the producer is healed
                    # — decommit + re-run it, then retry the consumer
                    # against the spliced-in fresh spool path
                    self._heal_corrupt_spool(corrupt)
                # never block on a pending backup — it stays in the race;
                # the next primary draws a fresh number from next_attempt
                continue
        # primaries exhausted: grant outstanding backups a bounded grace
        deadline = time.time() + 30.0
        while time.time() < deadline and any(
            not b["done"] for b in backups
        ):
            win = backup_winner()
            if win is not None:
                return win["path"]
            time.sleep(POLL_INTERVAL)
        win = backup_winner()
        if win is not None:
            return win["path"]
        raise SchedulerError(
            f"task {query_id}.{f.id}.{task_index} failed after "
            f"{self.max_attempts} attempts: {last_error}"
        )

    def _heal_corrupt_spool(self, path: str) -> bool:
        """Retire the corrupt committed attempt owning `path` and re-run
        its producer task under fresh attempt numbers, splicing the new
        spool dir into the committed list every consumer reads.  Returns
        True once the producer is healthy again (including when a
        concurrent consumer got there first); False when the path doesn't
        map to a healable stage."""
        # path: {base}/{query}/{fragment}/{task}.{attempt}/buffer_{id}.bin
        attempt_dir = os.path.dirname(os.path.abspath(path))
        frag_dir = os.path.dirname(attempt_dir)
        task_s, _, attempt_s = os.path.basename(attempt_dir).partition(".")
        ctx = self._heal_ctx.get(frag_dir)
        try:
            task_index = int(task_s)
        except ValueError:
            return False
        if ctx is None:
            return False
        (f, frag_json, per_task_splits, out_buffers, paths,
         epoch_qid, epoch_committed, epoch_by_id) = ctx
        if task_index >= len(paths):
            return False
        with self._heal_lock:
            if os.path.abspath(paths[task_index]) != attempt_dir:
                return True  # another consumer already healed this one
            SpoolHandle(attempt_dir).decommit()
            # fresh attempt numbers: above every attempt dir ever created
            # for this task — task ids must not be reused (the worker's
            # create_or_update is idempotent and would hand back the old
            # task instead of re-running it)
            used = [int(attempt_s)] if attempt_s.isdigit() else []
            try:
                for d in os.listdir(frag_dir):
                    t, _, a = d.partition(".")
                    if t == task_s and a.isdigit():
                        used.append(int(a))
            except OSError:
                pass
            attempt_base = max(used, default=-1) + 1
            new_path = self._run_task_with_retries(
                epoch_qid, f, task_index, frag_json,
                per_task_splits[task_index], out_buffers, epoch_committed,
                epoch_by_id, attempt_base=attempt_base,
            )
            paths[task_index] = new_path
            self.heal_actions.append({
                "action": "respawn_corrupt_attempt",
                "fragment": f.id,
                "task": task_index,
                "corrupt_path": attempt_dir,
                "healed_path": new_path,
            })
            from ..obs import journal

            journal.emit(
                journal.SPOOL_HEAL, query_id=epoch_qid,
                task_id=f"{epoch_qid}.{f.id}.{task_index}",
                severity=journal.WARN,
                fragment=f.id, corruptPath=attempt_dir,
                healedPath=new_path,
            )
            return True

    def _uri_gone(self, uri: str) -> bool:
        """True when the node manager has declared the attempt's host
        GONE: the poll-failure tolerance (meant for GC pauses and slow
        responses) is skipped and the attempt fails over immediately,
        reusing every already-committed upstream spool."""
        fn = getattr(self.node_manager, "gone_uris", None)
        if fn is None:
            return False
        try:
            return uri in fn()
        except Exception:
            return False

    def _poll_task(self, uri: str, task_id: str):
        """One status poll: (state, reachable) — state None while running
        or on a transient poll failure."""
        try:
            with urllib.request.urlopen(
                f"{uri}/v1/task/{task_id}", timeout=5.0
            ) as resp:
                doc = json.loads(resp.read())
        except (urllib.error.URLError, ConnectionError, OSError):
            return None, False
        state = doc.get("state")
        if state == "FINISHED":
            return "FINISHED", True
        if state in ("FAILED", "ABORTED", "CANCELED"):
            return f"{state}: {doc.get('error')}", True
        return None, True

    def _await_task(self, uri: str, task_id: str):
        deadline = time.time() + TASK_TIMEOUT
        consecutive_failures = 0
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                    f"{uri}/v1/task/{task_id}", timeout=5.0
                ) as resp:
                    doc = json.loads(resp.read())
                consecutive_failures = 0
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                # tolerate transient poll blips (a stalled worker thread is
                # not a dead worker); ContinuousTaskStatusFetcher backoff
                consecutive_failures += 1
                if self._uri_gone(uri):
                    raise SchedulerError(
                        f"worker {uri} GONE (node lifecycle): {e}"
                    )
                if consecutive_failures >= POLL_FAILURE_TOLERANCE:
                    raise SchedulerError(f"worker {uri} lost: {e}")
                time.sleep(0.2)
                continue
            state = doc.get("state")
            if state == "FINISHED":
                return
            if state in ("FAILED", "ABORTED", "CANCELED"):
                raise SchedulerError(
                    f"task {task_id} {state}: {doc.get('error')}"
                )
            time.sleep(POLL_INTERVAL)
        raise SchedulerError(f"task {task_id} timed out")
