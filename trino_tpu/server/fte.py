"""Fault-tolerant (task-retry) query scheduler over the spooled exchange.

Reference parity: execution/scheduler/faulttolerant/
EventDrivenFaultTolerantQueryScheduler.java:199 — stage-by-stage execution
where every stage's output is spooled to durable storage (Exchange SPI /
trino-exchange-filesystem), failed task attempts are re-scheduled on other
alive workers, and consumers only ever read the spool paths of attempts the
scheduler committed (structural dedup of duplicate attempt output — the
DeduplicatingDirectExchangeBuffer / ExchangeSourceOutputSelector role).

Differences from the pipelined scheduler (scheduler.py): stages run with a
barrier between producer and consumer (no streaming overlap), so a worker
death or injected task failure only costs the retried task, never the query
(retry-policy=TASK).  Worker loss between stages is tolerated by re-picking
placement from the currently-alive node set per attempt.

Spool integrity: committed attempts are trusted only as far as their CRCs.
When a consumer task (or the root read) hits SpoolCorruptionError, the
scheduler treats the *producer's* committed attempt as a late task failure:
decommit the corrupt attempt, re-run that one producer task under a fresh
attempt number, splice the new spool path into the committed map, and let
the consumer retry — a flipped bit on the exchange costs one task re-run,
never the query (the Project-Tardigrade contract extended to data at rest).
"""
from __future__ import annotations

import json
import os
import re
import threading
import time
import urllib.error
import urllib.request
import uuid
from typing import Dict, List, Optional, Tuple

from ..catalog import CatalogManager
from ..exchange.filesystem import (
    FileSystemExchangeManager,
    SpoolCorruptionError,
    SpoolHandle,
    read_spool_pages,
)
from ..exec.partitioner import concat_pages
from ..page import Page
from ..plan import nodes as P
from ..plan.fragment import (
    ARBITRARY,
    HASH,
    SINGLE,
    SOURCE,
    PlanFragment,
    fragment_plan,
)
from ..serde import encode_value, plan_to_json
from .scheduler import (
    SchedulerError,
    _post_json,
    assign_splits,
    source_buffer_index,
)

MAX_ATTEMPTS = 4
POLL_INTERVAL = 0.02
TASK_TIMEOUT = 300.0
POLL_FAILURE_TOLERANCE = 3  # consecutive status-poll errors = worker lost
# speculative execution (EventDrivenFaultTolerantQueryScheduler SPECULATIVE
# class): a task running longer than SPECULATION_FACTOR x the median
# completed sibling (and at least SPECULATION_MIN_S) gets a backup attempt
# on another worker; first committed attempt wins
SPECULATION_FACTOR = 2.0
SPECULATION_MIN_S = 0.75
# failed attempts retry with exponentially grown memory
# (ExponentialGrowthPartitionMemoryEstimator)
MEMORY_GROWTH_FACTOR = 2

# the quoted-path marker SpoolCorruptionError embeds in task error
# strings; parsing it back out is how a consumer's FAILED state names
# the corrupt PRODUCER attempt to heal
_SPOOL_ERR_RE = re.compile(r"spool corruption at '([^']+)'")


def _corrupt_spool_path(err) -> Optional[str]:
    m = _SPOOL_ERR_RE.search(str(err))
    return m.group(1) if m else None


def _count_scans(n: P.PlanNode) -> int:
    total = 1 if isinstance(n, P.TableScan) else 0
    for s in n.sources:
        total += _count_scans(s)
    return total


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    return s[len(s) // 2]


class FaultTolerantScheduler:
    """retry-policy=TASK execution of one query."""

    def __init__(
        self,
        catalogs: CatalogManager,
        node_manager,
        exchange: Optional[FileSystemExchangeManager] = None,
        properties: Optional[dict] = None,
    ):
        self.catalogs = catalogs
        self.node_manager = node_manager
        self.exchange = exchange or FileSystemExchangeManager()
        self.properties = properties or {}
        p = self.properties
        self.max_attempts = int(p.get("fte_max_attempts") or MAX_ATTEMPTS)
        self.task_timeout = float(
            p.get("fte_task_timeout_s") or TASK_TIMEOUT
        )
        self.spec_factor = float(
            p.get("fte_speculation_factor") or SPECULATION_FACTOR
        )
        self.spec_min_s = float(
            p.get("fte_speculation_min_s") or SPECULATION_MIN_S
        )

    # ------------------------------------------------------------------
    def run(self, plan: P.Output, query_id: Optional[str] = None) -> Page:
        query_id = query_id or f"q_{uuid.uuid4().hex[:12]}"
        fragments = fragment_plan(plan)
        by_id = {f.id: f for f in fragments}
        consumer: Dict[int, int] = {}
        for f in fragments:
            for sf in f.source_fragments:
                consumer[sf] = f.id

        # stage width is fixed up-front (task count = buffer addressing),
        # but *placement* is re-chosen per attempt from the alive set
        width: Dict[int, int] = {}
        cluster = self.node_manager.alive()
        if not cluster:
            raise SchedulerError("NO_NODES_AVAILABLE: no alive workers")
        for f in fragments:
            width[f.id] = (
                len(cluster)
                if f.partitioning in (SOURCE, HASH, ARBITRARY)
                else 1
            )

        # committed spool dirs: fragment -> [task_index -> SpoolHandle path]
        committed: Dict[int, List[str]] = {}
        self._created_tasks: List[Tuple[str, str]] = []  # (uri, task_id)
        # per-stage (frag_json, per-task splits, out_buffers) so a corrupt
        # committed attempt can be healed by re-running just its producer
        self._stage_ctx: Dict[int, tuple] = {}
        self._heal_lock = threading.RLock()  # heals can nest across stages
        self.heal_actions: List[dict] = []  # observability for chaos tests
        # observed spool bytes per completed fragment (the
        # OutputStatsEstimator role) + the adaptive actions taken from
        # them (surfaced for tests/observability)
        self.output_stats: Dict[int, int] = {}
        self.adaptive_actions: List[dict] = []
        try:
            order = sorted(
                (f for f in fragments if f.id != 0), key=lambda f: f.id
            ) + [by_id[0]]
            for f in order:
                committed[f.id] = self._run_stage(
                    query_id, f, width, committed, by_id, consumer
                )
                if bool(self.properties.get("adaptive_replanning", True)):
                    self.output_stats[f.id] = self._spool_bytes(
                        committed[f.id]
                    )
            for _ in range(self.max_attempts):
                try:
                    root_pages = read_spool_pages(
                        SpoolHandle(committed[0][0]).buffer_file(0)
                    )
                    break
                except SpoolCorruptionError as e:
                    # the ROOT attempt itself is corrupt: heal it like any
                    # other producer (decommit + re-run) and re-read
                    if not self._heal_corrupt_spool(
                        query_id, e.path, committed, by_id
                    ):
                        raise
            else:
                raise SchedulerError(
                    "root spool still corrupt after "
                    f"{self.max_attempts} heal attempts"
                )
            if not root_pages:
                raise SchedulerError("root stage produced no pages")
            return concat_pages(root_pages)
        finally:
            # abort + delete every attempt on the workers (frees task state;
            # abandoned attempts stop before re-creating spool dirs)
            for uri, task_id in self._created_tasks:
                try:
                    req = urllib.request.Request(
                        f"{uri}/v1/task/{task_id}", method="DELETE"
                    )
                    urllib.request.urlopen(req, timeout=5.0).read()
                except Exception:
                    pass
            self.exchange.cleanup_query(query_id)

    # ------------------------------------------------------------------
    def _sources_for(
        self,
        f: PlanFragment,
        task_index: int,
        committed: Dict[int, List[str]],
        by_id: Dict[int, PlanFragment],
    ) -> Dict[str, list]:
        """Spool-file locations of the committed upstream attempts (same
        buffer routing as the pipelined scheduler, different location shape)."""
        sources: Dict[str, list] = {}
        for sf in f.source_fragments:
            src = by_id[sf]
            buf = source_buffer_index(src, task_index)
            sources[str(sf)] = [
                {"path": SpoolHandle(path).buffer_file(buf)}
                for path in committed[sf]
            ]
        return sources

    def _run_stage(
        self,
        query_id: str,
        f: PlanFragment,
        width: Dict[int, int],
        committed: Dict[int, List[str]],
        by_id: Dict[int, PlanFragment],
        consumer: Dict[int, int],
    ) -> List[str]:
        ntasks = width[f.id]
        out_buffers = (
            width[consumer[f.id]]
            if f.output_partitioning in (HASH, ARBITRARY)
            else 1
        )
        per_task_splits = assign_splits(self.catalogs, f, ntasks)
        root = self._adapt_fragment(f)
        frag_json = plan_to_json(root)
        # retained so a later-detected corrupt committed attempt can be
        # healed by re-running exactly one producer task of this stage
        self._stage_ctx[f.id] = (frag_json, per_task_splits, out_buffers)
        from concurrent.futures import ThreadPoolExecutor

        sibling_times: List[float] = []  # completed task durations (stage)
        with ThreadPoolExecutor(max_workers=max(ntasks, 1)) as pool:
            futures = [
                pool.submit(
                    self._run_task_with_retries,
                    query_id, f, i, frag_json, per_task_splits[i],
                    out_buffers, committed, by_id, sibling_times, pool,
                )
                for i in range(ntasks)
            ]
            return [fut.result() for fut in futures]

    def _spool_bytes(self, spool_dirs: List[str]) -> int:
        """Total committed UNCOMPRESSED output bytes of a stage, read
        from the page-frame headers only (serde.pages_stats) — the
        observed stat the adaptive planner consumes.  Compressed file
        sizes would misrank sides (zstd flattens monotone int columns
        ~10x)."""
        import os

        from ..serde import pages_stats

        total = 0
        for d in spool_dirs:
            try:
                for base, _dirs, files in os.walk(d):
                    for name in files:
                        p = os.path.join(base, name)
                        try:
                            with open(p, "rb") as fh:
                                _rows, ub = pages_stats(fh.read())
                            total += ub
                        except Exception:
                            total += os.path.getsize(p)
            except OSError:
                pass
        return total

    def _adapt_fragment(self, f: PlanFragment) -> P.PlanNode:
        """Adaptive replanning between stages (AdaptivePlanner.java +
        OutputStatsEstimator, scoped to the structure-preserving action):
        a not-yet-run fragment's inner join whose sides read committed
        upstream spools is RE-ORIENTED when the observed bytes contradict
        the planner's static choice — the build (right) side must be the
        smaller input.  Exchange topology, task widths, and buffer
        addressing are untouched; the in-fragment executor re-derives
        kernel flags (dup self-checks make a wrong uniqueness guess a
        retry, not an error)."""
        if not self.output_stats or not bool(
            self.properties.get("adaptive_replanning", True)
        ):
            return f.root

        stats = self.output_stats

        def observed(n: P.PlanNode) -> Optional[int]:
            """Bytes entering this subtree: committed spool sizes for
            RemoteSources (the real observation), connector-stats
            estimates for fragment-local scans (rows x cols x 8 — both
            sides must be comparable even when only one crossed an
            exchange)."""
            total = 0
            found = False
            bad = False

            def walk(x):
                nonlocal total, found, bad
                if isinstance(x, P.RemoteSource):
                    found = True
                    if x.fragment_id in stats:
                        total += stats[x.fragment_id]
                    else:
                        bad = True
                    return
                if isinstance(x, P.TableScan):
                    found = True
                    try:
                        md = self.catalogs.get(x.catalog).metadata()
                        rows = md.get_table_statistics(x.table).row_count
                        total += int(rows) * 8 * max(
                            len(x.assignments), 1
                        )
                    except Exception:
                        bad = True
                    return
                for s in x.sources:
                    walk(s)

            walk(n)
            if not found or bad:
                return None
            return total

        import dataclasses as dc

        def adapt(n: P.PlanNode) -> P.PlanNode:
            srcs = tuple(adapt(s) for s in n.sources)
            if srcs and any(a is not b for a, b in zip(srcs, n.sources)):
                from ..plan.memo import _replace_sources

                n = _replace_sources(n, srcs)
            if not (
                isinstance(n, P.Join)
                and n.kind == "inner"
                and n.criteria
            ):
                return n
            lb = observed(n.left)
            rb = observed(n.right)
            if lb is None or rb is None or rb <= lb * 2:
                return n
            # swapping must not disturb the fragment's TableScan preorder:
            # split assignment and dynamic filters address scans by index
            # (fragment.scan_tables), so only swap when at most one side
            # holds scans
            if _count_scans(n.left) and _count_scans(n.right):
                return n
            self.adaptive_actions.append({
                "action": "swap_join_sides",
                "fragment": f.id,
                "observed_left_bytes": lb,
                "observed_right_bytes": rb,
            })
            return P.Join(
                "inner", n.right, n.left,
                tuple((r, l) for l, r in n.criteria),
                n.filter,
                expansion=False,  # runtime dup checks re-derive exactly
                distribution=n.distribution,
                compact_rows=n.compact_rows,
            )

        try:
            return adapt(f.root)
        except Exception:
            return f.root

    def _start_attempt(
        self, query_id, f, task_index, attempt, frag_json, splits,
        out_buffers, committed, by_id, exclude_uri=None,
    ):
        """POST one attempt; returns (uri, task_id, sink).  exclude_uri
        steers a backup away from the straggling primary's worker."""
        workers = self.node_manager.alive()
        if not workers:
            raise SchedulerError("NO_NODES_AVAILABLE during retry")
        candidates = [w for w in workers if w[1] != exclude_uri] or workers
        node_id, uri = candidates[(task_index + attempt) % len(candidates)]
        sink = self.exchange.sink(query_id, f.id, task_index, attempt)
        task_id = f"{query_id}.{f.id}.{task_index}.{attempt}"
        props = dict(self.properties)
        base_mem = props.get("query_max_memory_bytes")
        if base_mem and attempt:
            # re-try with exponentially grown memory
            # (ExponentialGrowthPartitionMemoryEstimator)
            props["query_max_memory_bytes"] = int(
                base_mem * (MEMORY_GROWTH_FACTOR ** attempt)
            )
        doc = {
            "fragment": frag_json,
            "splits": {
                str(k): [encode_value(s) for s in v]
                for k, v in splits.items()
            },
            "output": {
                "partitioning": f.output_partitioning,
                "keys": list(f.output_keys),
                "nbuffers": out_buffers,
            },
            "sources": self._sources_for(f, task_index, committed, by_id),
            "properties": props,
            "spool_path": sink.path,
        }
        _post_json(f"{uri}/v1/task/{task_id}", doc)
        from ..utils.metrics import REGISTRY

        REGISTRY.counter(
            "trino_tpu_scheduler_dispatch_total",
            "Remote task creations dispatched to workers",
        ).inc()
        if attempt > 0:
            REGISTRY.counter(
                "trino_tpu_scheduler_retry_total",
                "Task attempts beyond the first (failover, backup, heal)",
            ).inc()
        self._created_tasks.append((uri, task_id))
        return uri, task_id, sink

    def _abort_task(self, uri, task_id):
        try:
            req = urllib.request.Request(
                f"{uri}/v1/task/{task_id}", method="DELETE"
            )
            urllib.request.urlopen(req, timeout=5.0).read()
        except Exception:
            pass

    def _run_task_with_retries(
        self, query_id, f, task_index, frag_json, splits, out_buffers,
        committed, by_id, sibling_times=None, pool=None, attempt_base=0,
    ) -> str:
        """Primary attempts with failover + at most one speculative backup
        per primary attempt; FIRST COMMITTED ATTEMPT WINS, the loser is
        aborted.  Backups run on daemon threads so neither the stage pool
        nor the retry loop ever blocks on a slow backup.  attempt_base > 0
        is the heal path re-running a producer whose earlier attempts'
        numbers (task ids + spool dirs) must never be reused."""
        speculate = bool(self.properties.get("speculative_execution", True))
        last_error = None
        # Monotonic attempt allocator: EVERY launched attempt (primary or
        # backup, finished or not) consumes a number, so a task_id / spool
        # dir {task}.{attempt} is never reused — a timed-out-but-running
        # backup can never collide with a later primary.
        next_attempt = attempt_base
        max_attempt = attempt_base + self.max_attempts
        backups: List[dict] = []  # {'done','path','duration','uri','task'}

        def backup_winner():
            for b in backups:
                if b["done"] and b["path"] is not None:
                    return b
            return None

        while next_attempt < max_attempt:
            attempt = next_attempt
            next_attempt += 1
            try:
                uri, task_id, sink = self._start_attempt(
                    query_id, f, task_index, attempt, frag_json, splits,
                    out_buffers, committed, by_id,
                )
            except SchedulerError:
                raise
            except Exception as e:
                last_error = e
                continue
            launched_backup = False
            poll_failures = 0
            t0 = time.time()
            try:
                while True:
                    state, polled = self._poll_task(uri, task_id)
                    if polled:
                        poll_failures = 0
                    else:
                        poll_failures += 1
                        if poll_failures >= POLL_FAILURE_TOLERANCE:
                            raise SchedulerError(
                                f"worker {uri} lost (status polls failing)"
                            )
                    if state == "FINISHED":
                        break
                    if state is not None and state != "RUNNING":
                        raise SchedulerError(f"task {task_id} {state}")
                    if time.time() - t0 > self.task_timeout:
                        raise SchedulerError(f"task {task_id} timed out")
                    win = backup_winner()
                    if win is not None:
                        self._abort_task(uri, task_id)
                        if sibling_times is not None:
                            sibling_times.append(win["duration"])
                        return win["path"]
                    if (
                        speculate
                        and not launched_backup
                        and next_attempt < max_attempt
                        and sibling_times
                        and time.time() - t0
                        > max(
                            self.spec_min_s,
                            self.spec_factor * _median(sibling_times),
                        )
                    ):
                        launched_backup = True
                        battempt = next_attempt
                        next_attempt += 1
                        b = {"done": False, "path": None, "duration": 0.0,
                             "uri": None, "task": None}
                        backups.append(b)

                        def run_backup(b=b, battempt=battempt,
                                       primary_uri=uri):
                            bt0 = time.time()
                            try:
                                buri, btid, bsink = self._start_attempt(
                                    query_id, f, task_index, battempt,
                                    frag_json, splits, out_buffers,
                                    committed, by_id,
                                    exclude_uri=primary_uri,
                                )
                                b["uri"], b["task"] = buri, btid
                                self._await_task(buri, btid)
                                if bsink.committed:
                                    b["path"] = bsink.path
                            except Exception:
                                pass
                            finally:
                                b["duration"] = time.time() - bt0
                                b["done"] = True

                        threading.Thread(
                            target=run_backup, daemon=True
                        ).start()
                    time.sleep(POLL_INTERVAL)
                if not sink.committed:
                    raise SchedulerError(
                        f"task {task_id} finished without committing spool"
                    )
                # primary won: abort any still-running backup (frees the
                # worker; the loser's spool dir is never read)
                for b in backups:
                    if not b["done"] and b["uri"]:
                        self._abort_task(b["uri"], b["task"])
                if sibling_times is not None:
                    sibling_times.append(time.time() - t0)
                return sink.path
            except Exception as e:
                last_error = e
                win = backup_winner()
                if win is not None:
                    return win["path"]
                corrupt = _corrupt_spool_path(e)
                if corrupt is not None:
                    # an UPSTREAM committed attempt failed its CRCs: this
                    # consumer cannot succeed until the producer is healed
                    # — decommit + re-run it, then retry the consumer
                    # against the spliced-in fresh spool path
                    self._heal_corrupt_spool(
                        query_id, corrupt, committed, by_id
                    )
                # never block on a pending backup — it stays in the race;
                # the next primary draws a fresh number from next_attempt
                continue
        # primaries exhausted: grant outstanding backups a bounded grace
        deadline = time.time() + 30.0
        while time.time() < deadline and any(
            not b["done"] for b in backups
        ):
            win = backup_winner()
            if win is not None:
                return win["path"]
            time.sleep(POLL_INTERVAL)
        win = backup_winner()
        if win is not None:
            return win["path"]
        raise SchedulerError(
            f"task {query_id}.{f.id}.{task_index} failed after "
            f"{self.max_attempts} attempts: {last_error}"
        )

    def _heal_corrupt_spool(
        self,
        query_id: str,
        path: str,
        committed: Dict[int, List[str]],
        by_id: Dict[int, PlanFragment],
    ) -> bool:
        """Retire the corrupt committed attempt owning `path` and re-run
        its producer task under fresh attempt numbers, splicing the new
        spool dir into `committed`.  Returns True once the producer is
        healthy again (including when a concurrent consumer got there
        first); False when the path doesn't map to a healable stage."""
        # path: {base}/{query}/{fragment}/{task}.{attempt}/buffer_{id}.bin
        attempt_dir = os.path.dirname(os.path.abspath(path))
        frag_dir = os.path.dirname(attempt_dir)
        task_s, _, attempt_s = os.path.basename(attempt_dir).partition(".")
        try:
            fid = int(os.path.basename(frag_dir))
            task_index = int(task_s)
        except ValueError:
            return False
        ctx = self._stage_ctx.get(fid)
        paths = committed.get(fid)
        if ctx is None or paths is None or task_index >= len(paths):
            return False
        with self._heal_lock:
            if os.path.abspath(paths[task_index]) != attempt_dir:
                return True  # another consumer already healed this one
            SpoolHandle(attempt_dir).decommit()
            # fresh attempt numbers: above every attempt dir ever created
            # for this task — task ids must not be reused (the worker's
            # create_or_update is idempotent and would hand back the old
            # task instead of re-running it)
            used = [int(attempt_s)] if attempt_s.isdigit() else []
            try:
                for d in os.listdir(frag_dir):
                    t, _, a = d.partition(".")
                    if t == task_s and a.isdigit():
                        used.append(int(a))
            except OSError:
                pass
            attempt_base = max(used, default=-1) + 1
            frag_json, per_task_splits, out_buffers = ctx
            new_path = self._run_task_with_retries(
                query_id, by_id[fid], task_index, frag_json,
                per_task_splits[task_index], out_buffers, committed,
                by_id, attempt_base=attempt_base,
            )
            paths[task_index] = new_path
            self.heal_actions.append({
                "action": "respawn_corrupt_attempt",
                "fragment": fid,
                "task": task_index,
                "corrupt_path": attempt_dir,
                "healed_path": new_path,
            })
            return True

    def _poll_task(self, uri: str, task_id: str):
        """One status poll: (state, reachable) — state None while running
        or on a transient poll failure."""
        try:
            with urllib.request.urlopen(
                f"{uri}/v1/task/{task_id}", timeout=5.0
            ) as resp:
                doc = json.loads(resp.read())
        except (urllib.error.URLError, ConnectionError, OSError):
            return None, False
        state = doc.get("state")
        if state == "FINISHED":
            return "FINISHED", True
        if state in ("FAILED", "ABORTED", "CANCELED"):
            return f"{state}: {doc.get('error')}", True
        return None, True

    def _await_task(self, uri: str, task_id: str):
        deadline = time.time() + TASK_TIMEOUT
        consecutive_failures = 0
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                    f"{uri}/v1/task/{task_id}", timeout=5.0
                ) as resp:
                    doc = json.loads(resp.read())
                consecutive_failures = 0
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                # tolerate transient poll blips (a stalled worker thread is
                # not a dead worker); ContinuousTaskStatusFetcher backoff
                consecutive_failures += 1
                if consecutive_failures >= POLL_FAILURE_TOLERANCE:
                    raise SchedulerError(f"worker {uri} lost: {e}")
                time.sleep(0.2)
                continue
            state = doc.get("state")
            if state == "FINISHED":
                return
            if state in ("FAILED", "ABORTED", "CANCELED"):
                raise SchedulerError(
                    f"task {task_id} {state}: {doc.get('error')}"
                )
            time.sleep(POLL_INTERVAL)
        raise SchedulerError(f"task {task_id} timed out")
