"""Fault-tolerant (task-retry) query scheduler over the spooled exchange.

Reference parity: execution/scheduler/faulttolerant/
EventDrivenFaultTolerantQueryScheduler.java:199 — stage-by-stage execution
where every stage's output is spooled to durable storage (Exchange SPI /
trino-exchange-filesystem), failed task attempts are re-scheduled on other
alive workers, and consumers only ever read the spool paths of attempts the
scheduler committed (structural dedup of duplicate attempt output — the
DeduplicatingDirectExchangeBuffer / ExchangeSourceOutputSelector role).

Differences from the pipelined scheduler (scheduler.py): stages run with a
barrier between producer and consumer (no streaming overlap), so a worker
death or injected task failure only costs the retried task, never the query
(retry-policy=TASK).  Worker loss between stages is tolerated by re-picking
placement from the currently-alive node set per attempt.
"""
from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
import uuid
from typing import Dict, List, Optional, Tuple

from ..catalog import CatalogManager
from ..exchange.filesystem import FileSystemExchangeManager, read_spool_pages
from ..exec.partitioner import concat_pages
from ..page import Page
from ..plan import nodes as P
from ..plan.fragment import HASH, SINGLE, SOURCE, PlanFragment, fragment_plan
from ..serde import encode_value, plan_to_json
from .scheduler import (
    SchedulerError,
    _post_json,
    assign_splits,
    source_buffer_index,
)

MAX_ATTEMPTS = 4
POLL_INTERVAL = 0.02
TASK_TIMEOUT = 300.0
POLL_FAILURE_TOLERANCE = 3  # consecutive status-poll errors = worker lost


class FaultTolerantScheduler:
    """retry-policy=TASK execution of one query."""

    def __init__(
        self,
        catalogs: CatalogManager,
        node_manager,
        exchange: Optional[FileSystemExchangeManager] = None,
        properties: Optional[dict] = None,
    ):
        self.catalogs = catalogs
        self.node_manager = node_manager
        self.exchange = exchange or FileSystemExchangeManager()
        self.properties = properties or {}

    # ------------------------------------------------------------------
    def run(self, plan: P.Output, query_id: Optional[str] = None) -> Page:
        query_id = query_id or f"q_{uuid.uuid4().hex[:12]}"
        fragments = fragment_plan(plan)
        by_id = {f.id: f for f in fragments}
        consumer: Dict[int, int] = {}
        for f in fragments:
            for sf in f.source_fragments:
                consumer[sf] = f.id

        # stage width is fixed up-front (task count = buffer addressing),
        # but *placement* is re-chosen per attempt from the alive set
        width: Dict[int, int] = {}
        cluster = self.node_manager.alive()
        if not cluster:
            raise SchedulerError("NO_NODES_AVAILABLE: no alive workers")
        for f in fragments:
            width[f.id] = len(cluster) if f.partitioning in (SOURCE, HASH) else 1

        # committed spool dirs: fragment -> [task_index -> SpoolHandle path]
        committed: Dict[int, List[str]] = {}
        self._created_tasks: List[Tuple[str, str]] = []  # (uri, task_id)
        try:
            order = sorted(
                (f for f in fragments if f.id != 0), key=lambda f: f.id
            ) + [by_id[0]]
            for f in order:
                committed[f.id] = self._run_stage(
                    query_id, f, width, committed, by_id, consumer
                )
            from ..exchange.filesystem import SpoolHandle

            root_pages = read_spool_pages(
                SpoolHandle(committed[0][0]).buffer_file(0)
            )
            if not root_pages:
                raise SchedulerError("root stage produced no pages")
            return concat_pages(root_pages)
        finally:
            # abort + delete every attempt on the workers (frees task state;
            # abandoned attempts stop before re-creating spool dirs)
            for uri, task_id in self._created_tasks:
                try:
                    req = urllib.request.Request(
                        f"{uri}/v1/task/{task_id}", method="DELETE"
                    )
                    urllib.request.urlopen(req, timeout=5.0).read()
                except Exception:
                    pass
            self.exchange.cleanup_query(query_id)

    # ------------------------------------------------------------------
    def _sources_for(
        self,
        f: PlanFragment,
        task_index: int,
        committed: Dict[int, List[str]],
        by_id: Dict[int, PlanFragment],
    ) -> Dict[str, list]:
        """Spool-file locations of the committed upstream attempts (same
        buffer routing as the pipelined scheduler, different location shape)."""
        from ..exchange.filesystem import SpoolHandle

        sources: Dict[str, list] = {}
        for sf in f.source_fragments:
            src = by_id[sf]
            buf = source_buffer_index(src, task_index)
            sources[str(sf)] = [
                {"path": SpoolHandle(path).buffer_file(buf)}
                for path in committed[sf]
            ]
        return sources

    def _run_stage(
        self,
        query_id: str,
        f: PlanFragment,
        width: Dict[int, int],
        committed: Dict[int, List[str]],
        by_id: Dict[int, PlanFragment],
        consumer: Dict[int, int],
    ) -> List[str]:
        ntasks = width[f.id]
        out_buffers = (
            width[consumer[f.id]] if f.output_partitioning == HASH else 1
        )
        per_task_splits = assign_splits(self.catalogs, f, ntasks)
        frag_json = plan_to_json(f.root)
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=max(ntasks, 1)) as pool:
            futures = [
                pool.submit(
                    self._run_task_with_retries,
                    query_id, f, i, frag_json, per_task_splits[i],
                    out_buffers, committed, by_id,
                )
                for i in range(ntasks)
            ]
            return [fut.result() for fut in futures]

    def _run_task_with_retries(
        self, query_id, f, task_index, frag_json, splits, out_buffers,
        committed, by_id,
    ) -> str:
        last_error = None
        for attempt in range(MAX_ATTEMPTS):
            workers = self.node_manager.alive()
            if not workers:
                raise SchedulerError("NO_NODES_AVAILABLE during retry")
            node_id, uri = workers[(task_index + attempt) % len(workers)]
            sink = self.exchange.sink(query_id, f.id, task_index, attempt)
            task_id = f"{query_id}.{f.id}.{task_index}.{attempt}"
            doc = {
                "fragment": frag_json,
                "splits": {
                    str(k): [encode_value(s) for s in v]
                    for k, v in splits.items()
                },
                "output": {
                    "partitioning": f.output_partitioning,
                    "keys": list(f.output_keys),
                    "nbuffers": out_buffers,
                },
                "sources": self._sources_for(
                    f, task_index, committed, by_id
                ),
                "properties": self.properties,
                "spool_path": sink.path,
            }
            try:
                _post_json(f"{uri}/v1/task/{task_id}", doc)
                self._created_tasks.append((uri, task_id))
                self._await_task(uri, task_id)
                if not sink.committed:
                    raise SchedulerError(
                        f"task {task_id} finished without committing spool"
                    )
                return sink.path
            except Exception as e:
                last_error = e
                continue  # next attempt on another worker
        raise SchedulerError(
            f"task {query_id}.{f.id}.{task_index} failed after "
            f"{MAX_ATTEMPTS} attempts: {last_error}"
        )

    def _await_task(self, uri: str, task_id: str):
        deadline = time.time() + TASK_TIMEOUT
        consecutive_failures = 0
        while time.time() < deadline:
            try:
                with urllib.request.urlopen(
                    f"{uri}/v1/task/{task_id}", timeout=5.0
                ) as resp:
                    doc = json.loads(resp.read())
                consecutive_failures = 0
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                # tolerate transient poll blips (a stalled worker thread is
                # not a dead worker); ContinuousTaskStatusFetcher backoff
                consecutive_failures += 1
                if consecutive_failures >= POLL_FAILURE_TOLERANCE:
                    raise SchedulerError(f"worker {uri} lost: {e}")
                time.sleep(0.2)
                continue
            state = doc.get("state")
            if state == "FINISHED":
                return
            if state in ("FAILED", "ABORTED", "CANCELED"):
                raise SchedulerError(
                    f"task {task_id} {state}: {doc.get('error')}"
                )
            time.sleep(POLL_INTERVAL)
        raise SchedulerError(f"task {task_id} timed out")
