"""Resource groups: hierarchical multi-tenant admission and scheduling.

Reference parity: execution/resourcegroups/InternalResourceGroup +
InternalResourceGroupManager and the file-based configuration manager
(plugin/trino-resource-group-managers): a tree of groups with concurrency
and queue limits, selectors matching (user, source) to a group, per-group
scheduling policies (``fair`` | ``weighted_fair`` | ``query_priority``)
with ``schedulingWeight``, and QUERY_QUEUE_FULL rejection.

Beyond the flat tree this adds the overload posture of *The Tail at
Scale* (Dean & Barroso): per-group queue deadlines that SHED an aged
query with a structured retryable error instead of letting it hang,
decayed CPU/slot cost accounting so a flooding tenant's effective
priority sinks under weighted-fair arbitration (it self-throttles
against its own history, no operator action needed), and per-tenant
memory shares the admission controller enforces so one tenant's
reservations cannot exhaust the pool.  Every shed and every
starvation-averted start lands in the incident journal for the query
doctor's ``overload`` rule.
"""
from __future__ import annotations

import itertools
import math
import re
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.metrics import REGISTRY

FAIR = "fair"
WEIGHTED_FAIR = "weighted_fair"
QUERY_PRIORITY = "query_priority"
SCHEDULING_POLICIES = (FAIR, WEIGHTED_FAIR, QUERY_PRIORITY)

# a queued query that waited longer than this before the scheduler got
# to it counts as a rescued near-starvation (journal starvation_averted)
STARVATION_GRACE_S = 5.0

# decayed cost half-life: a flooding tenant's charge fades over ~30s so
# a burst self-throttles without permanently demoting the group
COST_DECAY_HALF_LIFE_S = 30.0

_SEQ = itertools.count(1)


class QueryQueueFullError(RuntimeError):
    """Reference: StandardErrorCode.QUERY_QUEUE_FULL."""

    error_code = "QUERY_QUEUE_FULL"
    retryable = True


class QueryShedError(QueryQueueFullError):
    """A queued query crossed its group's queue deadline and was shed
    (load-shedding timeout, not a capacity rejection): retryable, mapped
    to ADMISSION_TIMEOUT so clients back off instead of hammering."""

    error_code = "ADMISSION_TIMEOUT"


class _DecayedCost:
    """Exponentially decayed scalar (CPU seconds + started-query slots):
    the weighted-fair arbiter divides this by the scheduling weight, so
    a group that recently consumed more than its share loses arbitration
    until the decay forgives it."""

    def __init__(self, half_life_s: float = COST_DECAY_HALF_LIFE_S):
        self.half_life_s = max(float(half_life_s), 1e-3)
        self._value = 0.0
        self._stamp = time.monotonic()

    def _decay_to(self, now: float):
        dt = now - self._stamp
        if dt > 0:
            self._value *= math.exp(-math.log(2.0) * dt / self.half_life_s)
            self._stamp = now

    def add(self, amount: float, now: Optional[float] = None):
        now = time.monotonic() if now is None else now
        self._decay_to(now)
        self._value += float(amount)

    def value(self, now: Optional[float] = None) -> float:
        now = time.monotonic() if now is None else now
        self._decay_to(now)
        return self._value


class _QueuedQuery:
    """One waiting submission: the start thunk plus everything the
    scheduler arbitrates on (age, priority, deadline) and the shed
    callback that turns a deadline miss into a structured failure."""

    __slots__ = ("start", "on_shed", "query_id", "priority",
                 "enqueued", "deadline", "seq")

    def __init__(self, start, on_shed, query_id, priority, deadline_s):
        self.start = start
        self.on_shed = on_shed
        self.query_id = query_id
        self.priority = int(priority or 0)
        self.enqueued = time.monotonic()
        self.deadline = (
            self.enqueued + deadline_s if deadline_s and deadline_s > 0
            else None
        )
        self.seq = next(_SEQ)


class InternalResourceGroup:
    """One node of the group tree (InternalResourceGroup analog).

    hard_concurrency_limit caps simultaneously running queries; max_queued
    caps the wait queue; excess submissions are rejected.  A parent's
    limits bound the sum of its children (checked transitively on
    acquire).  ``scheduling_policy`` decides which child (or own queued
    query) starts when a slot frees: ``fair`` is global FIFO by enqueue
    order, ``weighted_fair`` picks the child with the lowest decayed
    cost per unit ``scheduling_weight``, ``query_priority`` starts the
    highest-priority queued query first."""

    def __init__(
        self,
        name: str,
        hard_concurrency_limit: int = 100,
        max_queued: int = 1000,
        parent: Optional["InternalResourceGroup"] = None,
        soft_memory_limit_bytes: int = 0,
        scheduling_policy: str = FAIR,
        scheduling_weight: int = 1,
        queue_deadline_s: float = 0.0,
        memory_share: float = 0.0,
    ):
        self.name = name
        self.hard_concurrency_limit = hard_concurrency_limit
        self.max_queued = max_queued
        # softMemoryLimit analog: while the group's tracked usage is at
        # or above this, new queries queue instead of starting
        # (0 = unlimited)
        self.soft_memory_limit_bytes = soft_memory_limit_bytes
        self.memory_usage_bytes = 0
        if scheduling_policy not in SCHEDULING_POLICIES:
            raise ValueError(
                f"unknown scheduling policy {scheduling_policy!r} "
                f"(one of {SCHEDULING_POLICIES})"
            )
        self.scheduling_policy = scheduling_policy
        self.scheduling_weight = max(int(scheduling_weight), 1)
        # queued queries older than this are shed with QueryShedError
        # instead of waiting forever (0 = never shed)
        self.queue_deadline_s = float(queue_deadline_s or 0.0)
        # fraction of the cluster memory budget this group's tenant may
        # hold in admitted reservations (0 = unlimited); enforced by
        # MemoryAdmissionController through ResourceGroupManager
        self.memory_share = float(memory_share or 0.0)
        self.parent = parent
        self.running = 0
        self.queue: deque = deque()  # _QueuedQuery entries
        self.lock = parent.lock if parent else threading.Lock()
        self.children: List["InternalResourceGroup"] = []
        # decayed charge the weighted-fair arbiter keys on: one unit per
        # started query plus the CPU seconds charge_cpu() reports back
        self.cost = _DecayedCost()
        self.started_total = 0
        self.shed_total = 0
        if parent is not None:
            parent.children.append(self)

    @property
    def full_name(self) -> str:
        if self.parent is None:
            return self.name
        return f"{self.parent.full_name}.{self.name}"

    @property
    def tenant(self) -> str:
        """The tenant a query in this group bills against: the top-level
        group (direct child of the root, or the root itself)."""
        g: InternalResourceGroup = self
        while g.parent is not None and g.parent.parent is not None:
            g = g.parent
        return g.name

    def root(self) -> "InternalResourceGroup":
        g: InternalResourceGroup = self
        while g.parent is not None:
            g = g.parent
        return g

    def effective_memory_share(self) -> float:
        """This group's memory share, inheriting the nearest ancestor's
        when unset (0 all the way up = unlimited)."""
        g: Optional[InternalResourceGroup] = self
        while g is not None:
            if g.memory_share > 0:
                return g.memory_share
            g = g.parent
        return 0.0

    def _effective_deadline_s(self) -> float:
        """Queue deadline, inheriting the nearest ancestor's when this
        group does not set one."""
        g: Optional[InternalResourceGroup] = self
        while g is not None:
            if g.queue_deadline_s > 0:
                return g.queue_deadline_s
            g = g.parent
        return 0.0

    def _can_run_locked(self) -> bool:
        g: Optional[InternalResourceGroup] = self
        while g is not None:
            if g.running >= g.hard_concurrency_limit:
                return False
            if (
                g.soft_memory_limit_bytes
                and g.memory_usage_bytes >= g.soft_memory_limit_bytes
            ):
                return False
            g = g.parent
        return True

    def _add_running_locked(self, delta: int):
        g: Optional[InternalResourceGroup] = self
        while g is not None:
            g.running += delta
            g = g.parent

    def _charge_start_locked(self, now: float):
        """One started query charges a slot unit against this group and
        every ancestor (the weighted-fair arbiter reads each level)."""
        self.started_total += 1
        g: Optional[InternalResourceGroup] = self
        while g is not None:
            g.cost.add(1.0, now)
            g = g.parent

    def charge_cpu(self, seconds: float):
        """Report a finished query's wall/CPU seconds: the decayed cost
        rises, so a tenant that just burned lots of compute loses
        weighted-fair arbitration until the decay forgives it (the
        self-throttle half of softCpuLimit / cpuQuotaGeneration)."""
        if seconds <= 0:
            return
        now = time.monotonic()
        with self.lock:
            g: Optional[InternalResourceGroup] = self
            while g is not None:
                g.cost.add(float(seconds), now)
                g = g.parent

    # -- scheduling ------------------------------------------------------
    def _own_candidate_locked(self) -> Optional[_QueuedQuery]:
        if not self.queue:
            return None
        if self.scheduling_policy == QUERY_PRIORITY:
            return max(self.queue, key=lambda e: (e.priority, -e.seq))
        return self.queue[0]

    def _pick_locked(
        self, now: float
    ) -> Optional[Tuple["InternalResourceGroup", _QueuedQuery]]:
        """The next (owner, entry) eligible to start in this subtree, or
        None.  Each level applies ITS scheduling policy across its own
        queue and its children's picks; limits gate every level."""
        if not self._can_run_locked():
            # checks ancestors too, but called top-down so ancestors
            # were already vetted; self's limits are what matter here
            return None
        candidates: List[Tuple[InternalResourceGroup, _QueuedQuery]] = []
        own = self._own_candidate_locked()
        if own is not None:
            candidates.append((self, own))
        for child in self.children:
            picked = child._pick_locked(now)
            if picked is not None:
                # the arbitration source is the DIRECT child, whatever
                # depth the entry came from
                candidates.append((child, picked[1]))
        if not candidates:
            return None
        if self.scheduling_policy == WEIGHTED_FAIR:
            chosen = min(
                candidates,
                key=lambda c: (
                    c[0].cost.value(now) / c[0].scheduling_weight,
                    c[1].seq,
                ),
            )
        elif self.scheduling_policy == QUERY_PRIORITY:
            chosen = max(
                candidates, key=lambda c: (c[1].priority, -c[1].seq)
            )
        else:  # fair: global FIFO by enqueue order
            chosen = min(candidates, key=lambda c: c[1].seq)
        src, entry = chosen
        if src is self:
            return (self, entry)
        # resolve the actual owner group by re-asking the child (its
        # pick is deterministic under the lock)
        return src._pick_locked(now)

    def _drain_startable_locked(
        self, now: float
    ) -> List[Tuple["InternalResourceGroup", _QueuedQuery]]:
        """Pop every entry the tree can start right now (caller holds
        the lock); running counters are charged before release so a
        concurrent submit cannot oversubscribe."""
        started: List[Tuple[InternalResourceGroup, _QueuedQuery]] = []
        while True:
            picked = self.root()._pick_locked(now)
            if picked is None:
                return started
            owner, entry = picked
            owner.queue.remove(entry)
            owner._add_running_locked(1)
            owner._charge_start_locked(now)
            started.append((owner, entry))

    def _shed_expired_locked(
        self, now: float
    ) -> List[Tuple["InternalResourceGroup", _QueuedQuery]]:
        shed: List[Tuple[InternalResourceGroup, _QueuedQuery]] = []
        stack: List[InternalResourceGroup] = [self]
        while stack:
            g = stack.pop()
            expired = [
                e for e in g.queue
                if e.deadline is not None and now >= e.deadline
            ]
            for e in expired:
                g.queue.remove(e)
                g.shed_total += 1
                shed.append((g, e))
            stack.extend(g.children)
        return shed

    @staticmethod
    def _fire_started(started) -> None:
        now = time.monotonic()
        for owner, entry in started:
            waited = now - entry.enqueued
            if waited >= STARVATION_GRACE_S:
                # the arbiter rescued an aged query before its deadline
                # shed it: record the near-starvation for the doctor
                from ..obs import journal

                journal.emit(
                    journal.STARVATION_AVERTED,
                    query_id=entry.query_id,
                    severity=journal.WARN,
                    group=owner.full_name,
                    waitedS=round(waited, 3),
                )
                REGISTRY.counter(
                    "trino_tpu_resource_group_starvation_averted_total",
                    "Aged queued queries started before their deadline shed",
                ).inc(group=owner.full_name)
            owner._observe_gauges()
            entry.start()

    @staticmethod
    def _fire_shed(shed) -> None:
        from ..obs import journal

        for owner, entry in shed:
            waited = time.monotonic() - entry.enqueued
            err = QueryShedError(
                f"Query shed after {waited:.1f}s in the queue of resource "
                f"group \"{owner.full_name}\" (queue deadline "
                f"{owner._effective_deadline_s():.1f}s): the group is "
                f"overloaded; retry with backoff"
            )
            journal.emit(
                journal.QUERY_SHED,
                query_id=entry.query_id,
                severity=journal.WARN,
                group=owner.full_name,
                waitedS=round(waited, 3),
                queued=len(owner.queue),
            )
            REGISTRY.counter(
                "trino_tpu_resource_group_shed_total",
                "Queued queries shed past their group's queue deadline",
            ).inc(group=owner.full_name)
            owner._observe_gauges()
            if entry.on_shed is not None:
                try:
                    entry.on_shed(err)
                except Exception:  # noqa: BLE001 — shed fan-out is best-effort
                    pass

    def _observe_gauges(self):
        REGISTRY.gauge(
            "trino_tpu_resource_group_running_state",
            "Queries currently running per resource group",
        ).set(self.running, group=self.full_name)
        REGISTRY.gauge(
            "trino_tpu_resource_group_queued_state",
            "Queries currently queued per resource group",
        ).set(len(self.queue), group=self.full_name)

    # -- lifecycle -------------------------------------------------------
    def submit(
        self,
        start: Callable[[], None],
        query_id: str = "",
        priority: int = 0,
        on_shed: Optional[Callable[[Exception], None]] = None,
    ) -> str:
        """Either starts the query now (returns 'running'), queues it
        (returns 'queued'; `start` runs later — or `on_shed(err)` if the
        group's queue deadline passes first), or raises
        QueryQueueFullError."""
        now = time.monotonic()
        reject = run_now = False
        with self.lock:
            shed = self.root()._shed_expired_locked(now)
            if self._can_run_locked():
                self._add_running_locked(1)
                self._charge_start_locked(now)
                run_now = True
            elif len(self.queue) >= self.max_queued:
                reject = True
            else:
                self.queue.append(_QueuedQuery(
                    start, on_shed, query_id, priority,
                    self._effective_deadline_s(),
                ))
        self._fire_shed(shed)
        self._observe_gauges()
        if reject:
            REGISTRY.counter(
                "trino_tpu_resource_group_rejected_total",
                "Submissions rejected because the group queue was full",
            ).inc(group=self.full_name)
            raise QueryQueueFullError(
                f"Too many queued queries for \"{self.full_name}\" "
                f"(max {self.max_queued})"
            )
        if run_now:
            start()
            return "running"
        return "queued"

    def finish(self):
        """Release one running slot and start queued queries anywhere in
        the tree that now fit (processQueuedQueries walks from the root:
        a slot freed under a shared parent can admit a sibling's query),
        honoring each level's scheduling policy."""
        now = time.monotonic()
        with self.lock:
            self._add_running_locked(-1)
            shed = self.root()._shed_expired_locked(now)
            started = self._drain_startable_locked(now)
        self._fire_shed(shed)
        self._observe_gauges()
        self._fire_started(started)

    def shed_expired(self) -> int:
        """Shed every queued entry in this tree past its deadline (the
        coordinator's enforcement loop ticks this so an idle group still
        sheds on time); returns the number shed."""
        now = time.monotonic()
        with self.lock:
            shed = self.root()._shed_expired_locked(now)
            # shedding freed queue slots, never running slots, but a
            # deadline pass may coincide with startable work
            started = self._drain_startable_locked(now)
        self._fire_shed(shed)
        self._fire_started(started)
        return len(shed)

    def add_memory_usage(self, delta: int):
        """Track admitted-query memory against this group (and its
        ancestors); a negative delta re-processes the queue, since a
        group blocked on its soft memory limit may now admit."""
        now = time.monotonic()
        started = []
        with self.lock:
            g: Optional[InternalResourceGroup] = self
            while g is not None:
                g.memory_usage_bytes = max(0, g.memory_usage_bytes + delta)
                g = g.parent
            if delta < 0:
                started = self._drain_startable_locked(now)
        self._fire_started(started)

    def stats(self) -> dict:
        with self.lock:
            return {
                "name": self.full_name,
                "running": self.running,
                "queued": len(self.queue),
                "hardConcurrencyLimit": self.hard_concurrency_limit,
                "maxQueued": self.max_queued,
                "softMemoryLimitBytes": self.soft_memory_limit_bytes,
                "memoryUsageBytes": self.memory_usage_bytes,
                "schedulingPolicy": self.scheduling_policy,
                "schedulingWeight": self.scheduling_weight,
                "queueDeadlineS": self.queue_deadline_s,
                "memoryShare": self.memory_share,
                "decayedCost": round(self.cost.value(), 4),
                "startedTotal": self.started_total,
                "shedTotal": self.shed_total,
            }


class ResourceGroupManager:
    """Selector-based group assignment (InternalResourceGroupManager +
    the file-based SelectorSpec: first selector whose user/source regexes
    match wins; no match falls through to the root 'global' group)."""

    def __init__(self, config: Optional[dict] = None):
        # config: {"groups": [{"name", "hardConcurrencyLimit", "maxQueued",
        #                      "schedulingPolicy", "schedulingWeight",
        #                      "queueDeadlineS", "memoryShare",
        #                      "subGroups": [...]}, ...],
        #          "selectors": [{"user": regex, "source": regex,
        #                         "group": dotted.name}, ...]}
        self.groups: Dict[str, InternalResourceGroup] = {}
        self.selectors: List[dict] = []
        cfg = config or {}
        for g in cfg.get("groups", ()) or ():
            self._build_group(g, None)
        if "global" not in self.groups:
            self._build_group({"name": "global"}, None)
        for sel in cfg.get("selectors", ()) or ():
            self.selectors.append(sel)

    def _build_group(self, spec: dict, parent):
        g = InternalResourceGroup(
            spec["name"],
            int(spec.get("hardConcurrencyLimit", 100)),
            int(spec.get("maxQueued", 1000)),
            parent,
            soft_memory_limit_bytes=int(
                spec.get("softMemoryLimitBytes", 0)
            ),
            scheduling_policy=str(
                spec.get("schedulingPolicy", FAIR)
            ),
            scheduling_weight=int(spec.get("schedulingWeight", 1)),
            queue_deadline_s=float(spec.get("queueDeadlineS", 0.0)),
            memory_share=float(spec.get("memoryShare", 0.0)),
        )
        self.groups[g.full_name] = g
        REGISTRY.gauge(
            "trino_tpu_resource_group_weight_state",
            "Configured scheduling weight per resource group",
        ).set(g.scheduling_weight, group=g.full_name)
        for sub in spec.get("subGroups", ()) or ():
            self._build_group(sub, g)
        return g

    def select(self, user: str, source: str = "") -> InternalResourceGroup:
        for sel in self.selectors:
            if "user" in sel and not re.fullmatch(sel["user"], user or ""):
                continue
            if "source" in sel and not re.fullmatch(
                sel["source"], source or ""
            ):
                continue
            g = self.groups.get(sel["group"])
            if g is not None:
                return g
        return self.groups["global"]

    def tenant_memory_share(self, tenant: str) -> float:
        """The memory-share fraction configured for a tenant (top-level
        group name); 0 = unlimited.  The admission controller calls this
        to cap one tenant's total admitted reservations."""
        g = self.groups.get(tenant)
        if g is None:
            # tenants are bare top-level names while self.groups keys
            # dotted full names: resolve "interactive" to the root (or
            # direct child of a root) called that
            for cand in self.groups.values():
                if cand.name == tenant and (
                    cand.parent is None or cand.parent.parent is None
                ):
                    g = cand
                    break
        if g is None:
            return 0.0
        return g.effective_memory_share()

    def shed_expired(self) -> int:
        """Deadline-shed pass over every root (enforcement-loop tick)."""
        shed = 0
        for g in self.groups.values():
            if g.parent is None:
                shed += g.shed_expired()
        return shed

    def total_queued(self) -> int:
        """Queries queued across every root (autoscaler backlog signal);
        roots already aggregate nothing — queues live per group, so sum
        every group."""
        return sum(len(g.queue) for g in self.groups.values())

    def total_running(self) -> int:
        return sum(
            g.running for g in self.groups.values() if g.parent is None
        )

    def info(self) -> List[dict]:
        return [g.stats() for g in self.groups.values()]
