"""Resource groups: admission control and query queueing.

Reference parity: execution/resourcegroups/InternalResourceGroup +
InternalResourceGroupManager and the file-based configuration manager
(plugin/trino-resource-group-managers): a tree of groups with concurrency
and queue limits, selectors matching (user, source) to a group, FIFO/fair
start order, and QUERY_QUEUE_FULL rejection.
"""
from __future__ import annotations

import re
import threading
from collections import deque
from typing import Callable, Dict, List, Optional


class QueryQueueFullError(RuntimeError):
    """Reference: StandardErrorCode.QUERY_QUEUE_FULL."""


class InternalResourceGroup:
    """One node of the group tree (InternalResourceGroup analog).

    hard_concurrency_limit caps simultaneously running queries; max_queued
    caps the wait queue; excess submissions are rejected.  A parent's
    limits bound the sum of its children (checked transitively on
    acquire)."""

    def __init__(
        self,
        name: str,
        hard_concurrency_limit: int = 100,
        max_queued: int = 1000,
        parent: Optional["InternalResourceGroup"] = None,
        soft_memory_limit_bytes: int = 0,
    ):
        self.name = name
        self.hard_concurrency_limit = hard_concurrency_limit
        self.max_queued = max_queued
        # softMemoryLimit analog: while the group's tracked usage is at
        # or above this, new queries queue instead of starting
        # (0 = unlimited)
        self.soft_memory_limit_bytes = soft_memory_limit_bytes
        self.memory_usage_bytes = 0
        self.parent = parent
        self.running = 0
        self.queue: deque = deque()  # callables to start queued queries
        self.lock = parent.lock if parent else threading.Lock()
        self.children: List["InternalResourceGroup"] = []
        if parent is not None:
            parent.children.append(self)

    @property
    def full_name(self) -> str:
        if self.parent is None:
            return self.name
        return f"{self.parent.full_name}.{self.name}"

    def _can_run_locked(self) -> bool:
        g: Optional[InternalResourceGroup] = self
        while g is not None:
            if g.running >= g.hard_concurrency_limit:
                return False
            if (
                g.soft_memory_limit_bytes
                and g.memory_usage_bytes >= g.soft_memory_limit_bytes
            ):
                return False
            g = g.parent
        return True

    def _add_running_locked(self, delta: int):
        g: Optional[InternalResourceGroup] = self
        while g is not None:
            g.running += delta
            g = g.parent

    def submit(self, start: Callable[[], None]) -> str:
        """Either starts the query now (returns 'running'), queues it
        (returns 'queued'; `start` runs later), or raises
        QueryQueueFullError."""
        with self.lock:
            if self._can_run_locked():
                self._add_running_locked(1)
                run_now = True
            elif len(self.queue) >= self.max_queued:
                raise QueryQueueFullError(
                    f"Too many queued queries for \"{self.full_name}\" "
                    f"(max {self.max_queued})"
                )
            else:
                self.queue.append(start)
                run_now = False
        if run_now:
            start()
            return "running"
        return "queued"

    def finish(self):
        """Release one running slot and start queued queries anywhere in
        the tree that now fit (processQueuedQueries walks from the root:
        a slot freed under a shared parent can admit a sibling's query)."""
        root: InternalResourceGroup = self
        while root.parent is not None:
            root = root.parent
        to_start: List[Callable[[], None]] = []
        with self.lock:
            self._add_running_locked(-1)
            progress = True
            while progress:
                progress = False
                stack = [root]
                while stack:
                    g = stack.pop()
                    while g.queue and g._can_run_locked():
                        g._add_running_locked(1)
                        to_start.append(g.queue.popleft())
                        progress = True
                    stack.extend(g.children)
        for start in to_start:
            start()

    def add_memory_usage(self, delta: int):
        """Track admitted-query memory against this group (and its
        ancestors); a negative delta re-processes the queue, since a
        group blocked on its soft memory limit may now admit."""
        to_start: List[Callable[[], None]] = []
        with self.lock:
            g: Optional[InternalResourceGroup] = self
            root = self
            while g is not None:
                g.memory_usage_bytes = max(0, g.memory_usage_bytes + delta)
                root = g
                g = g.parent
            if delta < 0:
                stack = [root]
                while stack:
                    g = stack.pop()
                    while g.queue and g._can_run_locked():
                        g._add_running_locked(1)
                        to_start.append(g.queue.popleft())
                    stack.extend(g.children)
        for start in to_start:
            start()

    def stats(self) -> dict:
        with self.lock:
            return {
                "name": self.full_name,
                "running": self.running,
                "queued": len(self.queue),
                "hardConcurrencyLimit": self.hard_concurrency_limit,
                "maxQueued": self.max_queued,
                "softMemoryLimitBytes": self.soft_memory_limit_bytes,
                "memoryUsageBytes": self.memory_usage_bytes,
            }


class ResourceGroupManager:
    """Selector-based group assignment (InternalResourceGroupManager +
    the file-based SelectorSpec: first selector whose user/source regexes
    match wins; no match falls through to the root 'global' group)."""

    def __init__(self, config: Optional[dict] = None):
        # config: {"groups": [{"name", "hardConcurrencyLimit", "maxQueued",
        #                      "subGroups": [...]}, ...],
        #          "selectors": [{"user": regex, "source": regex,
        #                         "group": dotted.name}, ...]}
        self.groups: Dict[str, InternalResourceGroup] = {}
        self.selectors: List[dict] = []
        cfg = config or {}
        for g in cfg.get("groups", ()) or ():
            self._build_group(g, None)
        if "global" not in self.groups:
            self._build_group({"name": "global"}, None)
        for sel in cfg.get("selectors", ()) or ():
            self.selectors.append(sel)

    def _build_group(self, spec: dict, parent):
        g = InternalResourceGroup(
            spec["name"],
            int(spec.get("hardConcurrencyLimit", 100)),
            int(spec.get("maxQueued", 1000)),
            parent,
            soft_memory_limit_bytes=int(
                spec.get("softMemoryLimitBytes", 0)
            ),
        )
        self.groups[g.full_name] = g
        for sub in spec.get("subGroups", ()) or ():
            self._build_group(sub, g)
        return g

    def select(self, user: str, source: str = "") -> InternalResourceGroup:
        for sel in self.selectors:
            if "user" in sel and not re.fullmatch(sel["user"], user or ""):
                continue
            if "source" in sel and not re.fullmatch(
                sel["source"], source or ""
            ):
                continue
            g = self.groups.get(sel["group"])
            if g is not None:
                return g
        return self.groups["global"]

    def info(self) -> List[dict]:
        return [g.stats() for g in self.groups.values()]
