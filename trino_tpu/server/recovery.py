"""Coordinator crash recovery: durable query-state WAL + restart resume.

Reference non-parity (deliberately past it): the reference keeps the
whole query state machine (DispatchManager/QueryTracker) in coordinator
memory — a coordinator crash orphans every in-flight query even though
Project Tardigrade's committed FTE spools are sitting durably on disk.
Here every query-state transition is journaled through a write-ahead
intent log BEFORE it takes effect, using the same mmap'd torn-tail-
tolerant two-segment store contract as the flight recorder and incident
journal (pid-suffixed segments, crash-safe, readable after kill -9), so
a restarted coordinator can replay the log and pick the query back up:

  - ``query_submitted``  sql, user, slug, resource group, retry policy
  - ``query_planned``    fragment-graph digest (resume sanity check)
  - ``task_dispatched``  one task attempt POSTed to a worker
  - ``task_committed``   structural fragment signature + task index +
                         the committed spool path (the resume currency)
  - ``query_finished`` / ``query_failed``  terminal states

On boot with ``coordinator_recovery_dir`` set, :class:`RecoveryManager`
scans the WAL, re-registers every non-terminal query under its original
query id AND slug (the client's nextUri keeps working), waits for the
worker set to re-announce (discovery heartbeats target the fixed
coordinator URI, so survivors re-adopt themselves), then classifies:

  - retry_policy=task (FTE): re-plan the SQL, verify the fragment-graph
    digest, and re-enter the FaultTolerantScheduler seeded with the
    committed-spool map — only UNFINISHED tasks re-run; committed
    stages are reused byte-for-byte via the structural spool signatures
    (``QUERY_RESUMED`` journaled, cited by the doctor).
  - anything else (pipelined): the stream state died with the old
    process — fail with a structured retryable ``COORDINATOR_RESTART``
    error the client re-submits, and persist the orphan through the
    same history path as any failed query (``QUERY_ORPHANED``).

The seeded chaos site ``coordinator_death`` (utils/faults.py) hard-exits
the coordinator at a chosen WAL transition, AFTER the record lands in
the mmap'd segment — the crash is deterministic and the evidence
survives, exactly like ``worker_death``.
"""
from __future__ import annotations

import glob
import json
import mmap
import os
import threading
import time
from typing import Dict, List, Optional

# wire schema of one WAL record (lowerCamelCase, one naming regime with
# metrics/spans/journal events) — linted by scripts/check_metric_names.py
WAL_FIELDS = (
    "walId",
    "recordType",
    "queryId",
    "slug",
    "sql",
    "user",
    "source",
    "resourceGroup",
    "retryPolicy",
    "planDigest",
    "fragmentSig",
    "taskIndex",
    "spoolPath",
    "taskId",
    "uri",
    "state",
    "error",
    "detail",
    "ts",
)

# -- record types (the typed vocabulary replay() keys on) ----------------
QUERY_SUBMITTED = "query_submitted"
QUERY_PLANNED = "query_planned"
TASK_DISPATCHED = "task_dispatched"
TASK_COMMITTED = "task_committed"
QUERY_FINISHED = "query_finished"
QUERY_FAILED = "query_failed"

TERMINAL_TYPES = (QUERY_FINISHED, QUERY_FAILED)

DEFAULT_MAX_BYTES = 1 << 20
MAX_RECORD_BYTES = 8192
MIN_SEGMENT_BYTES = 1 << 16
_FILE_PREFIX = "wal-"

# the structured retryable error the client's retry loop re-submits on;
# rendered by server/protocol.py with errorType EXTERNAL + retriable
COORDINATOR_RESTART_CODE = "COORDINATOR_RESTART"

# WAL writer names must be unique per (pid, instance): two coordinators
# in one test process would otherwise reset each other's segments
_NAME_LOCK = threading.Lock()
_NAME_SEQ = 0


def _next_wal_name() -> str:
    global _NAME_SEQ
    with _NAME_LOCK:
        _NAME_SEQ += 1
        return f"{os.getpid()}-{_NAME_SEQ}"


class _Segment:
    """One preallocated mmap'd JSONL file of the on-disk WAL."""

    def __init__(self, path: str, size: int):
        self.path = path
        self.size = size
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            os.ftruncate(fd, size)
            self.mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self.offset = 0

    def reset(self):
        self.mm[: self.size] = b"\0" * self.size
        self.offset = 0

    def append(self, data: bytes) -> bool:
        if self.offset + len(data) > self.size:
            return False
        self.mm[self.offset : self.offset + len(data)] = data
        self.offset += len(data)
        return True

    def sync(self):
        try:
            self.mm.flush()
        except Exception:  # noqa: BLE001 — sync is advisory
            pass

    def close(self):
        try:
            self.mm.close()
        except Exception:  # noqa: BLE001
            pass


class CoordinatorWAL:
    """The coordinator's write-ahead intent log.

    Same durability contract as the incident journal: two preallocated
    mmap'd segments that alternate at half the byte budget, named by
    writer so a restarted coordinator never clobbers the crashed one's
    records (MAP_SHARED pages survive ``os._exit``).  The optional
    ``injector`` arms the seeded ``coordinator_death`` chaos site: the
    process hard-exits AFTER the chosen record lands in the segment —
    the transition is durably on record, the state change it announced
    never happened, which is exactly the torn state recovery must heal.
    """

    def __init__(
        self,
        directory: str,
        max_bytes: int = DEFAULT_MAX_BYTES,
        name: Optional[str] = None,
        injector=None,
    ):
        self.directory = str(directory)
        self.max_bytes = max(
            int(max_bytes or DEFAULT_MAX_BYTES), 2 * MIN_SEGMENT_BYTES
        )
        self.name = name or _next_wal_name()
        self._injector = injector
        self._lock = threading.Lock()
        self._next_id = 0
        self._segments: List[_Segment] = []
        self._active = 0
        os.makedirs(self.directory, exist_ok=True)
        seg_bytes = max(MIN_SEGMENT_BYTES, self.max_bytes // 2)
        for i in range(2):
            path = os.path.join(
                self.directory, f"{_FILE_PREFIX}{self.name}-{i}.jsonl"
            )
            seg = _Segment(path, seg_bytes)
            seg.reset()  # a reused path must not replay stale intents
            self._segments.append(seg)

    # ------------------------------------------------------------------
    def record(self, record_type: str, query_id: str = "", **fields) -> int:
        """Durably journal one state transition; returns its walId.

        The seeded ``coordinator_death`` site fires AFTER the append,
        keyed ``{recordType}:{queryId}`` so a chaos rule can pick the
        exact transition (e.g. ``{"match": "task_committed", "nth": 2}``
        dies at the second committed task of the run)."""
        rec = {"walId": 0, "recordType": str(record_type),
               "queryId": str(query_id or ""), "ts": time.time()}
        for k, v in fields.items():
            if v is not None:
                rec[k] = v
        data = self._encode(rec)
        with self._lock:
            self._next_id += 1
            rec["walId"] = self._next_id
            data = self._encode(rec)
            seg = self._segments[self._active]
            if not seg.append(data):
                self._active = 1 - self._active
                seg = self._segments[self._active]
                seg.reset()
                seg.append(data)
        from ..utils.metrics import REGISTRY

        REGISTRY.counter(
            "trino_tpu_coordinator_wal_records_total",
            "Coordinator WAL state-transition records, by record type",
        ).inc(type=str(record_type))
        if self._injector is not None and self._injector.fires(
            "coordinator_death", key=f"{record_type}:{query_id}"
        ):
            # the mmap'd record (and the injector's FAULT_INJECTED
            # journal event) survive the hard exit — kill -9 semantics
            os._exit(137)
        return rec["walId"]

    @staticmethod
    def _encode(rec: Dict) -> bytes:
        data = json.dumps(
            rec, separators=(",", ":"), default=str
        ).encode() + b"\n"
        if len(data) > MAX_RECORD_BYTES:
            slim = dict(rec)
            if "sql" in slim:
                slim["sql"] = str(slim["sql"])[:2000]
            slim["detail"] = {"truncated": True}
            data = json.dumps(
                slim, separators=(",", ":"), default=str
            ).encode() + b"\n"
        return data

    def sync(self):
        with self._lock:
            for seg in self._segments:
                seg.sync()

    def close(self):
        with self._lock:
            for seg in self._segments:
                seg.close()
            self._segments = []


# -- offline reader (restart scan + post-mortem tooling) -----------------


def read_wal_dir(directory: str) -> List[Dict]:
    """Parse every WAL segment in ``directory`` (all writer names) into
    records ordered by (ts, walId).  Torn trailing lines — the record
    being written when the coordinator died — and zeroed tail space are
    skipped, never an error."""
    records: List[Dict] = []
    for path in sorted(
        glob.glob(os.path.join(directory, _FILE_PREFIX + "*.jsonl"))
    ):
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            continue
        for line in data.split(b"\n"):
            line = line.strip(b"\0").strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn write: the crash interrupted this line
            if isinstance(rec, dict) and "recordType" in rec:
                records.append(rec)
    records.sort(key=lambda r: (r.get("ts", 0.0), r.get("walId", 0)))
    return records


class WalQuery:
    """One query's state reconstructed from its WAL records."""

    def __init__(self, query_id: str):
        self.query_id = query_id
        self.sql = ""
        self.user = "user"
        self.source = ""
        self.slug = ""
        self.resource_group = ""
        self.retry_policy = ""
        self.plan_digest: Optional[str] = None
        self.terminal: Optional[str] = None  # FINISHED | FAILED | None
        self.error: Optional[str] = None
        self.submitted_ts = 0.0
        self.last_ts = 0.0
        # fragmentSig -> {taskIndex: committed spool path}: the currency
        # a resumed FaultTolerantScheduler redeems via the structural
        # spool signatures — identical fragments reuse these verbatim
        self.committed: Dict[str, Dict[int, str]] = {}
        self.dispatched = 0

    @property
    def resumable(self) -> bool:
        """FTE queries resume from committed spools; pipelined queries'
        stream state died with the old coordinator process."""
        return self.terminal is None and self.retry_policy == "task"

    def committed_lists(self) -> Dict[str, List[Optional[str]]]:
        """``{fragmentSig: [task_index -> path-or-None]}`` in the shape
        FaultTolerantScheduler's ``precommitted`` seeding expects."""
        out: Dict[str, List[Optional[str]]] = {}
        for sig, by_task in self.committed.items():
            if not by_task:
                continue
            width = max(by_task) + 1
            out[sig] = [by_task.get(i) for i in range(width)]
        return out


def replay_wal(records: List[Dict]) -> Dict[str, WalQuery]:
    """Fold WAL records into per-query reconstructed state (all queries,
    terminal and not — callers filter)."""
    queries: Dict[str, WalQuery] = {}
    for rec in records:
        qid = str(rec.get("queryId") or "")
        if not qid:
            continue
        wq = queries.get(qid)
        if wq is None:
            wq = queries[qid] = WalQuery(qid)
        rtype = rec.get("recordType")
        wq.last_ts = max(wq.last_ts, float(rec.get("ts") or 0.0))
        if rtype == QUERY_SUBMITTED:
            wq.sql = str(rec.get("sql") or wq.sql)
            wq.user = str(rec.get("user") or wq.user)
            wq.source = str(rec.get("source") or wq.source)
            wq.slug = str(rec.get("slug") or wq.slug)
            wq.resource_group = str(
                rec.get("resourceGroup") or wq.resource_group
            )
            wq.retry_policy = str(rec.get("retryPolicy") or wq.retry_policy)
            wq.submitted_ts = float(rec.get("ts") or 0.0)
        elif rtype == QUERY_PLANNED:
            wq.plan_digest = rec.get("planDigest") or wq.plan_digest
        elif rtype == TASK_DISPATCHED:
            wq.dispatched += 1
        elif rtype == TASK_COMMITTED:
            sig = str(rec.get("fragmentSig") or "")
            path = rec.get("spoolPath")
            idx = rec.get("taskIndex")
            if sig and path is not None and idx is not None:
                wq.committed.setdefault(sig, {})[int(idx)] = str(path)
        elif rtype == QUERY_FINISHED:
            wq.terminal = "FINISHED"
        elif rtype == QUERY_FAILED:
            wq.terminal = "FAILED"
            wq.error = rec.get("error") or wq.error
    return queries


def plan_digest(plan) -> str:
    """Deterministic digest of the optimized plan's structure — replayed
    SQL must re-plan to the same shape before committed spools are
    trusted (a catalog/stats change between boots would silently read
    the wrong stage's bytes)."""
    import hashlib

    from ..serde import plan_to_json

    doc = json.dumps(
        plan_to_json(plan), separators=(",", ":"),
        sort_keys=True, default=str,
    )
    return hashlib.blake2b(doc.encode(), digest_size=16).hexdigest()


class RecoveryManager:
    """Restart-time recovery: scan, re-register, re-adopt, resume.

    Constructed by the coordinator when ``coordinator_recovery_dir`` is
    set.  ``register()`` runs synchronously during coordinator boot —
    every non-terminal query is back in the tracker (same query id, same
    slug) before the HTTP server answers its first poll.  ``run()``
    executes on a background thread: wait (bounded by
    ``coordinator_recovery_window_s``) for discovery re-announcements to
    rebuild the live worker set, then resume or orphan each query."""

    def __init__(self, coordinator, directory: str, window_s: float):
        self.coordinator = coordinator
        self.directory = directory
        self.window_s = float(window_s)
        self.pending: List[WalQuery] = []
        self.resumed: List[str] = []
        self.orphaned: List[str] = []
        self.done = threading.Event()
        self._started = time.time()

    # -- boot-time (synchronous) ----------------------------------------
    def register(self) -> int:
        """Scan the WAL and re-register every non-terminal query under
        its original id + slug; returns how many are pending recovery."""
        try:
            records = read_wal_dir(self.directory)
        except Exception:
            records = []
        # (our own WAL's segments were reset at construction, so the
        # scan only ever sees the crashed coordinators' records)
        for wq in replay_wal(records).values():
            if wq.terminal is not None or not wq.sql:
                continue
            self.pending.append(wq)
        self.pending.sort(key=lambda w: w.submitted_ts)
        co = self.coordinator
        for wq in self.pending:
            from .coordinator import QueryExecution

            q = QueryExecution(wq.query_id, wq.sql, wq.user)
            if wq.slug:
                q.slug = wq.slug
            q.state = "QUEUED"
            q.recovered = True
            co.queries[wq.query_id] = q
        if not self.pending:
            self.done.set()
        return len(self.pending)

    # -- background (after the server is answering polls) ---------------
    def run(self):
        from ..obs import journal
        from ..utils.metrics import REGISTRY

        co = self.coordinator
        t0 = time.time()
        try:
            if not self.pending:
                return
            journal.emit(
                journal.COORDINATOR_RESTART,
                node_id=co.node_id,
                severity=journal.WARN,
                pendingQueries=len(self.pending),
                recoveryDir=self.directory,
            )
            deadline = self._started + self.window_s
            # re-adopt the live worker set from discovery re-announcements
            # before dispatching anything — or terminalizing anything: an
            # orphan's retryable error invites an instant re-submit, which
            # must land on a serviceable cluster, not NO_NODES_AVAILABLE
            co.node_manager.await_alive(
                1, timeout=max(deadline - time.time(), 0.0)
            )
            for wq in self.pending:
                q = co.queries.get(wq.query_id)
                if q is None:
                    continue
                if wq.resumable and co.node_manager.alive():
                    try:
                        self._resume(q, wq)
                        continue
                    except Exception as e:  # fall through to orphan
                        orphan_error = (
                            f"{COORDINATOR_RESTART_CODE}: resume failed "
                            f"({type(e).__name__}: {e}); please re-submit"
                        )
                        self._orphan(q, wq, error=orphan_error)
                        continue
                self._orphan(q, wq)
        finally:
            self.done.set()
            REGISTRY.histogram(
                "trino_tpu_coordinator_recovery_wall_seconds",
                "Wall time of one restart-recovery pass "
                "(scan + re-adopt + resume/orphan)",
            ).observe(time.time() - t0)

    # ------------------------------------------------------------------
    def _resume(self, q, wq: WalQuery):
        """Re-enter the FTE scheduler seeded with the committed-spool
        map: identical fragments (structural signature match) reuse
        their spools verbatim, so only UNFINISHED tasks re-run."""
        from ..obs import journal
        from ..sql import ast
        from ..sql.parser import parse
        from ..utils.metrics import REGISTRY

        co = self.coordinator
        with q.lock:
            q.state = "PLANNING"
        stmt = parse(q.sql)
        if not isinstance(stmt, ast.Query):
            raise RuntimeError("only plain queries are resumable")
        plan = co.session._plan_stmt(stmt)
        if wq.plan_digest and plan_digest(plan) != wq.plan_digest:
            # the replayed SQL planned to a different shape (stats or
            # catalog drift between boots): committed spools cannot be
            # trusted — orphan instead of silently reading wrong bytes
            raise RuntimeError("plan digest mismatch after restart")
        precommitted = wq.committed_lists()
        with q.lock:
            q.state = "RUNNING"
        # the resumed run spools under an epoch-suffixed query id so new
        # attempt numbers can never collide with the crashed epoch's
        # dirs; committed OLD paths are absolute, so reads still work
        page = co._run_fte(
            q, plan,
            qid=f"{wq.query_id}_rec",
            precommitted=precommitted,
            wal_qid=wq.query_id,
        )
        with q.lock:
            q.page = page
            q.types = [c.type for c in page.columns]
            q.state = "FINISHED"
            q.finished = time.time()
        REGISTRY.counter(
            "trino_tpu_query_finished_total",
            "Queries that reached FINISHED",
        ).inc()
        REGISTRY.counter(
            "trino_tpu_coordinator_recovered_queries_total",
            "Queries resumed from the WAL after a coordinator restart",
        ).inc()
        co.recovered_queries += 1
        reused = sum(
            1 for paths in precommitted.values() for p in paths if p
        )
        event_id = journal.emit(
            journal.QUERY_RESUMED, query_id=wq.query_id,
            node_id=co.node_id, severity=journal.WARN,
            reusedSpools=reused, retryPolicy=wq.retry_policy,
        )
        q.resume_event_id = event_id
        try:
            co._finalize_query(q)
        except Exception:
            pass
        # the crashed epoch's spool tree is only safe to drop now that
        # the resumed run no longer reads its committed attempts
        try:
            from ..exchange.filesystem import FileSystemExchangeManager

            FileSystemExchangeManager().cleanup_query(wq.query_id)
        except Exception:
            pass

    def _orphan(self, q, wq: WalQuery, error: Optional[str] = None):
        """Terminalize a non-resumable query with the structured
        retryable COORDINATOR_RESTART error, routed through the same
        persist-with-errorCode path as any failed query — the orphan is
        visible in system.runtime.completed_queries after restart."""
        from ..obs import journal
        from ..utils.metrics import REGISTRY

        co = self.coordinator
        with q.lock:
            q.state = "FAILED"
            q.error = error or (
                f"{COORDINATOR_RESTART_CODE}: coordinator restarted "
                "mid-query; stream state was lost — please re-submit"
            )
            q.finished = time.time()
        REGISTRY.counter(
            "trino_tpu_query_failed_total", "Queries that reached FAILED"
        ).inc()
        REGISTRY.counter(
            "trino_tpu_coordinator_orphaned_queries_total",
            "Non-resumable queries orphaned by a coordinator restart",
        ).inc()
        co.orphaned_queries += 1
        event_id = journal.emit(
            journal.QUERY_ORPHANED, query_id=wq.query_id,
            node_id=co.node_id, severity=journal.ERROR,
            retryPolicy=wq.retry_policy or "none",
            dispatchedTasks=wq.dispatched,
        )
        q.orphan_event_id = event_id
        try:
            co._finalize_query(q)
        except Exception:
            pass
        try:
            from ..exchange.filesystem import FileSystemExchangeManager

            FileSystemExchangeManager().cleanup_query(wq.query_id)
        except Exception:
            pass
