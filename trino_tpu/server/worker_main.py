"""Standalone worker entrypoint: ``python -m trino_tpu.server.worker_main``.

The in-process DistributedQueryRunner is the default test topology, but a
node-churn chaos harness needs a worker the OS can actually kill — an
in-process WorkerServer shares its fate with the test runner, so kill -9
semantics (no drain, no goodbye announcement, sockets refuse instantly)
are only reachable with a real child process.  This entrypoint boots one
WorkerServer against a running coordinator, prints a single JSON line
``{"nodeId": ..., "uri": ...}`` on stdout so the parent can target it,
and then sleeps until killed.

Worker-level fault injection (``--fault-injection``) arms chaos sites the
in-process runner must never fire — ``worker_death`` hard-exits with
status 137 at task start, exactly like the OOM killer.
"""
from __future__ import annotations

import argparse
import json
import signal
import sys
import time


DEFAULT_CATALOGS = [["tpch", "tpch", {"tpch.scale-factor": 0.01}]]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="run one trino_tpu worker process"
    )
    p.add_argument(
        "--coordinator", required=True,
        help="coordinator base URI to announce to",
    )
    p.add_argument(
        "--catalogs", default=None,
        help="JSON [[name, connector, config], ...]; default: tpch sf0.01",
    )
    p.add_argument("--port", type=int, default=0)
    p.add_argument(
        "--fault-injection", default=None,
        help="worker-level FaultInjector spec (JSON {seed, site: rule})",
    )
    # multi-host identity: a worker launched with these flags announces
    # itself as a host-sized capacity unit (one process owning a slice of
    # the global device mesh).  The device count itself comes from
    # XLA_FLAGS=--xla_force_host_platform_device_count=K, which the
    # PARENT must place in the environment — it is read at first jax
    # import (the enable_x64() call below), long before argparse could
    # act on a flag.
    p.add_argument(
        "--host", default=None,
        help="host identity to announce (multi-host topology)",
    )
    p.add_argument(
        "--process-index", type=int, default=None,
        help="process index within the multi-host cluster",
    )
    args = p.parse_args(argv)

    # parity with the in-process topology: conftest/force_cpu enable
    # x64 everywhere else, and a worker stuck on int32 overflows on
    # wide aggregates the coordinator planned in int64
    from .. import enable_x64

    enable_x64()

    from ..testing.runner import _build_catalogs
    from .worker import WorkerServer

    spec = json.loads(args.catalogs) if args.catalogs else DEFAULT_CATALOGS
    catalogs = _build_catalogs(
        [(name, conn, cfg) for name, conn, cfg in spec]
    )
    fault_injection = (
        json.loads(args.fault_injection) if args.fault_injection else None
    )
    w = WorkerServer(
        catalogs,
        coordinator_uri=args.coordinator,
        port=args.port,
        fault_injection=fault_injection,
        host=args.host,
        process_index=args.process_index,
    ).start()
    print(json.dumps({"nodeId": w.node_id, "uri": w.uri}), flush=True)

    # SIGTERM runs the drain walk (the operator's `kill` is a graceful
    # decommission; only SIGKILL is churn): refuse new tasks, finish and
    # spool what's running, announce DRAINED, then exit
    draining = {"flag": False}

    def _on_sigterm(_sig, _frame):
        draining["flag"] = True
        w.start_drain()

    signal.signal(signal.SIGTERM, _on_sigterm)
    try:
        while True:
            time.sleep(0.2)
            if draining["flag"] and w.state == "DRAINED":
                time.sleep(1.0)  # let the DRAINED announcement land
                w.stop()
                return 0
    except KeyboardInterrupt:
        w.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
