"""Coordinator autoscaler: elastic worker count with zero failed queries.

The control loop watches three signals every tick — resource-group
backlog (queries queued across the tree), memory-admission wait, and
cluster pool pressure — and moves the worker count between
``min_workers`` and ``max_workers``:

  * **scale-out**: sustained backlog or pool pressure adds a worker via
    the pluggable ``scale_out`` callback (the test/bench harness wires
    ``DistributedQueryRunner.add_subprocess_worker``; late joiners are
    schedulable the moment they announce — PR 12 proved the late-join
    path).
  * **scale-in**: sustained surplus capacity drains the
    highest-lexicographic ACTIVE worker through the PR 10 lifecycle: the
    coordinator marks it DRAINING in the node manager FIRST (so the
    scheduler stops placing on it before the worker even hears), then
    PUTs ``/v1/info/state DRAINING``; the worker finishes running tasks,
    flushes telemetry, and announces DRAINED.  Running queries never
    notice — scale-in mid-traffic completes with zero failed queries.

Every action lands in the incident journal (``scale_out`` /
``scale_in`` events) so the query doctor's overload rule can tell
"the cluster was saturated and grew" from "the cluster fell over".
"""
from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import Callable, List, Optional

from ..utils.metrics import REGISTRY

# how long a signal must persist before the loop acts on it: reactive
# enough for a serving bench, calm enough not to flap on one burst
DEFAULT_HOLD_S = 0.5
DEFAULT_COOLDOWN_S = 2.0
DEFAULT_IDLE_GRACE_S = 1.5
DEFAULT_BACKLOG_HIGH = 4
DEFAULT_PRESSURE_HIGH = 0.85


class Autoscaler:
    """Queue-depth + pool-pressure driven worker elasticity."""

    def __init__(
        self,
        coordinator,
        scale_out: Optional[Callable[[], object]] = None,
        min_workers: int = 1,
        max_workers: int = 4,
        backlog_high: int = DEFAULT_BACKLOG_HIGH,
        pressure_high: float = DEFAULT_PRESSURE_HIGH,
        hold_s: float = DEFAULT_HOLD_S,
        cooldown_s: float = DEFAULT_COOLDOWN_S,
        idle_grace_s: float = DEFAULT_IDLE_GRACE_S,
    ):
        self.coordinator = coordinator
        self.scale_out_cb = scale_out
        self.min_workers = max(int(min_workers), 1)
        self.max_workers = max(int(max_workers), self.min_workers)
        self.backlog_high = max(int(backlog_high), 1)
        self.pressure_high = float(pressure_high)
        self.hold_s = float(hold_s)
        self.cooldown_s = float(cooldown_s)
        self.idle_grace_s = float(idle_grace_s)
        self._lock = threading.Lock()
        self._hot_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._last_action = 0.0
        self._busy = False
        # action records for bench/system-table reporting:
        # {"action", "nodeId", "workers", "backlog", "ts"}
        self.events: List[dict] = []

    # -- signals --------------------------------------------------------
    def _backlog(self) -> int:
        co = self.coordinator
        queued = co.resource_groups.total_queued()
        waiting = len(co.admission.stats().get("waiting") or ())
        return queued + waiting

    def _pressure(self) -> float:
        cm = self.coordinator.cluster_memory
        total = cm.cluster_total_bytes()
        if total <= 0:
            return 0.0
        return cm.cluster_reserved_bytes() / total

    def _topology_of(self, node_id: str) -> Optional[dict]:
        """The node's announced multi-host topology, if it has one —
        when set, this capacity unit is HOST-sized (a whole process with
        its own device slice), and the scale event says so."""
        nm = self.coordinator.node_manager
        try:
            with nm.lock:
                n = nm.nodes.get(node_id)
                if n is not None and n.topology:
                    return dict(n.topology)
        except Exception:  # noqa: BLE001 — telemetry only
            pass
        return None

    def _record(self, action: str, node_id: str, workers: int,
                backlog: int, topology: Optional[dict] = None):
        from ..obs import journal

        detail = {}
        if topology:
            # host-granular elasticity: the unit admitted/retired is a
            # whole host process and its device slice, not a bare node
            detail = {
                "host": topology.get("host", ""),
                "processIndex": topology.get("processIndex", 0),
                "localDevices": topology.get("localDevices", 0),
            }
        event_id = journal.emit(
            journal.SCALE_OUT if action == "scale_out"
            else journal.SCALE_IN,
            node_id=node_id,
            severity=journal.INFO,
            workers=workers,
            backlog=backlog,
            **detail,
        )
        REGISTRY.counter(
            "trino_tpu_autoscaler_actions_total",
            "Autoscaler scale actions, by direction",
        ).inc(action=action)
        self.events.append({
            "action": action,
            "nodeId": node_id,
            "workers": workers,
            "backlog": backlog,
            "eventId": event_id,
            "ts": time.time(),
            **detail,
        })

    # -- the loop body --------------------------------------------------
    def tick(self, now: Optional[float] = None):
        """One control decision; called from the coordinator's
        enforcement loop (so it shares that loop's cadence).  Actions
        run on a helper thread — ``add_subprocess_worker`` blocks until
        the new worker announces, and the enforcement loop must keep
        enforcing memory limits meanwhile."""
        nm = self.coordinator.node_manager
        if nm is None:
            return
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._busy:
                return
            backlog = self._backlog()
            pressure = self._pressure()
            alive = nm.alive()
            hot = backlog >= self.backlog_high or (
                pressure >= self.pressure_high
            )
            idle = backlog == 0 and pressure < self.pressure_high / 2
            self._hot_since = (
                (self._hot_since or now) if hot else None
            )
            self._idle_since = (
                (self._idle_since or now) if idle else None
            )
            if now - self._last_action < self.cooldown_s:
                return
            if (
                hot
                and self._hot_since is not None
                and now - self._hot_since >= self.hold_s
                and len(alive) < self.max_workers
                and self.scale_out_cb is not None
            ):
                self._busy = True
                self._last_action = now
                self._hot_since = None
                action = ("out", None, backlog, len(alive))
            elif (
                idle
                and self._idle_since is not None
                and now - self._idle_since >= self.idle_grace_s
                and len(alive) > self.min_workers
            ):
                self._busy = True
                self._last_action = now
                self._idle_since = None
                # drain the LAST worker in stable order: the runner's
                # most recently added subprocess worker sorts after the
                # long-lived in-process ones in the common harness
                victim = alive[-1]
                action = ("in", victim, backlog, len(alive))
            else:
                return
        threading.Thread(
            target=self._act, args=(action,), daemon=True
        ).start()

    def _act(self, action):
        direction, victim, backlog, workers = action
        try:
            if direction == "out":
                try:
                    added = self.scale_out_cb()
                except Exception:  # noqa: BLE001 — a failed spawn must
                    return         # not wedge the loop; cooldown retries
                # the harness spawner returns (Popen, node_id, uri):
                # resolve the admitted node's topology so a host-sized
                # admission is journaled as one
                new_id = ""
                if isinstance(added, (tuple, list)) and len(added) >= 2:
                    new_id = str(added[1])
                self._record(
                    "scale_out", new_id, workers + 1, backlog,
                    topology=self._topology_of(new_id) if new_id else None,
                )
            else:
                node_id, uri = victim
                topo = self._topology_of(node_id)
                self._drain(node_id, uri)
                self._record(
                    "scale_in", node_id, workers - 1, backlog,
                    topology=topo,
                )
        finally:
            with self._lock:
                self._busy = False

    def _drain(self, node_id: str, uri: str):
        """Graceful decommission: unschedule FIRST (node manager state
        wins immediately — zero new placements race the drain), then
        tell the worker, which finishes running tasks and announces
        DRAINED on its own."""
        nm = self.coordinator.node_manager
        nm.announce(node_id, uri, state="DRAINING")
        try:
            req = urllib.request.Request(
                f"{uri}/v1/info/state",
                data=json.dumps("DRAINING").encode(),
                headers={"Content-Type": "application/json"},
                method="PUT",
            )
            urllib.request.urlopen(req, timeout=5.0).read()
        except Exception:  # noqa: BLE001 — a dead victim is already
            pass           # unscheduled; the failure detector escalates

    def stats(self) -> dict:
        with self._lock:
            return {
                "minWorkers": self.min_workers,
                "maxWorkers": self.max_workers,
                "backlogHigh": self.backlog_high,
                "pressureHigh": self.pressure_high,
                "events": list(self.events),
            }
