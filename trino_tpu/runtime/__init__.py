"""Runtime supervision: the crash-containment boundary around the TPU.

``runtime/supervisor.py`` owns the supervised kernel-dispatch boundary —
every device execution in ``exec/`` crosses it, so a device loss or wedge
is attributed (breadcrumb naming the culprit kernel), contained (device
quarantine + blacklist), and survived (degraded CPU execution).
"""
from .supervisor import (  # noqa: F401
    Breadcrumb,
    DeviceFaultError,
    DeviceSupervisor,
    default_supervisor,
    fallback_counts,
    last_breadcrumb,
    reset_default_supervisor,
)
