"""Supervised kernel dispatch: crash attribution, quarantine, fallback.

The real-TPU bench regressed to ``JaxRuntimeError: UNAVAILABLE: TPU worker
process crashed`` on nearly every config (BENCH_r05) with nothing naming
the culprit kernel, and a single fault killed the whole worker.  Following
Dean & Barroso's *The Tail at Scale* (CACM 2013) — tolerate component
failure at the system level instead of assuming it away — the TPU runtime
is treated here as a component that WILL crash and wedge:

- every device execution in ``exec/`` crosses :meth:`DeviceSupervisor.
  dispatch`, which records a crash-forensics :class:`Breadcrumb` (kernel
  signature, input shapes/dtypes, HBM reservation, query/task id) BEFORE
  the dispatch, so an ``UNAVAILABLE``/device-loss error — or a wedge
  caught by the watchdog timeout on the dispatch thread — is rethrown as
  a structured :class:`DeviceFaultError` naming the culprit kernel (the
  bisect handle ROADMAP Open item 1 asks for);
- on fault the device goes QUARANTINED with capped-exponential-backoff
  re-probe (a tiny canary kernel); after ``device_fault_max_strikes``
  faults inside STRIKE_WINDOW_S it is BLACKLISTED for the process
  lifetime;
- the owning worker degrades instead of refusing: executors catch the
  fault and re-run the fragment eagerly on the CPU backend, and the node
  advertises DEGRADED through ``/v1/info`` + announcements so schedulers
  route new work toward healthy nodes while FTE retries elsewhere.

Two seeded fault sites (``device_loss``, ``device_wedge`` in
``utils/faults.py``) make the whole path deterministically testable on
the CPU backend.  One supervisor exists per node (each ``WorkerServer``
owns one; a ``Session`` owns one for in-process execution) because the
distributed test runner hosts many nodes in one process — quarantine
must stay node-local.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from ..utils.metrics import REGISTRY

# device health (per device)
ACTIVE = "ACTIVE"
QUARANTINED = "QUARANTINED"
BLACKLISTED = "BLACKLISTED"

# numeric encoding for the trino_tpu_device_state gauge
_STATE_CODE = {ACTIVE: 0.0, QUARANTINED: 1.0, BLACKLISTED: 2.0}

# faults inside this window count toward the blacklist strike total;
# older strikes age out (a flaky hour should not doom a week-old process)
STRIKE_WINDOW_S = 600.0

# re-probe backoff never exceeds this, so a recovered device is found
# within half a minute even after a long quarantine
MAX_PROBE_BACKOFF_S = 30.0

# error-message signatures of a LOST device (tunnel/worker crash, device
# dropped off the bus).  Deliberately conservative: INVALID_ARGUMENT
# (poisoned executable) and compile OOM keep their existing targeted
# handlers in exec/local.py and must NOT be swallowed here.
_DEVICE_LOSS_SIGNATURES = (
    "UNAVAILABLE",
    "worker process crashed",
    "DATA_LOSS",
    "DataLoss",
    "device is lost",
    "Device lost",
    "failed to connect to all addresses",
)


def _counter(name: str, help: str):
    return REGISTRY.counter(name, help)


class Breadcrumb:
    """Crash forensics for ONE dispatch, recorded before it happens.

    When the dispatch never returns (crash, wedge, process death) this is
    the only attribution that exists — which is why bench.py persists the
    last one into the BENCH artifact for every crashed config."""

    def __init__(
        self,
        kernel: str,
        query_id: str = "",
        task_id: str = "",
        node_id: str = "",
        mode: str = "jit",
        shapes: Optional[dict] = None,
        hbm_reserved_bytes: int = 0,
    ):
        self.kernel = kernel          # fragment digest / "eager-N" / site
        self.query_id = query_id
        self.task_id = task_id
        self.node_id = node_id
        self.mode = mode              # jit | eager | device_get | probe
        self.shapes = shapes or {}    # input name -> "dtype[shape]"
        self.hbm_reserved_bytes = int(hbm_reserved_bytes)
        self.ts = time.time()

    def to_dict(self) -> dict:
        return {
            "kernel": self.kernel,
            "queryId": self.query_id,
            "taskId": self.task_id,
            "nodeId": self.node_id,
            "mode": self.mode,
            "shapes": dict(self.shapes),
            "hbmReservedBytes": self.hbm_reserved_bytes,
            "ts": self.ts,
        }

    def __str__(self):
        return (
            f"kernel={self.kernel} mode={self.mode} query={self.query_id}"
            f"{' task=' + self.task_id if self.task_id else ''}"
            f" hbm_reserved={self.hbm_reserved_bytes}"
        )


class DeviceFaultError(RuntimeError):
    """A device execution was lost or wedged; names the culprit kernel.

    NOT a ``jax.errors.JaxRuntimeError`` subclass on purpose: the
    executor's existing JaxRuntimeError handler (poisoned-executable
    eviction, compile-OOM streaming fallback) must never intercept a
    device fault — this error's handler is the degraded CPU fallback."""

    def __init__(self, kind: str, breadcrumb: Breadcrumb,
                 cause: Optional[BaseException] = None):
        self.kind = kind              # device_loss | device_wedge | ...
        self.breadcrumb = breadcrumb
        self.cause_text = f"{type(cause).__name__}: {cause}" if cause else ""
        detail = f" [{self.cause_text[:200]}]" if cause else ""
        super().__init__(f"{kind}: {breadcrumb}{detail}")


class _WedgeTimeout(Exception):
    """Internal: the watchdog join timed out (dispatch thread wedged)."""


class _SimulatedDeviceLoss(RuntimeError):
    """Seeded ``device_loss`` firing: carries the real fault's signature
    so the classifier treats it exactly like a genuine TPU crash."""


def _is_device_loss(exc: BaseException) -> bool:
    if isinstance(exc, _SimulatedDeviceLoss):
        return True
    msg = str(exc)
    return any(sig in msg for sig in _DEVICE_LOSS_SIGNATURES)


class _DeviceHealth:
    """Per-device fault bookkeeping (state machine + strike window)."""

    def __init__(self, device_id: int):
        self.device_id = device_id
        self.state = ACTIVE
        self.strikes: deque = deque()   # fault timestamps in the window
        self.faults_total = 0
        self.probe_failures = 0
        self.next_probe = 0.0
        self.last_fault_kind = ""


# process-wide forensics: the LAST breadcrumb recorded by ANY supervisor
# instance, plus fallback counters — bench.py reads these after a config
# crashed without having to know which session/worker dispatched
_GLOBAL_LOCK = threading.Lock()
_LAST_BREADCRUMB: Optional[Breadcrumb] = None
_FALLBACKS = {"attempted": 0, "completed": 0}


def last_breadcrumb() -> Optional[dict]:
    """The most recent dispatch breadcrumb in this process (or None)."""
    with _GLOBAL_LOCK:
        return _LAST_BREADCRUMB.to_dict() if _LAST_BREADCRUMB else None


def fallback_counts() -> dict:
    """Degraded-CPU-fallback attempts/completions in this process."""
    with _GLOBAL_LOCK:
        return dict(_FALLBACKS)


def _note_fallback(key: str):
    with _GLOBAL_LOCK:
        _FALLBACKS[key] += 1


class DeviceSupervisor:
    """Supervised dispatch boundary + device state machine for one node.

    Wire-up mirrors ``LocalMemoryManager``: the worker/session that owns
    the node creates one and threads it to executors via the exec config
    (``device_supervisor``); chaos specs attach through
    ``fault_injector`` exactly like the memory manager's ``oom`` site."""

    def __init__(
        self,
        node_id: str = "local",
        max_strikes: int = 3,
        probe_backoff_s: float = 1.0,
        watchdog_timeout_s: float = 60.0,
        fault_injector=None,
    ):
        self.node_id = node_id
        self.max_strikes = int(max_strikes)
        self.probe_backoff_s = float(probe_backoff_s)
        self.watchdog_timeout_s = float(watchdog_timeout_s)
        self.fault_injector = fault_injector
        self._lock = threading.RLock()
        self._devices: Dict[int, _DeviceHealth] = {0: _DeviceHealth(0)}
        self.last_breadcrumb: Optional[Breadcrumb] = None
        self.breadcrumbs: deque = deque(maxlen=32)
        self.fallback_attempted = 0
        self.fallback_completed = 0
        # black-box dispatch ring (obs/flight_recorder.py): memory-only by
        # default — the system.runtime.flight_recorder table and bench
        # forensics read the live tail; configure() upgrades it to the
        # crash-safe mmap'd on-disk ring when flight_recorder_dir is set
        from ..obs.flight_recorder import FlightRecorder

        self.flight_recorder = FlightRecorder(
            None, max_records=256, name=node_id
        )
        self._publish_state()

    # -- configuration -------------------------------------------------
    def configure(self, props) -> "DeviceSupervisor":
        """Adopt session/task properties (dict or SessionProperties)."""
        get = props.get if hasattr(props, "get") else None
        if get is None:
            return self
        for attr, key, cast in (
            ("max_strikes", "device_fault_max_strikes", int),
            ("probe_backoff_s", "device_probe_backoff_s", float),
            ("watchdog_timeout_s", "device_watchdog_timeout_s", float),
        ):
            v = get(key)
            if v is not None and v != "":
                try:
                    setattr(self, attr, cast(v))
                except (TypeError, ValueError):
                    pass
        self._configure_flight_recorder(
            get("flight_recorder_dir"), get("flight_recorder_max_records")
        )
        return self

    def _configure_flight_recorder(self, directory, max_records):
        """Re-point the dispatch ring: an empty dir keeps the in-memory
        mirror; a directory turns on the crash-safe on-disk segments."""
        from ..obs.flight_recorder import FlightRecorder

        directory = str(directory or "").strip() or None
        try:
            max_records = int(max_records or 0) or 512
        except (TypeError, ValueError):
            max_records = 512
        cur = self.flight_recorder
        if (
            cur is not None
            and cur.directory == directory
            and (directory is None or cur.max_records == max_records)
        ):
            return
        if cur is not None:
            cur.close()
        self.flight_recorder = FlightRecorder(
            directory, max_records=max_records, name=self.node_id
        )

    # -- state queries -------------------------------------------------
    def _device(self, device_id: int = 0) -> _DeviceHealth:
        d = self._devices.get(device_id)
        if d is None:
            d = self._devices[device_id] = _DeviceHealth(device_id)
        return d

    def healthy(self, device_id: int = 0) -> bool:
        with self._lock:
            return self._device(device_id).state == ACTIVE

    def device_state(self, device_id: int = 0) -> str:
        with self._lock:
            return self._device(device_id).state

    def node_state(self) -> str:
        """ACTIVE (all devices fine) / DEGRADED (some device sick, CPU
        fallback keeps the node serving) / QUARANTINED (every device out
        AND fallback is off — the node cannot host fragments at all)."""
        with self._lock:
            states = [d.state for d in self._devices.values()]
        if all(s == ACTIVE for s in states):
            return "ACTIVE"
        if self.cpu_fallback_enabled:
            return "DEGRADED"
        return "QUARANTINED" if all(s != ACTIVE for s in states) \
            else "DEGRADED"

    # executors consult this before degrading; schedulers consult the
    # announced node_state() — a node with fallback disabled quarantines
    # outright instead of degrading
    cpu_fallback_enabled = True

    def snapshot(self) -> dict:
        """Announcement/``/v1/info`` payload: node + per-device health."""
        with self._lock:
            devices = [
                {
                    "id": d.device_id,
                    "state": d.state,
                    "strikes": len(d.strikes),
                    "faults": d.faults_total,
                    "lastFaultKind": d.last_fault_kind,
                }
                for d in self._devices.values()
            ]
            bc = self.last_breadcrumb
        return {
            "state": self.node_state(),
            "devices": devices,
            "fallbacksAttempted": self.fallback_attempted,
            "fallbacksCompleted": self.fallback_completed,
            "lastBreadcrumb": bc.to_dict() if bc else None,
        }

    # -- breadcrumbs ---------------------------------------------------
    def _record(self, bc: Breadcrumb):
        global _LAST_BREADCRUMB
        bc.node_id = bc.node_id or self.node_id
        with self._lock:
            self.last_breadcrumb = bc
            self.breadcrumbs.append(bc)
        with _GLOBAL_LOCK:
            _LAST_BREADCRUMB = bc

    # -- fault accounting ----------------------------------------------
    def _fault(self, bc: Breadcrumb, kind: str,
               cause: Optional[BaseException],
               device_id: int = 0) -> DeviceFaultError:
        now = time.time()
        transition = None
        with self._lock:
            d = self._device(device_id)
            d.faults_total += 1
            d.last_fault_kind = kind
            d.strikes.append(now)
            while d.strikes and now - d.strikes[0] > STRIKE_WINDOW_S:
                d.strikes.popleft()
            if d.state != BLACKLISTED:
                if len(d.strikes) >= self.max_strikes:
                    # N strikes inside the window: out for the process
                    # lifetime — no probe ever reinstates it
                    d.state = BLACKLISTED
                    transition = BLACKLISTED
                else:
                    d.state = QUARANTINED
                    transition = QUARANTINED
                    d.probe_failures += 1
                    d.next_probe = now + self._backoff(d.probe_failures)
            strikes = len(d.strikes)
        _counter(
            "trino_tpu_device_faults_total",
            "Device faults (loss/wedge) caught at the supervised "
            "dispatch boundary",
        ).inc(kind=kind, node=self.node_id)
        from ..obs import journal

        journal.emit(
            journal.DEVICE_FAULT,
            query_id=bc.query_id, task_id=bc.task_id,
            node_id=self.node_id, severity=journal.ERROR,
            kind=kind, kernel=bc.kernel, device=device_id,
            cause=(f"{type(cause).__name__}: {cause}"[:200]
                   if cause else ""),
        )
        if transition is not None:
            journal.emit(
                journal.DEVICE_QUARANTINE if transition == QUARANTINED
                else journal.DEVICE_BLACKLIST,
                query_id=bc.query_id, node_id=self.node_id,
                severity=journal.ERROR,
                device=device_id, strikes=strikes,
            )
        self._publish_state()
        return DeviceFaultError(kind, bc, cause)

    def _backoff(self, failures: int) -> float:
        base = max(self.probe_backoff_s, 0.001)
        return min(base * (2 ** max(failures - 1, 0)), MAX_PROBE_BACKOFF_S)

    def _publish_state(self):
        g = REGISTRY.gauge(
            "trino_tpu_device_state",
            "Device health per node (0=ACTIVE, 1=QUARANTINED, "
            "2=BLACKLISTED)",
        )
        with self._lock:
            for d in self._devices.values():
                g.set(_STATE_CODE[d.state], node=self.node_id,
                      device=str(d.device_id))

    # -- re-probe ------------------------------------------------------
    def maybe_probe(self, device_id: int = 0) -> bool:
        """Run the canary against a QUARANTINED device once its backoff
        has elapsed; returns True when the device is ACTIVE afterwards.
        BLACKLISTED devices are never probed (process-lifetime ban)."""
        with self._lock:
            d = self._device(device_id)
            if d.state == ACTIVE:
                return True
            if d.state == BLACKLISTED:
                return False
            if time.time() < d.next_probe:
                return False
        ok = self._probe(device_id)
        _counter(
            "trino_tpu_device_probe_total",
            "Canary re-probes of quarantined devices",
        ).inc(node=self.node_id, outcome="ok" if ok else "fail")
        now = time.time()
        with self._lock:
            d = self._device(device_id)
            if d.state == BLACKLISTED:
                return False
            if ok:
                d.state = ACTIVE
                d.probe_failures = 0
                d.next_probe = 0.0
            else:
                d.probe_failures += 1
                d.next_probe = now + self._backoff(d.probe_failures)
        if ok:
            from ..obs import journal

            journal.emit(
                journal.DEVICE_RECOVERED, node_id=self.node_id,
                device=device_id,
            )
        self._publish_state()
        return ok

    def _probe(self, device_id: int) -> bool:
        """Tiny canary kernel.  Consults the fault injector first so a
        seeded fault keeps the canary failing until its rule clears —
        which is what makes quarantine-then-recover deterministic on
        the CPU backend."""
        inj = self.fault_injector
        key = f"probe:{self.node_id}"
        try:
            if inj is not None:
                if inj.fires("device_wedge", key=key):
                    return False  # a wedged device times the canary out
                if inj.fires("device_loss", key=key):
                    raise _SimulatedDeviceLoss(
                        "UNAVAILABLE: TPU worker process crashed "
                        "(injected device_loss, canary)"
                    )
            import jax
            import jax.numpy as jnp

            out = jax.device_get(jnp.arange(8, dtype=jnp.int32) * 2 + 1)
            return int(out[-1]) == 15
        except Exception:
            return False

    # -- the supervised boundary ----------------------------------------
    def dispatch(self, thunk: Callable, bc: Breadcrumb, device_id: int = 0):
        """Run one device execution under supervision.

        Records the breadcrumb, refuses if the device is out (the caller
        degrades to CPU), injects seeded faults, arms the watchdog, and
        translates loss/wedge into :class:`DeviceFaultError`.  Any other
        exception — including the JaxRuntimeErrors the executor handles
        itself (INVALID_ARGUMENT, compile OOM) — passes through."""
        self._record(bc)
        rec = self.flight_recorder
        seq = rec.record_dispatch(bc) if rec is not None else 0
        with self._lock:
            d = self._device(device_id)
            state = d.state
        if state != ACTIVE:
            # no probe here: dispatch is the hot path; probing happens at
            # execute() entry and in the worker's announce loop
            if rec is not None:
                rec.record_fault(seq, bc, "device_" + state.lower())
            raise DeviceFaultError("device_" + state.lower(), bc)
        inj = self.fault_injector
        timeout = self.watchdog_timeout_s

        def supervised():
            if inj is not None:
                rule = inj.rules.get("device_wedge")
                if rule is not None and inj.fires(
                    "device_wedge", key=bc.kernel
                ):
                    # simulated wedge: stall the dispatch thread past the
                    # watchdog (default: twice the timeout)
                    time.sleep(float(rule.get(
                        "stall_s",
                        (timeout * 2.0) if timeout > 0 else 1.0,
                    )))
                if inj.fires("device_loss", key=bc.kernel):
                    raise _SimulatedDeviceLoss(
                        "UNAVAILABLE: TPU worker process crashed "
                        f"(injected device_loss at kernel {bc.kernel})"
                    )
            return thunk()

        start = time.time()
        try:
            if timeout and timeout > 0:
                out = self._with_watchdog(supervised, timeout)
            else:
                out = supervised()
        except _WedgeTimeout as e:
            if rec is not None:
                rec.record_fault(seq, bc, "device_wedge", str(e))
            raise self._fault(bc, "device_wedge", e, device_id) from None
        except Exception as e:
            if _is_device_loss(e):
                if rec is not None:
                    rec.record_fault(seq, bc, "device_loss", str(e))
                raise self._fault(bc, "device_loss", e, device_id) from e
            if rec is not None:
                # not a device fault (INVALID_ARGUMENT, compile OOM, plan
                # errors): still attributed — the ring shows which kernel
                # the error surfaced under before the executor handles it
                rec.record_fault(seq, bc, "error", str(e))
            raise
        if rec is not None:
            rec.record_complete(seq, bc, time.time() - start)
        return out

    def device_get(self, objs, bc: Breadcrumb, device_id: int = 0):
        """Supervised device->host transfer (the sync point where async
        dispatch faults actually surface)."""
        import jax

        return self.dispatch(lambda: jax.device_get(objs), bc, device_id)

    def _with_watchdog(self, fn: Callable, timeout: float):
        """Run fn on a side thread, join with the watchdog timeout.  A
        wedged dispatch cannot be killed (the thread is stuck inside the
        runtime), so it is abandoned as a daemon and the fault raised."""
        box: dict = {}
        done = threading.Event()

        def run():
            try:
                box["result"] = fn()
            except BaseException as e:  # rethrown on the caller thread
                box["error"] = e
            finally:
                done.set()

        t = threading.Thread(
            target=run, daemon=True,
            name=f"dispatch-{self.node_id}",
        )
        t.start()
        if not done.wait(timeout):
            raise _WedgeTimeout(
                f"dispatch exceeded watchdog timeout {timeout:.1f}s"
            )
        if "error" in box:
            raise box["error"]
        return box.get("result")

    # -- degraded-mode bookkeeping --------------------------------------
    def note_fallback_attempt(self, query_id: str = ""):
        with self._lock:
            self.fallback_attempted += 1
        _note_fallback("attempted")
        _counter(
            "trino_tpu_device_fallback_total",
            "Degraded-mode CPU re-executions after a device fault",
        ).inc(node=self.node_id)
        from ..obs import journal

        journal.emit(
            journal.CPU_FALLBACK, query_id=query_id,
            node_id=self.node_id, severity=journal.WARN,
        )

    def note_fallback_completed(self):
        with self._lock:
            self.fallback_completed += 1
        _note_fallback("completed")


# default supervisor for bare executors (no session/worker wiring); the
# distributed runner never uses it — every WorkerServer owns its own
_DEFAULT: Optional[DeviceSupervisor] = None


def default_supervisor() -> DeviceSupervisor:
    global _DEFAULT
    with _GLOBAL_LOCK:
        if _DEFAULT is None:
            _DEFAULT = DeviceSupervisor(node_id="local")
        return _DEFAULT


def reset_default_supervisor():
    """Test isolation: drop the process-default supervisor state."""
    global _DEFAULT, _LAST_BREADCRUMB
    with _GLOBAL_LOCK:
        _DEFAULT = None
        _LAST_BREADCRUMB = None
        _FALLBACKS["attempted"] = 0
        _FALLBACKS["completed"] = 0
