"""Hierarchical memory accounting.

Reference parity: lib/trino-memory-context (AggregatedMemoryContext /
LocalMemoryContext), worker memory/MemoryPool.java:44 with per-query
tagging, and the coordinator-side query.max-memory enforcement
(ExceededMemoryLimitException).

Device (HBM) reservations are estimated at trace time from static array
shapes — exact for this engine since every kernel is static-shape.  The
revocation/spill path (MemoryRevokingScheduler -> host-RAM spill) hangs
off revocable contexts; spill itself is future work (SURVEY §7 step 7).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional


class ExceededMemoryLimitError(RuntimeError):
    pass


class MemoryPool:
    """A byte budget shared by queries (worker MemoryPool analog)."""

    def __init__(self, size: int):
        self.size = size
        self.reserved = 0
        self.by_query: Dict[str, int] = {}
        self._lock = threading.Lock()

    def reserve(self, query_id: str, bytes_: int):
        with self._lock:
            if self.reserved + bytes_ > self.size:
                raise ExceededMemoryLimitError(
                    f"pool exhausted: reserved {self.reserved + bytes_} "
                    f"> {self.size} (query {query_id})"
                )
            self.reserved += bytes_
            self.by_query[query_id] = self.by_query.get(query_id, 0) + bytes_

    def try_reserve(self, query_id: str, bytes_: int) -> bool:
        """Reserve-if-fits (the LocalMemoryManager's non-raising probe)."""
        try:
            self.reserve(query_id, bytes_)
            return True
        except ExceededMemoryLimitError:
            return False

    def free_bytes(self) -> int:
        with self._lock:
            return max(0, self.size - self.reserved)

    def query_bytes(self, query_id: str) -> int:
        with self._lock:
            return self.by_query.get(query_id, 0)

    def snapshot(self) -> Dict[str, object]:
        """Point-in-time view for heartbeats / system.runtime.memory."""
        with self._lock:
            return {
                "size": int(self.size),
                "reserved": int(self.reserved),
                "free": max(0, int(self.size) - int(self.reserved)),
                "byQuery": dict(self.by_query),
            }

    def free(self, query_id: str, bytes_: Optional[int] = None):
        with self._lock:
            have = self.by_query.get(query_id, 0)
            amount = have if bytes_ is None else min(bytes_, have)
            self.reserved -= amount
            if amount >= have:
                self.by_query.pop(query_id, None)
            else:
                self.by_query[query_id] = have - amount


class MemoryContext:
    """Tree-structured accounting (user/revocable split)."""

    def __init__(self, name: str, parent: Optional["MemoryContext"] = None,
                 pool: Optional[MemoryPool] = None, query_id: str = ""):
        self.name = name
        self.parent = parent
        self.pool = pool if pool is not None else (
            parent.pool if parent else None
        )
        self.query_id = query_id or (parent.query_id if parent else "")
        self.user_bytes = 0
        self.revocable_bytes = 0
        self.peak = 0
        self.children: List["MemoryContext"] = []
        if parent is not None:
            parent.children.append(self)

    def new_child(self, name: str) -> "MemoryContext":
        return MemoryContext(name, self)

    def set_bytes(self, bytes_: int, revocable: bool = False):
        prev = self.revocable_bytes if revocable else self.user_bytes
        delta = bytes_ - prev
        if delta > 0 and self.pool is not None:
            self.pool.reserve(self.query_id, delta)
        elif delta < 0 and self.pool is not None:
            self.pool.free(self.query_id, -delta)
        if revocable:
            self.revocable_bytes = bytes_
        else:
            self.user_bytes = bytes_
        self.peak = max(self.peak, self.total_bytes())

    def total_bytes(self) -> int:
        return (
            self.user_bytes
            + self.revocable_bytes
            + sum(c.total_bytes() for c in self.children)
        )

    def close(self):
        if self.pool is not None:
            self.pool.free(self.query_id, self.user_bytes + self.revocable_bytes)
        self.user_bytes = 0
        self.revocable_bytes = 0
        for c in self.children:
            c.close()


def estimate_batch_bytes(lanes) -> int:
    """Static-shape byte estimate of a Batch's lanes (values + validity)."""
    total = 0
    for v, ok in lanes.values():
        total += int(v.size) * v.dtype.itemsize + int(ok.size)
    return total
