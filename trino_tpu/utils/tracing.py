"""Tracing: span recording through engine layers.

Reference parity: OpenTelemetry integration (tracing/TracingMetadata.java,
TrinoAttributes span-attribute schema, query/task spans created in
DispatchManager.java:155 and SqlTaskManager).  This is an OTel-compatible
span model (name, trace/span ids, parent, start/end, attributes) with an
in-memory recorder; an exporter can forward to a real OTel endpoint.
"""
from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
import uuid
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start: float
    end: Optional[float] = None
    attributes: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def duration_ms(self) -> float:
        return ((self.end or time.time()) - self.start) * 1000


class Tracer:
    """Per-process tracer with thread-local span stacks."""

    def __init__(self):
        self.spans: List[Span] = []
        self._local = threading.local()
        self._lock = threading.Lock()

    def _stack(self) -> List[Span]:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    @contextlib.contextmanager
    def span(self, name: str, **attributes):
        stack = self._stack()
        parent = stack[-1] if stack else None
        s = Span(
            name=name,
            trace_id=parent.trace_id if parent else uuid.uuid4().hex,
            span_id=uuid.uuid4().hex[:16],
            parent_id=parent.span_id if parent else None,
            start=time.time(),
            attributes=dict(attributes),
        )
        stack.append(s)
        try:
            yield s
        finally:
            s.end = time.time()
            stack.pop()
            with self._lock:
                self.spans.append(s)

    def for_trace(self, trace_id: str) -> List[Span]:
        with self._lock:
            return [s for s in self.spans if s.trace_id == trace_id]

    def clear(self):
        with self._lock:
            self.spans.clear()

    # -- export ---------------------------------------------------------
    def attach_exporter(self, exporter: "OtlpFileExporter"):
        self.exporter = exporter

    def flush(self):
        """Export + drop all recorded spans (called at query completion —
        the airlift OTel exporter's batch-flush role).  Without an exporter
        spans stay in memory for tests/system tables."""
        exporter = getattr(self, "exporter", None)
        if exporter is None:
            return
        with self._lock:
            spans, self.spans = self.spans, []
        if spans:
            exporter.export(spans)


class OtlpFileExporter:
    """OTLP/JSON span exporter writing one `resourceSpans` document per
    flush to a local file (newline-delimited) — the OpenTelemetry wire
    schema (trace service ExportTraceServiceRequest JSON mapping), minus
    the network: an OTel collector can tail the file, and air-gapped
    environments (like the bench TPU) still get durable traces.

    Reference parity: airlift's OpenTelemetry exporter wired through
    tracing/TracingMetadata.java + TrinoAttributes span schema.
    """

    def __init__(self, path: str, service_name: str = "trino-tpu"):
        self.path = path
        self.service_name = service_name
        self._lock = threading.Lock()

    def export(self, spans: List[Span]):
        import json

        doc = {
            "resourceSpans": [{
                "resource": {"attributes": [{
                    "key": "service.name",
                    "value": {"stringValue": self.service_name},
                }]},
                "scopeSpans": [{
                    "scope": {"name": "trino_tpu"},
                    "spans": [
                        {
                            "traceId": s.trace_id,
                            "spanId": s.span_id,
                            "parentSpanId": s.parent_id or "",
                            "name": s.name,
                            "startTimeUnixNano": int(s.start * 1e9),
                            "endTimeUnixNano": int(
                                (s.end or s.start) * 1e9
                            ),
                            "attributes": [
                                {"key": k,
                                 "value": {"stringValue": str(v)}}
                                for k, v in s.attributes.items()
                            ],
                        }
                        for s in spans
                    ],
                }],
            }]
        }
        with self._lock:
            with open(self.path, "a") as f:
                f.write(json.dumps(doc) + "\n")


TRACER = Tracer()

# TRINO_TPU_OTLP_FILE wires the process tracer to a file exporter at
# import (the etc/config.properties tracing.* binding analog)
import os as _os

_otlp = _os.environ.get("TRINO_TPU_OTLP_FILE")
if _otlp:
    TRACER.attach_exporter(OtlpFileExporter(_otlp))
