"""Tracing: span recording through engine layers.

Reference parity: OpenTelemetry integration (tracing/TracingMetadata.java,
TrinoAttributes span-attribute schema, query/task spans created in
DispatchManager.java:155 and SqlTaskManager).  This is an OTel-compatible
span model (name, trace/span ids, parent, start/end, attributes) with an
in-memory recorder; an exporter can forward to a real OTel endpoint.
"""
from __future__ import annotations

import collections
import contextlib
import dataclasses
import threading
import time
import uuid
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start: float
    end: Optional[float] = None
    attributes: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def duration_ms(self) -> float:
        return ((self.end or time.time()) - self.start) * 1000

    @property
    def traceparent(self) -> str:
        return format_traceparent(self.trace_id, self.span_id)


def format_traceparent(trace_id: str, span_id: str) -> str:
    """W3C Trace Context `traceparent`: version-traceid-spanid-flags."""
    return "00-%s-%s-01" % (trace_id, span_id)


def parse_traceparent(header: Optional[str]) -> Optional[Dict[str, str]]:
    """Parse a W3C `traceparent` header into trace/parent span ids.

    Returns None for absent or malformed headers (per spec, an invalid
    header means "start a fresh trace", never an error).
    """
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) < 4:
        return None
    _version, trace_id, span_id = parts[0], parts[1], parts[2]
    if len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        int(trace_id, 16)
        int(span_id, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    return {"trace_id": trace_id, "parent_id": span_id}


class Tracer:
    """Per-process tracer with thread-local span stacks.

    Completed spans land in a bounded ring buffer (oldest dropped first)
    so a long-lived process with no exporter attached holds at most
    `max_spans` spans in memory.
    """

    DEFAULT_MAX_SPANS = 4096

    def __init__(self, max_spans: int = DEFAULT_MAX_SPANS):
        self.max_spans = max_spans
        self.spans: "collections.deque[Span]" = collections.deque(maxlen=max_spans)
        self.exporter: Optional["OtlpFileExporter"] = None
        self._local = threading.local()
        self._lock = threading.Lock()

    def _stack(self) -> List[Span]:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    @contextlib.contextmanager
    def span(self, name: str, traceparent: Optional[str] = None, **attributes):
        """Open a span.  A remote `traceparent` joins that trace when the
        calling thread has no local parent (the Dapper cross-process link:
        coordinator->worker dispatch, exchange fetch threads)."""
        stack = self._stack()
        parent = stack[-1] if stack else None
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        else:
            remote = parse_traceparent(traceparent)
            if remote is not None:
                trace_id, parent_id = remote["trace_id"], remote["parent_id"]
            else:
                trace_id, parent_id = uuid.uuid4().hex, None
        s = Span(
            name=name,
            trace_id=trace_id,
            span_id=uuid.uuid4().hex[:16],
            parent_id=parent_id,
            start=time.time(),
            attributes=dict(attributes),
        )
        stack.append(s)
        try:
            yield s
        finally:
            s.end = time.time()
            stack.pop()
            with self._lock:
                self.spans.append(s)

    def current_span(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    def current_traceparent(self) -> Optional[str]:
        s = self.current_span()
        return s.traceparent if s is not None else None

    def for_trace(self, trace_id: str) -> List[Span]:
        with self._lock:
            return [s for s in self.spans if s.trace_id == trace_id]

    def clear(self):
        with self._lock:
            self.spans.clear()

    # -- export ---------------------------------------------------------
    def attach_exporter(self, exporter: "OtlpFileExporter"):
        self.exporter = exporter

    def flush(self):
        """Export + drop all recorded spans (called at query completion —
        the airlift OTel exporter's batch-flush role).  Without an exporter
        spans stay in memory for tests/system tables."""
        exporter = self.exporter
        if exporter is None:
            return
        with self._lock:
            spans = list(self.spans)
            self.spans.clear()
        if spans:
            exporter.export(spans)


class OtlpFileExporter:
    """OTLP/JSON span exporter writing one `resourceSpans` document per
    flush to a local file (newline-delimited) — the OpenTelemetry wire
    schema (trace service ExportTraceServiceRequest JSON mapping), minus
    the network: an OTel collector can tail the file, and air-gapped
    environments (like the bench TPU) still get durable traces.

    Reference parity: airlift's OpenTelemetry exporter wired through
    tracing/TracingMetadata.java + TrinoAttributes span schema.
    """

    def __init__(self, path: str, service_name: str = "trino-tpu"):
        self.path = path
        self.service_name = service_name
        self._lock = threading.Lock()

    def export(self, spans: List[Span]):
        import json

        doc = {
            "resourceSpans": [{
                "resource": {"attributes": [{
                    "key": "service.name",
                    "value": {"stringValue": self.service_name},
                }]},
                "scopeSpans": [{
                    "scope": {"name": "trino_tpu"},
                    "spans": [
                        {
                            "traceId": s.trace_id,
                            "spanId": s.span_id,
                            "parentSpanId": s.parent_id or "",
                            "name": s.name,
                            "startTimeUnixNano": int(s.start * 1e9),
                            "endTimeUnixNano": int(
                                (s.end or s.start) * 1e9
                            ),
                            "attributes": [
                                {"key": k,
                                 "value": {"stringValue": str(v)}}
                                for k, v in s.attributes.items()
                            ],
                        }
                        for s in spans
                    ],
                }],
            }]
        }
        with self._lock:
            with open(self.path, "a") as f:
                f.write(json.dumps(doc) + "\n")


TRACER = Tracer()

# TRINO_TPU_OTLP_FILE wires the process tracer to a file exporter at
# import (the etc/config.properties tracing.* binding analog)
import os as _os

_otlp = _os.environ.get("TRINO_TPU_OTLP_FILE")
if _otlp:
    TRACER.attach_exporter(OtlpFileExporter(_otlp))
