"""Tracing: span recording through engine layers.

Reference parity: OpenTelemetry integration (tracing/TracingMetadata.java,
TrinoAttributes span-attribute schema, query/task spans created in
DispatchManager.java:155 and SqlTaskManager).  This is an OTel-compatible
span model (name, trace/span ids, parent, start/end, attributes) with an
in-memory recorder; an exporter can forward to a real OTel endpoint.
"""
from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
import uuid
from typing import Any, Dict, List, Optional


@dataclasses.dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start: float
    end: Optional[float] = None
    attributes: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def duration_ms(self) -> float:
        return ((self.end or time.time()) - self.start) * 1000


class Tracer:
    """Per-process tracer with thread-local span stacks."""

    def __init__(self):
        self.spans: List[Span] = []
        self._local = threading.local()
        self._lock = threading.Lock()

    def _stack(self) -> List[Span]:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    @contextlib.contextmanager
    def span(self, name: str, **attributes):
        stack = self._stack()
        parent = stack[-1] if stack else None
        s = Span(
            name=name,
            trace_id=parent.trace_id if parent else uuid.uuid4().hex,
            span_id=uuid.uuid4().hex[:16],
            parent_id=parent.span_id if parent else None,
            start=time.time(),
            attributes=dict(attributes),
        )
        stack.append(s)
        try:
            yield s
        finally:
            s.end = time.time()
            stack.pop()
            with self._lock:
                self.spans.append(s)

    def for_trace(self, trace_id: str) -> List[Span]:
        with self._lock:
            return [s for s in self.spans if s.trace_id == trace_id]

    def clear(self):
        with self._lock:
            self.spans.clear()


TRACER = Tracer()
