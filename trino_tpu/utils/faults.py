"""Seeded fault injection: the chaos harness behind the robustness tests.

Reference parity: execution/FailureInjector.java (taskId-addressed one-shot
failures wired through TaskResource's failTask endpoint) generalized into
the config-driven style of Project Tardigrade's BaseFailureRecoveryTest —
named injection SITES across the data plane fire by deterministic seeded
rules, so any chaos run is exactly reproducible from its spec + seed.

A spec is a dict (or its JSON text, shipped as the ``fault_injection``
session/task property): the reserved key ``seed`` seeds every site's RNG;
every other key names a site and maps to a rule:

    {"seed": 7,
     "spool_write_corrupt": {"nth": 1},          # fire on the 1st call
     "exchange_fetch": {"p": 0.5, "times": 2},   # coin-flip, max 2 fires
     "task_stall": {"stall_s": 3.0, "match": ".1.0."}}  # scoped by key

Rule fields: ``nth`` (1-based call index), ``p`` (seeded probability),
neither = always; ``times`` caps total fires; ``match`` restricts the rule
to calls whose key contains the substring; ``stall_s`` / ``flip_byte``
parameterize the stall and corrupt helpers.
"""
from __future__ import annotations

import json
import random
import threading
import time
from typing import Dict, Optional

SITES = (
    "spool_write_corrupt",  # flip a bit in a spool-bound page frame
    "spool_read",           # corrupt spool detection on the read side
    "exchange_fetch",       # transient HTTP failure pulling pages
    "task_run",             # task fails at start (FailureInjector TASK)
    "task_stall",           # straggler injection (TASK_MANAGEMENT_TIMEOUT)
    "heartbeat",            # worker skips an announcement round
    "announce_drop",        # like heartbeat but named for node-churn
                            # chaos: announcement loss without process
                            # death (the GC-pause / partition analog)
    "worker_death",         # hard process exit (kill -9 analog) at task
                            # start; only honored by the WORKER-LEVEL
                            # injector of a subprocess worker — an
                            # in-process worker firing it would take the
                            # whole test runner down
    "cache_read",           # corrupt a spilled result-cache frame on read
    "oom",                  # memory reservation behaves as if the pool
                            # were exhausted (LocalMemoryManager tier)
    "stats_estimate",       # skew a fragment's estimated output rows by
                            # rule field `factor` (adaptive-replan tests)
    "device_loss",          # kernel dispatch dies UNAVAILABLE (TPU worker
                            # crash) at the supervised boundary
    "device_wedge",         # kernel dispatch stalls past the watchdog
                            # timeout (rule field `stall_s` overrides)
    "coordinator_death",    # hard coordinator exit (kill -9 analog) at a
                            # chosen WAL transition, keyed
                            # "{recordType}:{queryId}" so `match` picks
                            # the exact transition; only honored by the
                            # COORDINATOR-LEVEL injector of a subprocess
                            # coordinator (server/coordinator_main.py) —
                            # an in-process coordinator firing it would
                            # take the whole test runner down
    "objstore_latency",     # object-store op stalls (rule field
                            # `stall_s`) — the Tail-at-Scale storage
                            # analog; keyed "{op}:{path}" so `match`
                            # scopes it to e.g. metadata reads
    "objstore_error",       # transient object-store failure (S3 500/
                            # SlowDown analog); the fs layer retries with
                            # bounded backoff, so a rule with small
                            # `times` is invisible to callers while a
                            # persistent rule exhausts the retry budget
    "objstore_throttle",    # throttling rejection (S3 503) — retried
                            # like objstore_error but counted separately
                            # so dashboards can tell overload from faults
    "lake_commit_crash",    # hard writer exit (kill -9 analog) BETWEEN
                            # a lakehouse commit's data-file write and
                            # its metadata-pointer CAS, keyed
                            # "{table}:{snapshotId}"; only honored when
                            # TRINO_TPU_CRASH_FAULTS=1 marks the process
                            # as a sacrificial subprocess writer — an
                            # in-process session firing it would take
                            # the whole test runner down
)


class FaultInjector:
    """Named-site fault firing with deterministic seeded rules.

    One instance is shared by everything that should count calls together
    — the worker gives every task carrying the same spec the same
    injector, so "nth call" means the nth call on that worker across the
    whole query, not per task."""

    def __init__(self, rules: Optional[dict] = None, seed: int = 0):
        self.seed = int(seed)
        self.rules: Dict[str, dict] = {}
        for site, spec in (rules or {}).items():
            if site not in SITES:
                raise ValueError(
                    f"unknown fault site {site!r} (expected one of {SITES})"
                )
            self.rules[site] = dict(spec)
        self._lock = threading.RLock()
        self._calls = {s: 0 for s in self.rules}
        self._fired = {s: 0 for s in self.rules}
        self._rng = {
            s: random.Random(f"{self.seed}:{s}") for s in self.rules
        }
        # legacy taskId-addressed one-shot modes (the TaskResource
        # injectFailure endpoint folds into the same harness)
        self._task_modes: Dict[str, str] = {}

    # ------------------------------------------------------------------
    @classmethod
    def from_spec(cls, spec) -> "FaultInjector":
        """Build from a dict, its JSON text, or nothing (disabled)."""
        if not spec:
            return cls()
        if isinstance(spec, str):
            spec = json.loads(spec)
        spec = dict(spec)
        seed = spec.pop("seed", 0)
        return cls(spec, seed=seed)

    def enabled(self) -> bool:
        return bool(self.rules)

    # ------------------------------------------------------------------
    def fires(self, site: str, key: Optional[str] = None) -> bool:
        """Count one call at `site` and decide whether the fault fires."""
        rule = self.rules.get(site)
        if rule is None:
            return False
        with self._lock:
            match = rule.get("match")
            if match is not None and match not in str(key or ""):
                return False
            self._calls[site] += 1
            times = rule.get("times")
            if times is not None and self._fired[site] >= int(times):
                return False
            if "nth" in rule:
                fired = self._calls[site] == int(rule["nth"])
            elif "p" in rule:
                fired = self._rng[site].random() < float(rule["p"])
            else:
                fired = True
            if fired:
                self._fired[site] += 1
                from .metrics import REGISTRY

                REGISTRY.counter(
                    "trino_tpu_fault_injected_total",
                    "Chaos-harness fault firings by injection site",
                ).inc(site=site)
                # journaled BEFORE the fault takes effect: even a
                # worker_death hard-exit leaves the firing on record
                # (the mmap'd page survives os._exit)
                from ..obs import journal

                journal.emit(
                    journal.FAULT_INJECTED, severity=journal.WARN,
                    site=site, key=str(key or "")[:200],
                )
            return fired

    def fired_count(self, site: str) -> int:
        with self._lock:
            return self._fired.get(site, 0)

    def stall(self, site: str = "task_stall", key: Optional[str] = None):
        """Sleep rule['stall_s'] when the site fires (straggler injection)."""
        rule = self.rules.get(site)
        if rule is not None and self.fires(site, key):
            time.sleep(float(rule.get("stall_s", 1.0)))

    def corrupt(
        self, site: str, payload: bytes, key: Optional[str] = None
    ) -> bytes:
        """Flip one deterministic bit of `payload` when the site fires."""
        if not payload or not self.fires(site, key):
            return payload
        rule = self.rules[site]
        pos = rule.get("flip_byte")
        if pos is None:
            pos = self._rng[site].randrange(len(payload))
        buf = bytearray(payload)
        buf[int(pos) % len(buf)] ^= 0x01
        return bytes(buf)

    # -- legacy taskId-addressed modes ---------------------------------
    def set_task_mode(self, task_id: str, mode: str):
        with self._lock:
            self._task_modes[task_id] = mode

    def take_task_mode(self, task_id: str) -> Optional[str]:
        with self._lock:
            return self._task_modes.pop(task_id, None)
