"""Query event listener SPI.

Reference parity: spi/eventlistener/ (EventListener, QueryCreatedEvent,
QueryCompletedEvent, SplitCompletedEvent) dispatched by
eventlistener/EventListenerManager + event/QueryMonitor.  Listeners are
plugged into the session/coordinator; payloads carry the reference's core
fields (query id, sql, state, timing, row counts, error).
"""
from __future__ import annotations

import dataclasses
import time
from typing import List, Optional


@dataclasses.dataclass(frozen=True)
class QueryCreatedEvent:
    query_id: str
    sql: str
    create_time: float


@dataclasses.dataclass(frozen=True)
class QueryCompletedEvent:
    query_id: str
    sql: str
    state: str  # FINISHED | FAILED
    create_time: float
    end_time: float
    output_rows: int = 0
    error: Optional[str] = None

    @property
    def wall_ms(self) -> float:
        return (self.end_time - self.create_time) * 1000


class EventListener:
    """SPI: subclass and register (spi/eventlistener/EventListener)."""

    def query_created(self, event: QueryCreatedEvent):
        pass

    def query_completed(self, event: QueryCompletedEvent):
        pass


class EventListenerManager:
    def __init__(self):
        self.listeners: List[EventListener] = []

    def add(self, listener: EventListener):
        self.listeners.append(listener)

    def query_created(self, query_id: str, sql: str) -> float:
        t = time.time()
        ev = QueryCreatedEvent(query_id, sql, t)
        for l in self.listeners:
            l.query_created(ev)
        return t

    def query_completed(self, query_id: str, sql: str, state: str,
                        create_time: float, output_rows: int = 0,
                        error: Optional[str] = None):
        ev = QueryCompletedEvent(
            query_id, sql, state, create_time, time.time(), output_rows, error
        )
        for l in self.listeners:
            l.query_completed(ev)
