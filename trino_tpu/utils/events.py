"""Query event listener SPI.

Reference parity: spi/eventlistener/ (EventListener, QueryCreatedEvent,
QueryCompletedEvent, SplitCompletedEvent) dispatched by
eventlistener/EventListenerManager + event/QueryMonitor.  Listeners are
plugged into the session/coordinator; payloads carry the reference's core
fields (query id, sql, state, timing, row counts, error).
"""
from __future__ import annotations

import dataclasses
import time
from typing import List, Optional


@dataclasses.dataclass(frozen=True)
class QueryCreatedEvent:
    query_id: str
    sql: str
    create_time: float


@dataclasses.dataclass(frozen=True)
class QueryCompletedEvent:
    query_id: str
    sql: str
    state: str  # FINISHED | FAILED
    create_time: float
    end_time: float
    output_rows: int = 0
    error: Optional[str] = None

    @property
    def wall_ms(self) -> float:
        return (self.end_time - self.create_time) * 1000


@dataclasses.dataclass(frozen=True)
class CacheEvent:
    """One cache-tier operation (hit/miss/put/evict/spill/heal/...): the
    observability feed behind system.runtime.caches aggregate counters."""

    tier: str  # result | compile | scan
    op: str
    nbytes: int
    time: float


class EventListener:
    """SPI: subclass and register (spi/eventlistener/EventListener)."""

    def query_created(self, event: QueryCreatedEvent):
        pass

    def query_completed(self, event: QueryCompletedEvent):
        pass

    def cache_event(self, event: CacheEvent):
        pass


class EventListenerManager:
    def __init__(self):
        self.listeners: List[EventListener] = []

    def add(self, listener: EventListener):
        self.listeners.append(listener)

    def query_created(self, query_id: str, sql: str) -> float:
        t = time.time()
        ev = QueryCreatedEvent(query_id, sql, t)
        for l in self.listeners:
            l.query_created(ev)
        return t

    def query_completed(self, query_id: str, sql: str, state: str,
                        create_time: float, output_rows: int = 0,
                        error: Optional[str] = None):
        ev = QueryCompletedEvent(
            query_id, sql, state, create_time, time.time(), output_rows, error
        )
        for l in self.listeners:
            l.query_completed(ev)

    def cache_event(self, tier: str, op: str, nbytes: int = 0):
        if not self.listeners:  # hot path: hits/misses fire per query
            return
        ev = CacheEvent(tier, op, int(nbytes), time.time())
        for l in self.listeners:
            l.cache_event(ev)


class HttpEventListener(EventListener):
    """POSTs query events as JSON to a remote collector
    (plugin/trino-http-event-listener analog).  Failures are swallowed:
    eventing must never fail queries.

    Posts flow through one background queue + worker thread, so a slow or
    dead collector backs up into a bounded queue (events then drop) instead
    of spawning an unbounded thread per event."""

    QUEUE_MAX = 1024

    def __init__(self, uri: str, timeout: float = 2.0):
        import queue

        self.uri = uri.rstrip("/")
        self.timeout = timeout
        self._queue: "queue.Queue[dict]" = queue.Queue(maxsize=self.QUEUE_MAX)
        self._worker = None
        self._worker_lock = __import__("threading").Lock()

    def _ensure_worker(self):
        import threading

        with self._worker_lock:
            if self._worker is None or not self._worker.is_alive():
                self._worker = threading.Thread(
                    target=self._drain, name="http-event-listener", daemon=True
                )
                self._worker.start()

    def _drain(self):
        while True:
            doc = self._queue.get()
            try:
                self._send(doc)
            finally:
                self._queue.task_done()

    def _send(self, doc: dict):
        import json as _json
        import urllib.request

        from .metrics import REGISTRY

        try:
            req = urllib.request.Request(
                self.uri,
                data=_json.dumps(doc).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                r.read()
            REGISTRY.counter(
                "trino_tpu_event_posted_total", "Query events delivered to HTTP collectors"
            ).inc()
        except Exception:
            REGISTRY.counter(
                "trino_tpu_event_post_failed_total", "Query event deliveries that errored"
            ).inc()

    def _post(self, doc: dict):
        import queue

        from .metrics import REGISTRY

        # fire-and-forget: eventing must not add latency to the query path
        self._ensure_worker()
        try:
            self._queue.put_nowait(doc)
        except queue.Full:
            REGISTRY.counter(
                "trino_tpu_event_dropped_total",
                "Query events dropped because the listener queue was full",
            ).inc()

    def query_created(self, event: QueryCreatedEvent):
        self._post({
            "event": "QueryCreated",
            "queryId": event.query_id,
            "sql": event.sql,
            "createTime": event.create_time,
        })

    def query_completed(self, event: QueryCompletedEvent):
        self._post({
            "event": "QueryCompleted",
            "queryId": event.query_id,
            "sql": event.sql,
            "state": event.state,
            "wallMillis": event.wall_ms,
            "outputRows": event.output_rows,
            "error": event.error,
        })
