"""Process-global metrics registry (Prometheus-style).

The reference engine exposes per-component counters through JMX MBeans
scraped into a metrics pipeline; here a single in-process registry plays
that role.  Three instrument kinds cover the engine's needs:

- ``Counter``   — monotonically increasing totals (``_total`` / ``_bytes``)
- ``Gauge``     — point-in-time values that can go up and down
- ``Histogram`` — fixed-bucket latency distributions (``_seconds``) with
  p50/p95/p99 estimated by linear interpolation inside the bucket

All instruments accept optional labels on observation, so one metric
family (e.g. ``trino_tpu_cache_op_total``) fans out into per-label series
(``{tier="result",op="hit"}``) exactly as the Prometheus text exposition
expects.  Because the distributed test runner hosts coordinator and
workers in one process, the module-level ``REGISTRY`` is intentionally
process-global: every ``/metrics`` endpoint serves the same truth.

Metric names must match ``trino_tpu_<subsystem>_<name>`` and end in
``_total``, ``_bytes``, ``_seconds``, or ``_state`` (state-machine
gauges) — enforced here at registration time and over the source tree by
``scripts/check_metric_names.py``.
"""
from __future__ import annotations

import re
import threading
from typing import Dict, List, Optional, Sequence, Tuple

METRIC_SUBSYSTEMS = (
    "query",
    "scheduler",
    "exchange",
    "spool",
    "cache",
    "fault",
    "task",
    "kernel",
    "event",
    "memory",
    "stats",
    "device",
    "straggler",
    "node",
    "journal",
    "doctor",
    "resource_group",
    "autoscaler",
    "compile",
    "coordinator",
    "signature",
    "slo",
    "objstore",
    "lake",
    "host",
)

METRIC_NAME_RE = re.compile(
    r"^trino_tpu_(%s)(_[a-z0-9]+)*_(total|bytes|seconds|state)$"
    % "|".join(METRIC_SUBSYSTEMS)
)

# Byte-volume buckets (64 KiB .. 16 GiB, x4 steps) for ``_bytes``
# histograms such as the bandwidth ledger's per-dispatch byte volume.
BYTES_BUCKETS = tuple(float(1 << s) for s in range(16, 35, 2))

# Latency buckets in seconds; tuned for sub-millisecond kernels up to
# multi-second distributed queries.
DEFAULT_BUCKETS = (
    0.001,
    0.005,
    0.01,
    0.025,
    0.05,
    0.1,
    0.25,
    0.5,
    1.0,
    2.5,
    5.0,
    10.0,
    30.0,
)

LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, str]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _format_labels(key: LabelKey, extra: str = "") -> str:
    parts = ['%s="%s"' % (k, v.replace("\\", "\\\\").replace('"', '\\"')) for k, v in key]
    if extra:
        parts.append(extra)
    if not parts:
        return ""
    return "{%s}" % ",".join(parts)


def _fmt_value(v: float) -> str:
    if isinstance(v, float) and v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class Counter:
    """Monotonic counter, optionally labelled."""

    kind = "counter"

    def __init__(self, name: str, help: str):
        self.name = name
        self.help = help
        self._values: Dict[LabelKey, float] = {}
        self._lock = threading.Lock()

    def inc(self, value: float = 1.0, **labels: str) -> None:
        if value < 0:
            raise ValueError("counter cannot decrease")
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def total(self) -> float:
        with self._lock:
            return sum(self._values.values())

    def series(self) -> List[Tuple[LabelKey, float]]:
        with self._lock:
            return sorted(self._values.items())

    def render(self) -> List[str]:
        lines = ["# HELP %s %s" % (self.name, self.help), "# TYPE %s counter" % self.name]
        series = self.series() or [((), 0.0)]
        for key, v in series:
            lines.append("%s%s %s" % (self.name, _format_labels(key), _fmt_value(v)))
        return lines


class Gauge:
    """Point-in-time value; supports set/inc/dec."""

    kind = "gauge"

    def __init__(self, name: str, help: str):
        self.name = name
        self.help = help
        self._values: Dict[LabelKey, float] = {}
        self._lock = threading.Lock()

    def set(self, value: float, **labels: str) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def inc(self, value: float = 1.0, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def dec(self, value: float = 1.0, **labels: str) -> None:
        self.inc(-value, **labels)

    def value(self, **labels: str) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0.0)

    def series(self) -> List[Tuple[LabelKey, float]]:
        with self._lock:
            return sorted(self._values.items())

    def render(self) -> List[str]:
        lines = ["# HELP %s %s" % (self.name, self.help), "# TYPE %s gauge" % self.name]
        series = self.series() or [((), 0.0)]
        for key, v in series:
            lines.append("%s%s %s" % (self.name, _format_labels(key), _fmt_value(v)))
        return lines


class Histogram:
    """Fixed-bucket histogram with cumulative Prometheus buckets.

    Quantiles are estimated per the classic ``histogram_quantile``
    approach: find the bucket the target rank lands in and linearly
    interpolate between its bounds.
    """

    kind = "histogram"

    def __init__(self, name: str, help: str, buckets: Optional[Sequence[float]] = None):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        self._counts: Dict[LabelKey, List[int]] = {}
        self._sums: Dict[LabelKey, float] = {}
        self._totals: Dict[LabelKey, int] = {}
        self._lock = threading.Lock()

    def observe(self, value: float, **labels: str) -> None:
        key = _label_key(labels)
        with self._lock:
            counts = self._counts.get(key)
            if counts is None:
                counts = [0] * (len(self.buckets) + 1)
                self._counts[key] = counts
            idx = len(self.buckets)
            for i, b in enumerate(self.buckets):
                if value <= b:
                    idx = i
                    break
            counts[idx] += 1
            self._sums[key] = self._sums.get(key, 0.0) + float(value)
            self._totals[key] = self._totals.get(key, 0) + 1

    def count(self, **labels: str) -> int:
        with self._lock:
            return self._totals.get(_label_key(labels), 0)

    def sum(self, **labels: str) -> float:
        with self._lock:
            return self._sums.get(_label_key(labels), 0.0)

    def quantile(self, q: float, **labels: str) -> float:
        """Estimate the q-quantile (0 < q <= 1) across one label series."""
        key = _label_key(labels)
        with self._lock:
            counts = self._counts.get(key)
            total = self._totals.get(key, 0)
            if not counts or total == 0:
                return 0.0
            rank = q * total
            seen = 0.0
            for i, c in enumerate(counts):
                if c == 0:
                    continue
                lo = 0.0 if i == 0 else self.buckets[i - 1]
                hi = self.buckets[i] if i < len(self.buckets) else self.buckets[-1]
                if seen + c >= rank:
                    frac = (rank - seen) / c
                    return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
                seen += c
            return self.buckets[-1]

    def series(self) -> List[Tuple[LabelKey, List[int], float, int]]:
        with self._lock:
            return [
                (key, list(self._counts[key]), self._sums.get(key, 0.0), self._totals.get(key, 0))
                for key in sorted(self._counts)
            ]

    def render(self) -> List[str]:
        lines = ["# HELP %s %s" % (self.name, self.help), "# TYPE %s histogram" % self.name]
        series = self.series()
        if not series:
            series = [((), [0] * (len(self.buckets) + 1), 0.0, 0)]
        for key, counts, total_sum, total in series:
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += counts[i]
                lines.append(
                    "%s_bucket%s %d"
                    % (self.name, _format_labels(key, 'le="%s"' % _fmt_value(b)), cum)
                )
            lines.append(
                "%s_bucket%s %d" % (self.name, _format_labels(key, 'le="+Inf"'), total)
            )
            lines.append("%s_sum%s %s" % (self.name, _format_labels(key), _fmt_value(total_sum)))
            lines.append("%s_count%s %d" % (self.name, _format_labels(key), total))
        return lines


class MetricsRegistry:
    """Get-or-create home for all instruments in the process."""

    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._lock = threading.RLock()

    def _get_or_create(self, name: str, factory):
        if not METRIC_NAME_RE.match(name):
            raise ValueError(
                "metric name %r violates trino_tpu_<subsystem>_<name>"
                "{_total|_bytes|_seconds|_state} convention" % name
            )
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        m = self._get_or_create(name, lambda: Counter(name, help))
        if not isinstance(m, Counter):
            raise TypeError("metric %r already registered as %s" % (name, m.kind))
        return m

    def gauge(self, name: str, help: str = "") -> Gauge:
        m = self._get_or_create(name, lambda: Gauge(name, help))
        if not isinstance(m, Gauge):
            raise TypeError("metric %r already registered as %s" % (name, m.kind))
        return m

    def histogram(
        self, name: str, help: str = "", buckets: Optional[Sequence[float]] = None
    ) -> Histogram:
        m = self._get_or_create(name, lambda: Histogram(name, help, buckets))
        if not isinstance(m, Histogram):
            raise TypeError("metric %r already registered as %s" % (name, m.kind))
        return m

    def get(self, name: str):
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> List[object]:
        with self._lock:
            return [self._metrics[n] for n in sorted(self._metrics)]

    def render_prometheus(self) -> str:
        lines: List[str] = []
        for m in self.metrics():
            lines.extend(m.render())
        return "\n".join(lines) + "\n"

    def snapshot(self) -> Dict[str, float]:
        """Flat ``name{labels} -> value`` map for bench artifacts."""
        out: Dict[str, float] = {}
        for m in self.metrics():
            if isinstance(m, (Counter, Gauge)):
                for key, v in m.series():
                    out[m.name + _format_labels(key)] = v
            elif isinstance(m, Histogram):
                for key, _counts, total_sum, total in m.series():
                    out[m.name + "_count" + _format_labels(key)] = total
                    out[m.name + "_sum" + _format_labels(key)] = total_sum
        return out

    def rows(self) -> Dict[str, List]:
        """Column-oriented rows for the ``system.runtime.metrics`` table."""
        names: List[str] = []
        kinds: List[str] = []
        labels: List[str] = []
        values: List[float] = []
        p50s: List[Optional[float]] = []
        p95s: List[Optional[float]] = []
        p99s: List[Optional[float]] = []
        for m in self.metrics():
            if isinstance(m, (Counter, Gauge)):
                for key, v in m.series():
                    names.append(m.name)
                    kinds.append(m.kind)
                    labels.append(_format_labels(key))
                    values.append(float(v))
                    p50s.append(None)
                    p95s.append(None)
                    p99s.append(None)
            elif isinstance(m, Histogram):
                for key, _counts, _sum, total in m.series():
                    lbl = dict(key)
                    names.append(m.name)
                    kinds.append(m.kind)
                    labels.append(_format_labels(key))
                    values.append(float(total))
                    p50s.append(m.quantile(0.50, **lbl))
                    p95s.append(m.quantile(0.95, **lbl))
                    p99s.append(m.quantile(0.99, **lbl))
        return {
            "name": names,
            "kind": kinds,
            "labels": labels,
            "value": values,
            "p50": p50s,
            "p95": p95s,
            "p99": p99s,
        }

    def reset(self) -> None:
        """Drop all instruments (test isolation only)."""
        with self._lock:
            self._metrics.clear()


REGISTRY = MetricsRegistry()


def counter(name: str, help: str = "") -> Counter:
    return REGISTRY.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return REGISTRY.gauge(name, help)


def histogram(name: str, help: str = "", buckets: Optional[Sequence[float]] = None) -> Histogram:
    return REGISTRY.histogram(name, help, buckets)


def render_prometheus() -> str:
    return REGISTRY.render_prometheus()
