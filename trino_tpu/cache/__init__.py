"""Unified cache subsystem: one memory-accounted, observable home for the
engine's three cooperating cache tiers.

- signature:     canonical plan digests + determinism analysis (keys)
- result_cache:  fragment result pages, LRU + TPG2 disk spill (session tier)
- compile_cache: compiled XLA fragment executables (process tier, with a
                 persistent on-disk index via JAX's compilation cache)

The session-owned DeviceScanCache (exec/local.py) is the fourth, older tier;
CacheManager adopts it for stats so ``system.runtime.caches`` and /v1/cache
report every tier in one place.  Hits/misses/evictions flow to
utils.events listeners as CacheEvents.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from .compile_cache import CompileCache, shared_compile_cache
from .result_cache import FragmentResultCache
from .signature import (
    PlanSignature,
    analyze_determinism,
    fragment_fingerprint,
    plan_signature,
)

__all__ = [
    "CacheManager",
    "CompileCache",
    "FragmentResultCache",
    "PlanSignature",
    "analyze_determinism",
    "fragment_fingerprint",
    "plan_signature",
    "shared_compile_cache",
]

_ROW_COLUMNS = (
    "hits", "misses", "puts", "evictions", "entries", "bytes", "max_bytes",
    "heals", "invalidations",
)


class CacheManager:
    """Per-session facade over the cache tiers: stats aggregation for the
    system table / HTTP endpoint and the single CacheEvent funnel."""

    def __init__(
        self,
        result_cache: FragmentResultCache,
        compile_cache: CompileCache,
        scan_cache=None,
        events=None,
    ):
        self.result_cache = result_cache
        self.compile_cache = compile_cache
        self.scan_cache = scan_cache
        self._events = events

    def emit(self, tier: str, op: str, nbytes: int = 0) -> None:
        if self._events is not None:
            self._events.cache_event(tier, op, nbytes)

    def stats_rows(self) -> List[Dict[str, int]]:
        """Fixed-schema rows for system.runtime.caches (one per tier)."""
        rows = []
        for src in (self.result_cache, self.compile_cache, self.scan_cache):
            if src is None:
                continue
            st = src.stats()
            rows.append(
                {"name": st.get("name", type(src).__name__)}
                | {c: int(st.get(c, 0)) for c in _ROW_COLUMNS}
            )
        return rows

    def snapshot(self) -> List[dict]:
        """Full stats (superset of stats_rows) for /v1/cache."""
        out = []
        for src in (self.result_cache, self.compile_cache, self.scan_cache):
            if src is not None:
                out.append(dict(src.stats()))
        return out

    def clear(self) -> None:
        self.result_cache.clear()
        self.compile_cache.clear()
