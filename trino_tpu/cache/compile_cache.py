"""Compiled-fragment (XLA executable) cache with a persistent on-disk tier.

Absorbs the jit-key construction previously inlined in exec/local.py:
the key is (fragment fingerprint, capacity ladder state, per-scan padded
shape bucket + versioned scan identity + dictionary fingerprint), with every
plan-local component (node ids, force-set members) translated to plan
traversal ordinals so structurally identical fragments from different
sessions — or different processes — produce the same key.

Two tiers:

- in-memory, process-global (``shared_compile_cache()``): entries hold the
  jitted callable plus its trace cell; a second session re-running an
  already-seen fragment performs zero re-traces.  Exposes the dict surface
  exec/local expects (get/[]=/pop/clear) plus LRU bounding and stats.

- persistent, on-disk: JAX's persistent compilation cache (the established
  pattern for eliminating cold-start XLA compiles) keyed by the same
  program; ``attach_persistent`` points ``jax_compilation_cache_dir`` at a
  shared directory and maintains an index of (fingerprint, shape-bucket)
  digests this tier has compiled, so a second process re-traces but skips
  the XLA compile and records the reuse as a persistent hit.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
from collections import OrderedDict
from typing import Dict, Optional

from ..plan import nodes as P


def _stable(o) -> str:
    """Canonical textual form with deterministic set ordering (frozenset
    repr follows hash-table order, which is process-dependent)."""
    if isinstance(o, frozenset):
        return "fs{" + ",".join(sorted(_stable(x) for x in o)) + "}"
    if isinstance(o, (tuple, list)):
        return "(" + ",".join(_stable(x) for x in o) + ")"
    if isinstance(o, dict):
        return "{" + ",".join(
            sorted(f"{_stable(k)}:{_stable(v)}" for k, v in o.items())
        ) + "}"
    return repr(o)


def stable_key_digest(key) -> str:
    return hashlib.sha256(_stable(key).encode()).hexdigest()


def plan_ordinals(plan: P.PlanNode):
    """(id -> preorder ordinal, ordinal -> node) over the plan tree.  The
    ordinal is the cross-process-stable stand-in for id(node) in cache keys
    (two plans with equal fragment fingerprints traverse identically)."""
    order: Dict[int, int] = {}
    by_ord: Dict[int, P.PlanNode] = {}

    def walk(n):
        if id(n) in order:
            return
        o = len(order)
        order[id(n)] = o
        by_ord[o] = n
        for s in n.sources:
            walk(s)

    walk(plan)
    return order, by_ord


def fragment_key(ex, plan, scans, counts, pad_capacity):
    """Build the compile-cache key for one fragment execution attempt.
    Returns (key, order, by_ord); order/by_ord also serve the caller for
    translating trace-recorded node references (dup checks, force sets)
    between sessions sharing an entry.  ``ex`` is the executor (capacity
    ladder state + per-scan components); ``pad_capacity`` is passed in to
    avoid importing the executor module from here."""
    from .signature import fragment_fingerprint

    order, by_ord = plan_ordinals(plan)

    def o(i):
        return order.get(i, i)

    try:
        fp = fragment_fingerprint(plan)
    except Exception:  # unknown node kinds: degrade to per-object identity
        fp = id(plan)
    key = (
        fp, ex.group_capacity, ex.join_factor,
        getattr(ex, "topn_factor", 1),
        getattr(ex, "compact_factor", 1),
        getattr(ex, "group_salt", 0),
        getattr(ex, "force_wide_mul", False),
        frozenset(o(i) for i in getattr(ex, "force_expansion", ())),
        frozenset(o(i) for i in getattr(ex, "force_no_direct", ())),
        # a compiled program is a pure function of (plan, capacities,
        # padded lane shapes, BAKED dictionary contents) — NOT of which
        # splits produced the rows.  The per-scan component is therefore
        # (padded shape bucket, versioned-scan-identity-without-splits,
        # dictionary fingerprint).
        tuple(sorted(
            (o(nid),
             max(pad_capacity(counts[nid]),
                 int(ex.config.get("scan_cap_override") or 0)
                 if isinstance(ex._scan_nodes.get(nid), P.TableScan)
                 else 0),
             ex._jit_scan_component(nid))
            for nid in scans
        )),
    )
    return key, order, by_ord


def _key_buckets(key) -> list:
    """The padded shape buckets (ladder rungs) baked into a jit key.

    The per-scan component is the one element that is a tuple of
    ``(ordinal, rung, scan-identity)`` tuples — executors may append
    further marker components (``("donate", ...)``, ``("megakernels",
    ...)``) after it, so it is found by shape, not position."""
    if not isinstance(key, tuple):
        return []
    for comp in reversed(key):
        if (
            isinstance(comp, tuple) and comp
            and all(
                isinstance(c, tuple) and len(c) > 1
                and isinstance(c[1], int) for c in comp
            )
        ):
            return [int(c[1]) for c in comp]
    return []


class CompileCache:
    """LRU of compiled fragment entries ({"fn", "cell", "plan"}) exposing
    the dict surface the executor uses, with hit/miss/eviction accounting
    and an optional persistent tier."""

    def __init__(self, max_entries: int = 256, on_event=None):
        self._entries: "OrderedDict[object, dict]" = OrderedDict()
        self._lock = threading.RLock()
        self._on_event = on_event
        self._persistent_dir: Optional[str] = None
        self._index: Dict[str, dict] = {}
        self.hits = 0
        self.misses = 0
        self.puts = 0
        self.evictions = 0
        self.poison_evictions = 0
        self.persistent_hits = 0
        self.last_prewarm: Optional[dict] = None
        self.max_entries = int(max_entries)

    # -- persistent tier -------------------------------------------------
    def attach_persistent(self, cache_dir: str) -> None:
        """Point JAX's persistent compilation cache at ``cache_dir`` and
        load this cache's (fingerprint, shape-bucket) index.  Idempotent;
        safe to call from multiple sessions."""
        cache_dir = os.path.abspath(cache_dir)
        if self._persistent_dir == cache_dir:
            return
        os.makedirs(cache_dir, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # default thresholds skip tiny programs; a cache the operator asked
        # for should persist everything
        for opt, val in (
            ("jax_persistent_cache_min_compile_time_secs", 0.0),
            ("jax_persistent_cache_min_entry_size_bytes", -1),
        ):
            try:
                jax.config.update(opt, val)
            except Exception:
                pass
        self._persistent_dir = cache_dir
        self._index = self._load_index()

    def prewarm(self, cache_dir: str) -> Optional[dict]:
        """Cold-start prewarm of the persistent tier (once per directory).

        Two honest effects — no executables can be conjured without their
        plans, so this does exactly what a cold worker CAN do before the
        first query:

        - stream every cached artifact through a read so the XLA
          executables are in the OS page cache when the first re-trace
          asks for them (the disk read leaves the query path);
        - seed the compile observatory's family registry with every
          (family, kernel-digest) pair in the index, so the boot burst of
          re-traces classifies as ``persistent_load``/``first_compile``
          and the zero-retrace serve gate stays meaningful across
          restarts.

        Returns ``{"entries", "families", "rungShapes", "bytesPreloaded",
        "wallS"}`` for the bench's warm-start accounting, or None when
        already warmed / nothing to warm."""
        import time

        cache_dir = os.path.abspath(cache_dir)
        with self._lock:
            warmed = getattr(self, "_prewarmed_dirs", None)
            if warmed is None:
                warmed = self._prewarmed_dirs = set()
            if cache_dir in warmed:
                return None
            warmed.add(cache_dir)
        t0 = time.perf_counter()
        from ..obs import compile_observatory as _co

        obs = _co.get_observatory()
        families = set()
        rungs = set()
        for digest, rec in list(self._index.items()):
            fp = rec.get("fp")
            if fp is None:
                continue
            family = stable_key_digest(("family", fp))[:12]
            families.add(family)
            obs.seed_family(family, str(digest)[:12])
            for b in rec.get("buckets") or ():
                try:
                    rungs.add(int(b))
                except (TypeError, ValueError):
                    pass
        preloaded = 0
        try:
            names = os.listdir(cache_dir)
        except OSError:
            names = []
        for name in names:
            if name.startswith("index.json"):
                continue
            path = os.path.join(cache_dir, name)
            if not os.path.isfile(path):
                continue
            try:
                with open(path, "rb") as f:
                    while True:
                        chunk = f.read(1 << 20)
                        if not chunk:
                            break
                        preloaded += len(chunk)
            except OSError:
                continue
        result = {
            "entries": len(self._index),
            "families": len(families),
            "rungShapes": sorted(rungs),
            "bytesPreloaded": preloaded,
            "wallS": time.perf_counter() - t0,
        }
        self.last_prewarm = result
        return result

    def _index_path(self) -> str:
        return os.path.join(self._persistent_dir, "index.json")

    def _load_index(self) -> Dict[str, dict]:
        try:
            with open(self._index_path(), "r") as f:
                data = json.load(f)
            return dict(data.get("entries", {}))
        except (OSError, ValueError):
            return {}

    def _save_index(self) -> None:
        if self._persistent_dir is None:
            return
        tmp = self._index_path() + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({"entries": self._index}, f)
            os.replace(tmp, self._index_path())
        except OSError:
            pass

    def _index_record(self, key) -> None:
        if self._persistent_dir is None:
            return
        digest = stable_key_digest(key)
        rec = self._index.get(digest)
        if rec is None:
            self._index[digest] = {
                "fp": key[0] if isinstance(key, tuple) and key else None,
                "buckets": _key_buckets(key),
                "seen": 1,
            }
        else:
            rec["seen"] = rec.get("seen", 0) + 1
        self._save_index()

    # -- dict surface (exec/local duck-types this) -----------------------
    def get(self, key, default=None):
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                self._event("hit")
                return entry
            self.misses += 1
            # advisory: the program was compiled by an earlier process —
            # this execution re-traces, but XLA loads the executable from
            # jax's persistent cache instead of compiling
            if (self._persistent_dir is not None
                    and stable_key_digest(key) in self._index):
                self.persistent_hits += 1
                self._event("persistent_hit")
            else:
                self._event("miss")
            return default

    def __getitem__(self, key):
        entry = self.get(key)
        if entry is None:
            raise KeyError(key)
        return entry

    def __setitem__(self, key, entry) -> None:
        with self._lock:
            self._entries[key] = entry
            self._entries.move_to_end(key)
            self.puts += 1
            self._event("put")
            while len(self._entries) > self.max_entries:
                self._entries.popitem(last=False)
                self.evictions += 1
                self._event("evict")
            self._index_record(key)

    def pop(self, key, default=None):
        with self._lock:
            return self._entries.pop(key, default)

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._entries

    def __len__(self) -> int:
        return len(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    def persistent_known(self, key) -> bool:
        """Advisory, stat-free probe: was this program compiled by an
        earlier process (persistent-tier index hit)?  The compile
        observatory's cause classifier asks before a compile so the
        resulting re-trace is recorded as ``persistent_load`` rather
        than a genuine shape miss."""
        with self._lock:
            return (self._persistent_dir is not None
                    and stable_key_digest(key) in self._index)

    # -- poisoned-entry handling -----------------------------------------
    def evict_poisoned(self, key) -> bool:
        """Drop an entry whose executable faulted (the axon tunnel
        executable-reuse fault): the caller recompiles exactly once."""
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is not None:
                self.poison_evictions += 1
                self._event("poison_evict")
                return True
            return False

    def _event(self, op: str) -> None:
        from ..utils.metrics import REGISTRY

        REGISTRY.counter(
            "trino_tpu_cache_op_total", "Cache operations by tier and op"
        ).inc(tier="compile", op=op)
        # the observatory's tier view of the same ops: which compile-
        # cache tier (in-memory executable vs on-disk persistent index)
        # answered, feeding the cause taxonomy's persistent_load split
        REGISTRY.counter(
            "trino_tpu_compile_cache_tier_total",
            "Compile-cache operations by serving tier",
        ).inc(
            tier="persistent" if op == "persistent_hit" else "memory",
            op=op,
        )
        if self._on_event is not None:
            self._on_event("compile", op, 0)

    def stats(self) -> Dict[str, int]:
        return {
            "name": "compile_cache",
            "hits": self.hits,
            "misses": self.misses,
            "puts": self.puts,
            "evictions": self.evictions,
            "entries": len(self._entries),
            "bytes": 0,
            "max_bytes": 0,
            "heals": 0,
            "invalidations": 0,
            "poison_evictions": self.poison_evictions,
            "persistent_hits": self.persistent_hits,
        }


# One compile cache per process: executables are pure functions of the
# (fingerprint, shapes, dict-content) key, so sharing across sessions is
# safe — unlike result pages, which are session-scoped.
_SHARED: Optional[CompileCache] = None


def shared_compile_cache() -> CompileCache:
    global _SHARED
    if _SHARED is None:
        _SHARED = CompileCache()
    return _SHARED
