"""Canonical plan digests and fragment fingerprints.

Two related but distinct encodings of a plan tree feed the two cache tiers:

- ``plan_signature`` (result-cache key): a *canonical* digest that is
  invariant under output aliasing and symbol renaming, with literals
  parameterized out into a separate ``params`` tuple (the reference's
  canonical plan hashing for Presto's fragment result cache, Sethi et al.
  ICDE'19).  Join order, operator shapes, session-semantic plan attributes
  (distribution, expansion, direct domains) and the *shape* of constraints
  all stay in the digest, so two plans share a digest only when they compute
  the same function of the same tables modulo literal values.

- ``fragment_fingerprint`` (compile-cache key): an *exact* content hash —
  symbols, literals and output names included — that is stable across
  processes (unlike ``id(plan)`` / salted ``hash()``), so structurally
  identical fragments from different sessions map to the same compiled
  executable.

Both refuse nothing by themselves; determinism analysis is a separate pass
(`analyze_determinism`) consulting `expr.ir.NONDETERMINISTIC_FUNCTIONS` and
`Constant.nondeterministic_origin` so now()/rand()-class plans are never
result-cached.
"""
from __future__ import annotations

import dataclasses
import hashlib
from typing import Iterator, List, Optional, Tuple

from ..expr import ir
from ..plan import nodes as P

_SEP = "\x1f"


def shape_bucket(n: int) -> int:
    """Padded-shape bucket for compile-cache keys: next multiple of 128
    (TPU lane width) — delegates to exec.shapes.lane_align so in-memory
    and persistent keys coincide.  Note the jit key quantizes through the
    executor's PaddingLadder; this is the ladder-off floor."""
    from ..exec.shapes import lane_align

    return lane_align(int(n))


@dataclasses.dataclass(frozen=True)
class PlanSignature:
    digest: str  # canonical sha256 hex (alias/symbol/literal invariant)
    params: Tuple[str, ...]  # parameterized-out literals, in emission order
    tables: Tuple[Tuple[str, str], ...]  # (catalog, table) scanned, deduped
    deterministic: bool
    reason: Optional[str] = None  # why not deterministic / not cacheable


class _Emitter:
    """Token-stream serializer over the plan tree.  ``exact=False`` gives
    the canonical (result-cache) encoding; ``exact=True`` the fingerprint
    encoding.  Symbols are canonicalized to $k by first appearance; strings
    in protected positions (catalog/table/column/function/operator names)
    are emitted raw so real schema differences never alias."""

    def __init__(self, exact: bool):
        self.exact = exact
        self.tokens: List[str] = []
        self.params: List[str] = []
        self.tables: List[Tuple[str, str]] = []
        self._symmap: dict = {}

    # -- primitives ------------------------------------------------------
    def tok(self, *parts) -> None:
        self.tokens.append("|".join(str(p) for p in parts))

    def sym(self, s) -> str:
        if s is None:
            return "~"
        if self.exact:
            return str(s)
        v = self._symmap.get(s)
        if v is None:
            v = f"${len(self._symmap)}"
            self._symmap[s] = v
        return v

    def lit(self, value) -> str:
        """A literal value: inline when exact, parameterized otherwise."""
        if self.exact:
            return repr(value)
        self.params.append(repr(value))
        return "?"

    def ty(self, t) -> str:
        return "~" if t is None else str(t)

    def keys(self, syms) -> str:
        return ",".join(self.sym(s) for s in syms)

    def sortkeys(self, keys) -> str:
        return ",".join(
            f"{self.sym(k.column)}:{int(k.ascending)}:{int(k.nulls_first)}"
            for k in keys
        )

    # -- expressions -----------------------------------------------------
    def expr(self, e: Optional[ir.Expr]) -> None:
        if e is None:
            self.tok("e~")
            return
        if isinstance(e, ir.Constant):
            self.tok("lit", self.ty(e.type), self.lit(e.value),
                     int(e.nondeterministic_origin))
        elif isinstance(e, ir.ColumnRef):
            self.tok("col", self.sym(e.name), self.ty(e.type))
        elif isinstance(e, ir.Call):
            self.tok("call", e.name, self.ty(e.type), len(e.args))
            for a in e.args:
                self.expr(a)
        elif isinstance(e, ir.Comparison):
            self.tok("cmp", e.op)
            self.expr(e.left)
            self.expr(e.right)
        elif isinstance(e, ir.Logical):
            self.tok("logical", e.op, len(e.terms))
            for t in e.terms:
                self.expr(t)
        elif isinstance(e, ir.Not):
            self.tok("not")
            self.expr(e.term)
        elif isinstance(e, ir.IsNull):
            self.tok("isnull", int(e.negate))
            self.expr(e.term)
        elif isinstance(e, ir.Between):
            self.tok("between", int(e.negate))
            self.expr(e.value)
            self.expr(e.low)
            self.expr(e.high)
        elif isinstance(e, ir.In):
            self.tok("in", int(e.negate), len(e.items))
            self.expr(e.value)
            for i in e.items:
                self.expr(i)
        elif isinstance(e, ir.Case):
            self.tok("case", self.ty(e.type), len(e.whens))
            for w in e.whens:
                self.expr(w.condition)
                self.expr(w.result)
            self.expr(e.default)
        elif isinstance(e, ir.Cast):
            self.tok("cast", self.ty(e.type))
            self.expr(e.term)
        elif isinstance(e, ir.Lambda):
            # lambda params are user-chosen like aliases but scoping them
            # is not worth the complexity: emit raw (conservative — a
            # renamed lambda param changes the digest, never aliases).
            self.tok("lambda", self.ty(e.type), ",".join(e.params))
            self.expr(e.body)
        else:  # future Expr kinds: fall back to repr (exact, conservative)
            self.tok("expr", repr(e))

    # -- plan nodes ------------------------------------------------------
    def node(self, n: P.PlanNode) -> None:
        if isinstance(n, P.TableScan):
            tab = (n.catalog, n.table)
            if tab not in self.tables:
                self.tables.append(tab)
            self.tok(
                "scan", n.catalog, n.table,
                ",".join(f"{self.sym(s)}={c}" for s, c in n.assignments),
                ",".join(f"{self.sym(s)}:{self.ty(t)}" for s, t in n.types),
                # constraint values derive from the query's filter
                # literals: parameterize the whole tuple so literal-only
                # changes keep the digest
                self.lit(n.constraint) if n.constraint else "",
            )
        elif isinstance(n, P.Values):
            self.tok(
                "values", self.keys(n.symbols),
                ",".join(f"{self.sym(s)}:{self.ty(t)}" for s, t in n.types_),
                len(n.rows), self.lit((n.rows, n.dicts)),
            )
        elif isinstance(n, P.Filter):
            self.tok("filter", n.compact_rows)
            self.expr(n.predicate)
        elif isinstance(n, P.Project):
            self.tok("project", len(n.assignments))
            for s, e in n.assignments:
                self.tok("as", self.sym(s))
                self.expr(e)
        elif isinstance(n, P.GroupId):
            self.tok(
                "groupid", self.sym(n.gid_symbol),
                ";".join(self.keys(s) for s in n.sets),
            )
        elif isinstance(n, P.Aggregate):
            self.tok("agg", n.step, self.keys(n.keys), len(n.aggs))
            for a in n.aggs:
                self.tok(
                    "agginfo", self.sym(a.output), a.kind, self.sym(a.arg),
                    int(a.distinct), self.ty(a.input_type),
                    self.ty(a.output_type), self.sym(a.arg2),
                    self.ty(a.input2_type), repr(a.param),
                )
        elif isinstance(n, P.Join):
            self.tok(
                "join", n.kind,
                ",".join(f"{self.sym(a)}={self.sym(b)}"
                         for a, b in n.criteria),
                int(n.expansion), n.distribution, n.compact_rows,
                n.direct_domain,
            )
            self.expr(n.filter)
        elif isinstance(n, P.SemiJoin):
            self.tok(
                "semijoin", self.keys(n.source_keys),
                self.keys(n.filtering_keys), self.sym(n.output),
            )
            self.expr(n.filter)
        elif isinstance(n, P.ScalarJoin):
            self.tok("scalarjoin")
        elif isinstance(n, P.Window):
            self.tok(
                "window", self.keys(n.partition_by),
                self.sortkeys(n.order_by), len(n.functions),
            )
            for f in n.functions:
                fr = f.frame
                self.tok(
                    "winfn", self.sym(f.output), f.kind, self.keys(f.args),
                    repr(f.constants), fr.unit, fr.start_kind,
                    fr.start_offset, fr.end_kind, fr.end_offset,
                    self.ty(f.input_type), self.ty(f.output_type),
                )
        elif isinstance(n, P.Sort):
            self.tok("sort", self.sortkeys(n.keys))
        elif isinstance(n, P.TopN):
            self.tok("topn", n.count, self.sortkeys(n.keys))
        elif isinstance(n, P.Limit):
            self.tok("limit", n.count, n.offset)
        elif isinstance(n, P.Distinct):
            self.tok("distinct")
        elif isinstance(n, P.Sample):
            self.tok("sample", repr(n.fraction))
        elif isinstance(n, P.SetOperation):
            self.tok(
                "setop", n.kind, int(n.all), self.keys(n.symbols),
                ",".join(f"{self.sym(s)}:{self.ty(t)}" for s, t in n.types_),
            )
        elif isinstance(n, P.Unnest):
            self.tok(
                "unnest", self.sym(n.array_symbol),
                self.sym(n.element_symbol), self.ty(n.element_type),
                self.sym(n.ordinality_symbol), int(n.outer),
            )
        elif isinstance(n, P.MatchRecognize):
            self.tok(
                "match", self.keys(n.partition_by),
                self.sortkeys(n.order_by), repr(n.pattern), n.after_match,
                n.rows_per_match,
            )
            for name, e in n.defines:
                self.tok("define", name)
                self.expr(e)
            for s, e, t in n.measures:
                self.tok("measure", self.sym(s), self.ty(t))
                self.expr(e)
        elif isinstance(n, P.TableWriter):
            self.tok(
                "writer", n.catalog, n.table, ",".join(n.columns),
                int(n.overwrite), int(n.report_deleted),
                repr(n.create_schema), int(n.if_not_exists),
                self.sym(n.count_symbol), n.count_mode,
            )
        elif isinstance(n, P.Output):
            # client-facing names are excluded from the canonical digest
            # (alias invariance); the exact fingerprint keeps them
            if self.exact:
                self.tok("output", ",".join(n.names), self.keys(n.symbols))
            else:
                self.tok("output", self.keys(n.symbols))
        elif isinstance(n, P.Exchange):
            self.tok("exchange", n.partitioning, self.keys(n.keys))
        elif isinstance(n, P.RemoteSource):
            self.tok(
                "remote", n.fragment_id, self.keys(n.symbols),
                ",".join(f"{self.sym(s)}:{self.ty(t)}" for s, t in n.types_),
            )
        else:  # unknown node kind: exact repr keeps correctness (no
            # cross-plan aliasing), at the cost of canonicality
            self.tok("node", repr(n))
        for s in n.sources:
            self.node(s)


def iter_exprs(plan: P.PlanNode) -> Iterator[ir.Expr]:
    """Every ir.Expr reachable from any node field (predicates, projections,
    join filters, window/match definitions, ...)."""

    def from_val(v):
        if isinstance(v, P.PlanNode):
            return
        if isinstance(v, ir.Expr):
            yield v
        elif isinstance(v, tuple):
            for x in v:
                yield from from_val(x)
        elif dataclasses.is_dataclass(v):
            for f in dataclasses.fields(v):
                yield from from_val(getattr(v, f.name))

    stack = [plan]
    while stack:
        n = stack.pop()
        stack.extend(n.sources)
        for f in dataclasses.fields(n):
            yield from from_val(getattr(n, f.name))


def analyze_determinism(plan: P.PlanNode) -> Tuple[bool, Optional[str]]:
    """(deterministic, reason).  A plan is cacheable-deterministic when no
    expression calls a NONDETERMINISTIC_FUNCTIONS member and no constant was
    folded from one (now() & friends evaluate once per query)."""
    for e in iter_exprs(plan):
        for node in ir.walk(e):
            if (isinstance(node, ir.Call)
                    and node.name in ir.NONDETERMINISTIC_FUNCTIONS):
                return False, f"nondeterministic function {node.name}()"
            if isinstance(node, ir.Constant) and node.nondeterministic_origin:
                return False, "constant folded from a nondeterministic function"
    return True, None


def plan_signature(plan: P.PlanNode) -> PlanSignature:
    em = _Emitter(exact=False)
    em.node(plan)
    deterministic, reason = analyze_determinism(plan)
    digest = hashlib.sha256(_SEP.join(em.tokens).encode()).hexdigest()
    return PlanSignature(
        digest=digest,
        params=tuple(em.params),
        tables=tuple(em.tables),
        deterministic=deterministic,
        reason=reason,
    )


# id(plan) -> (plan, fingerprint): entries pin the plan object so the id
# stays valid while memoized; bounded by wholesale clear (plans are also
# pinned by the compile-cache entries that use them).
_FP_MEMO: dict = {}


def fragment_fingerprint(plan: P.PlanNode) -> str:
    """Exact, process-stable content hash of a plan/fragment tree — the
    compile-cache key component replacing id(plan)."""
    hit = _FP_MEMO.get(id(plan))
    if hit is not None and hit[0] is plan:
        return hit[1]
    em = _Emitter(exact=True)
    em.node(plan)
    fp = hashlib.sha256(_SEP.join(em.tokens).encode()).hexdigest()
    if len(_FP_MEMO) > 4096:
        _FP_MEMO.clear()
    _FP_MEMO[id(plan)] = (plan, fp)
    return fp
