"""Fragment-level columnar result cache.

Presto's fragment result caching (Sethi et al., ICDE'19) adapted to this
engine's whole-plan execution: entries are keyed by (canonical plan digest,
parameterized literals, per-table connector ``data_version`` fingerprints) —
the version component makes DML invalidation implicit (an INSERT bumps the
table's version, so the stale entry simply stops being addressable) while
``invalidate()`` eagerly reclaims its bytes.

Memory discipline: a byte-budgeted LRU accounted through utils.memory's
MemoryContext.  Cold (evicted) entries spill to disk as TPG2 checksummed
frames via serde.serialize_page; a corrupt spilled entry is a miss + heal
(the frame is deleted and the query recomputes), never a query error —
the same integrity contract the exchange/spool layer established.
"""
from __future__ import annotations

import hashlib
import os
import tempfile
import threading
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import numpy as np

from ..page import Page
from ..serde import PageIntegrityError, deserialize_page, serialize_page
from ..utils.memory import MemoryContext


def page_nbytes(page: Page) -> int:
    """Retained-size estimate of a Page (values + validity + dictionaries)."""
    total = 0
    for col in page.columns:
        v = np.asarray(col.values)
        total += v.nbytes
        if col.validity is not None:
            total += np.asarray(col.validity).nbytes
        if col.dictionary is not None:
            d = np.asarray(col.dictionary, dtype=object)
            total += sum(len(str(s)) + 8 for s in d.ravel())
    return total


def key_digest(key) -> str:
    """Stable hex digest of a cache key tuple (also the spill filename)."""
    return hashlib.sha256(repr(key).encode()).hexdigest()


class _Entry:
    __slots__ = ("page", "nbytes", "tables", "path")

    def __init__(self, page, nbytes, tables, path=None):
        self.page = page  # None when spilled to disk
        self.nbytes = nbytes
        self.tables = tables  # ((catalog, table), ...) for invalidation
        self.path = path  # spill file when page is None


class FragmentResultCache:
    """Session-scoped byte-budgeted LRU of query result Pages.

    Session-scoped on purpose: catalog names do not identify data across
    sessions (two sessions' memory catalogs share names but not stores), so
    a process-global result cache would serve one session's rows to another.
    The compile cache is the process-global tier.
    """

    def __init__(
        self,
        max_bytes: int = 256 << 20,
        spill_dir: Optional[str] = None,
        spill_max_bytes: int = 1 << 30,
        max_entry_fraction: float = 0.5,
        on_event=None,
    ):
        self.max_bytes = int(max_bytes)
        self.spill_max_bytes = int(spill_max_bytes)
        self.max_entry_fraction = float(max_entry_fraction)
        self._spill_dir = spill_dir
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self._spilled: "OrderedDict[str, _Entry]" = OrderedDict()
        self._lock = threading.Lock()
        self.memory = MemoryContext("result_cache")
        self._on_event = on_event
        self.hits = 0
        self.misses = 0
        self.puts = 0
        self.evictions = 0
        self.spills = 0
        self.spill_hits = 0
        self.heals = 0
        self.invalidations = 0
        self.rejected = 0

    # -- internals -------------------------------------------------------
    def _event(self, op: str, nbytes: int = 0) -> None:
        from ..utils.metrics import REGISTRY

        REGISTRY.counter(
            "trino_tpu_cache_op_total", "Cache operations by tier and op"
        ).inc(tier="result", op=op)
        if nbytes:
            REGISTRY.counter(
                "trino_tpu_cache_result_bytes", "Bytes moved through the result cache by op"
            ).inc(nbytes, op=op)
        if self._on_event is not None:
            self._on_event("result", op, nbytes)

    def _mem_bytes(self) -> int:
        return sum(e.nbytes for e in self._entries.values())

    def _account(self) -> None:
        self.memory.set_bytes(self._mem_bytes())

    def _ensure_spill_dir(self) -> str:
        if self._spill_dir is None:
            self._spill_dir = tempfile.mkdtemp(prefix="trino_tpu_rcache_")
        else:
            os.makedirs(self._spill_dir, exist_ok=True)
        return self._spill_dir

    def _spill(self, digest: str, entry: _Entry) -> None:
        """Evict to disk (best effort: an unwritable spill dir degrades to
        a plain drop, never an error)."""
        try:
            frame = serialize_page(entry.page)
            spill_used = sum(e.nbytes for e in self._spilled.values())
            if len(frame) + spill_used > self.spill_max_bytes:
                return
            path = os.path.join(self._ensure_spill_dir(), digest + ".tpg")
            with open(path, "wb") as f:
                f.write(frame)
        except OSError:
            return
        self._spilled[digest] = _Entry(None, len(frame), entry.tables, path)
        self.spills += 1
        self._event("spill", len(frame))

    def _evict_to_budget(self) -> None:
        while self._entries and self._mem_bytes() > self.max_bytes:
            digest, entry = self._entries.popitem(last=False)
            self._spill(digest, entry)
            self.evictions += 1
            self._event("evict", entry.nbytes)
        self._account()

    def _drop_spilled(self, digest: str) -> None:
        entry = self._spilled.pop(digest, None)
        if entry is not None and entry.path:
            try:
                os.unlink(entry.path)
            except OSError:
                pass

    # -- public surface --------------------------------------------------
    def get(self, key, injector=None) -> Optional[Page]:
        """Lookup by key tuple.  ``injector`` is the session FaultInjector:
        the ``cache_read`` site corrupts spilled frames to exercise the
        miss-and-heal path."""
        digest = key_digest(key)
        with self._lock:
            entry = self._entries.get(digest)
            if entry is not None:
                self._entries.move_to_end(digest)
                self.hits += 1
                self._event("hit", entry.nbytes)
                return entry.page
            spilled = self._spilled.get(digest)
            if spilled is not None:
                page = self._read_spilled(digest, spilled, injector)
                if page is not None:
                    # promote back to the hot tier
                    self._drop_spilled(digest)
                    entry = _Entry(page, page_nbytes(page), spilled.tables)
                    self._entries[digest] = entry
                    self._evict_to_budget()
                    self.hits += 1
                    self.spill_hits += 1
                    self._event("hit", entry.nbytes)
                    return page
                # corrupt or unreadable: healed (deleted) inside the read
                self.misses += 1
                self._event("miss")
                return None
            self.misses += 1
            self._event("miss")
            return None

    def _read_spilled(self, digest, entry, injector) -> Optional[Page]:
        try:
            with open(entry.path, "rb") as f:
                frame = f.read()
        except OSError:
            self._drop_spilled(digest)
            self.heals += 1
            self._event("heal")
            self._journal_heal(digest, "unreadable spilled frame")
            return None
        if injector is not None:
            frame = injector.corrupt("cache_read", frame, key=digest)
        try:
            return deserialize_page(frame)
        except (PageIntegrityError, ValueError):
            # checksum mismatch / truncation: heal by deleting the frame;
            # the caller recomputes and re-caches
            self._drop_spilled(digest)
            self.heals += 1
            self._event("heal")
            self._journal_heal(digest, "checksum mismatch")
            return None

    @staticmethod
    def _journal_heal(digest: str, why: str):
        from ..obs import journal

        journal.emit(
            journal.CACHE_HEAL, severity=journal.WARN,
            digest=str(digest)[:16], reason=why,
        )

    def put(self, key, page: Page, tables=()) -> bool:
        nbytes = page_nbytes(page)
        with self._lock:
            if nbytes > self.max_bytes * self.max_entry_fraction:
                self.rejected += 1
                self._event("reject", nbytes)
                return False
            digest = key_digest(key)
            self._drop_spilled(digest)
            self._entries[digest] = _Entry(page, nbytes, tuple(tables))
            self._entries.move_to_end(digest)
            self.puts += 1
            self._event("put", nbytes)
            self._evict_to_budget()
            return True

    def invalidate(self, catalog: str, table: str) -> int:
        """Eagerly drop every entry that scanned (catalog, table); returns
        the number of entries removed.  Version-keying already prevents
        stale hits — this reclaims the bytes."""
        target = (catalog, table)
        with self._lock:
            doomed = [d for d, e in self._entries.items() if target in e.tables]
            for d in doomed:
                del self._entries[d]
            doomed_spill = [
                d for d, e in self._spilled.items() if target in e.tables
            ]
            for d in doomed_spill:
                self._drop_spilled(d)
            n = len(doomed) + len(doomed_spill)
            if n:
                self.invalidations += n
                self._event("invalidate")
            self._account()
            return n

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            for d in list(self._spilled):
                self._drop_spilled(d)
            self._account()

    def stats(self) -> Dict[str, int]:
        return {
            "name": "result_cache",
            "hits": self.hits,
            "misses": self.misses,
            "puts": self.puts,
            "evictions": self.evictions,
            "entries": len(self._entries) + len(self._spilled),
            "bytes": self._mem_bytes(),
            "max_bytes": self.max_bytes,
            "heals": self.heals,
            "invalidations": self.invalidations,
        }
