"""Security: identities, authentication, and access control.

Reference parity: spi/security/ (Identity, AccessDeniedException,
SystemAccessControl), security/AccessControlManager (chained checks),
server/security/PasswordAuthenticator + the file-based rule plugins
(plugin/trino-password-authenticators' password file, and the file-based
system access control's catalog/table rules).
"""
from __future__ import annotations

import dataclasses
import fnmatch
import hashlib
from typing import Dict, List, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class Identity:
    """spi/security/Identity: the authenticated principal."""

    user: str
    groups: tuple = ()


class AccessDeniedError(PermissionError):
    """spi/security/AccessDeniedException."""


class AccessControl:
    """SystemAccessControl SPI subset: every method either returns or
    raises AccessDeniedError."""

    def check_can_execute_query(self, identity: Identity):
        pass

    def check_can_select(self, identity: Identity, catalog: str,
                         table: str, columns: Sequence[str]):
        pass

    def check_can_insert(self, identity: Identity, catalog: str, table: str):
        pass

    def check_can_delete(self, identity: Identity, catalog: str, table: str):
        pass

    def check_can_create_table(self, identity: Identity, catalog: str,
                               table: str):
        pass

    def check_can_drop_table(self, identity: Identity, catalog: str,
                             table: str):
        pass

    def check_can_set_session(self, identity: Identity, name: str):
        pass


class AllowAllAccessControl(AccessControl):
    pass


class FileBasedAccessControl(AccessControl):
    """Rule-list access control (the file-based SystemAccessControl):

    rules = {
      "catalogs": [{"user": glob, "catalog": glob,
                    "allow": "all"|"read-only"|"none"}, ...],
      "tables":   [{"user": glob, "catalog": glob, "table": glob,
                    "privileges": ["SELECT","INSERT","DELETE",
                                    "OWNERSHIP"]}, ...],
    }
    First matching rule wins; no catalog rule match denies the catalog,
    no table-rule section means tables inherit the catalog decision.
    """

    def __init__(self, rules: dict):
        self.catalog_rules: List[dict] = list(rules.get("catalogs", ()))
        self.table_rules: Optional[List[dict]] = (
            list(rules["tables"]) if "tables" in rules else None
        )

    def _catalog_access(self, identity: Identity, catalog: str) -> str:
        for r in self.catalog_rules:
            if not fnmatch.fnmatch(identity.user, r.get("user", "*")):
                continue
            if not fnmatch.fnmatch(catalog, r.get("catalog", "*")):
                continue
            return r.get("allow", "all")
        return "none"

    def _table_privileges(self, identity: Identity, catalog: str,
                          table: str) -> List[str]:
        if self.table_rules is None:
            access = self._catalog_access(identity, catalog)
            if access == "all":
                return ["SELECT", "INSERT", "DELETE", "OWNERSHIP"]
            if access == "read-only":
                return ["SELECT"]
            return []
        for r in self.table_rules:
            if not fnmatch.fnmatch(identity.user, r.get("user", "*")):
                continue
            if not fnmatch.fnmatch(catalog, r.get("catalog", "*")):
                continue
            if not fnmatch.fnmatch(table, r.get("table", "*")):
                continue
            return list(r.get("privileges", ()))
        return []

    def _require(self, identity, catalog, table, privilege):
        if self._catalog_access(identity, catalog) == "none":
            raise AccessDeniedError(
                f"Access Denied: Cannot access catalog {catalog}"
            )
        if privilege not in self._table_privileges(identity, catalog, table):
            raise AccessDeniedError(
                f"Access Denied: Cannot {privilege.lower()} from/into "
                f"table {catalog}.{table}"
            )

    def check_can_select(self, identity, catalog, table, columns):
        self._require(identity, catalog, table, "SELECT")

    def check_can_insert(self, identity, catalog, table):
        self._require(identity, catalog, table, "INSERT")

    def check_can_delete(self, identity, catalog, table):
        self._require(identity, catalog, table, "DELETE")

    def check_can_create_table(self, identity, catalog, table):
        self._require(identity, catalog, table, "OWNERSHIP")

    def check_can_drop_table(self, identity, catalog, table):
        self._require(identity, catalog, table, "OWNERSHIP")


class AccessControlManager(AccessControl):
    """security/AccessControlManager: every registered control must allow
    (deny-wins chaining)."""

    def __init__(self):
        self.controls: List[AccessControl] = []

    def add(self, control: AccessControl):
        self.controls.append(control)

    def _all(self, method: str, *args):
        for c in self.controls:
            getattr(c, method)(*args)

    def check_can_execute_query(self, identity):
        self._all("check_can_execute_query", identity)

    def check_can_select(self, identity, catalog, table, columns):
        self._all("check_can_select", identity, catalog, table, columns)

    def check_can_insert(self, identity, catalog, table):
        self._all("check_can_insert", identity, catalog, table)

    def check_can_delete(self, identity, catalog, table):
        self._all("check_can_delete", identity, catalog, table)

    def check_can_create_table(self, identity, catalog, table):
        self._all("check_can_create_table", identity, catalog, table)

    def check_can_drop_table(self, identity, catalog, table):
        self._all("check_can_drop_table", identity, catalog, table)

    def check_can_set_session(self, identity, name):
        self._all("check_can_set_session", identity, name)


class JwtAuthenticator:
    """Bearer-token (JWT) authentication — server/security/jwt analog.

    Validates HS256-signed JWTs with the standard compact serialization
    (header.payload.signature, base64url), checking the signature against
    the shared secret, `exp` expiry, and optional required audience; the
    identity comes from the principal-field claim (default `sub`).  RS256
    public-key verification is out of scope (stdlib-only build)."""

    def __init__(self, secret: str, principal_field: str = "sub",
                 audience: Optional[str] = None):
        self.secret = secret.encode()
        self.principal_field = principal_field
        self.audience = audience

    @staticmethod
    def _b64url_decode(s: str) -> bytes:
        import base64

        pad = "=" * (-len(s) % 4)
        return base64.urlsafe_b64decode(s + pad)

    @staticmethod
    def _b64url_encode(b: bytes) -> str:
        import base64

        return base64.urlsafe_b64encode(b).rstrip(b"=").decode()

    def sign(self, claims: Dict[str, object]) -> str:
        """Mint a token (testing + internal cluster communication)."""
        import hmac
        import json as _json

        header = self._b64url_encode(
            _json.dumps({"alg": "HS256", "typ": "JWT"}).encode()
        )
        payload = self._b64url_encode(_json.dumps(claims).encode())
        msg = f"{header}.{payload}".encode()
        sig = self._b64url_encode(
            hmac.new(self.secret, msg, hashlib.sha256).digest()
        )
        return f"{header}.{payload}.{sig}"

    def authenticate_token(self, token: str) -> Identity:
        import hmac
        import json as _json
        import time as _time

        try:
            header_s, payload_s, sig_s = token.split(".")
            header = _json.loads(self._b64url_decode(header_s))
            if header.get("alg") != "HS256":
                raise ValueError(f"unsupported alg {header.get('alg')}")
            msg = f"{header_s}.{payload_s}".encode()
            want = hmac.new(self.secret, msg, hashlib.sha256).digest()
            if not hmac.compare_digest(want, self._b64url_decode(sig_s)):
                raise ValueError("bad signature")
            claims = _json.loads(self._b64url_decode(payload_s))
        except AccessDeniedError:
            raise
        except Exception as e:
            raise AccessDeniedError(f"Access Denied: invalid JWT ({e})")
        exp = claims.get("exp")
        if exp is not None and _time.time() > float(exp):
            raise AccessDeniedError("Access Denied: token expired")
        if self.audience is not None:
            aud = claims.get("aud")
            auds = aud if isinstance(aud, list) else [aud]
            if self.audience not in auds:
                raise AccessDeniedError("Access Denied: wrong audience")
        principal = claims.get(self.principal_field)
        if not principal:
            raise AccessDeniedError(
                f"Access Denied: missing {self.principal_field} claim"
            )
        return Identity(str(principal))


class PasswordAuthenticator:
    """Password-file authentication (plugin/trino-password-authenticators
    PasswordStore): users map to salted sha256 digests; authenticate()
    returns an Identity or raises AccessDeniedError."""

    def __init__(self, users: Dict[str, str], salt: str = "trino-tpu"):
        """users: user -> plaintext password (hashed at construction)."""
        self.salt = salt
        self.digests = {
            u: self._digest(p) for u, p in users.items()
        }

    def _digest(self, password: str) -> str:
        return hashlib.sha256(
            (self.salt + ":" + password).encode()
        ).hexdigest()

    def authenticate(self, user: str, password: str) -> Identity:
        want = self.digests.get(user)
        if want is None or want != self._digest(password):
            raise AccessDeniedError("Access Denied: Invalid credentials")
        return Identity(user)
