"""Catalog management: mounted connectors + metadata facade.

Reference parity: metadata/MetadataManager (facade over connectors),
connector/CatalogManager + DefaultCatalogFactory (etc/catalog/*.properties
-> ConnectorFactory.create per catalog).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from .spi import Connector, ConnectorFactory, TableSchema, TableStatistics


class CatalogManager:
    def __init__(self):
        self._factories: Dict[str, ConnectorFactory] = {}
        self._catalogs: Dict[str, Connector] = {}

    def register_factory(self, factory: ConnectorFactory):
        self._factories[factory.name] = factory

    def create_catalog(self, name: str, connector_name: str, config: dict):
        factory = self._factories[connector_name]
        self._catalogs[name] = factory.create(name, config)

    def get(self, name: str) -> Connector:
        if name not in self._catalogs:
            raise KeyError(f"catalog not found: {name}")
        return self._catalogs[name]

    def names(self) -> List[str]:
        return list(self._catalogs)


class Metadata:
    """MetadataManager analog: resolution entry point for the analyzer."""

    def __init__(self, catalogs: CatalogManager):
        self.catalogs = catalogs

    def resolve_table(
        self, parts, default_catalog: Optional[str]
    ) -> "tuple[str, TableSchema]":
        """parts: (table,) | (schema, table) | (catalog, schema, table)."""
        if len(parts) == 3:
            catalog, _schema, table = parts
        elif len(parts) == 2:
            catalog, table = default_catalog, parts[1]
        else:
            catalog, table = default_catalog, parts[0]
        if catalog is None:
            raise ValueError(f"no catalog specified for table {'.'.join(parts)}")
        conn = self.catalogs.get(catalog)
        md = conn.metadata()
        if parts[-1] not in md.list_tables():
            raise KeyError(f"table not found: {catalog}.{parts[-1]}")
        return catalog, md.get_table_schema(parts[-1])

    def resolve_new_table(
        self, parts, default_catalog: Optional[str]
    ) -> "tuple[str, str]":
        """(catalog, table) for a table that need not exist (DDL targets)."""
        if len(parts) == 3:
            catalog, _schema, table = parts
        elif len(parts) == 2:
            catalog, table = default_catalog, parts[1]
        else:
            catalog, table = default_catalog, parts[0]
        if catalog is None:
            raise ValueError(f"no catalog specified for table {'.'.join(parts)}")
        return catalog, table

    def table_statistics(self, catalog: str, table: str) -> TableStatistics:
        return self.catalogs.get(catalog).metadata().get_table_statistics(table)
