"""Catalog management: mounted connectors + metadata facade.

Reference parity: metadata/MetadataManager (facade over connectors),
connector/CatalogManager + DefaultCatalogFactory (etc/catalog/*.properties
-> ConnectorFactory.create per catalog).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from .spi import Connector, ConnectorFactory, TableSchema, TableStatistics


@dataclasses.dataclass(frozen=True)
class ViewDefinition:
    """Stored CREATE VIEW definition (metadata/ViewDefinition.java:28:
    originalSql + column list, expanded at analysis time by the
    StatementAnalyzer's view branch — here Analyzer._plan_table)."""

    catalog: str
    name: str
    original_sql: str  # the view's query text, as written
    query: object  # parsed ast.Node of the query
    columns: Tuple[Tuple[str, str], ...]  # (name, type text) at creation
    # session default catalog when the view was created: unqualified
    # names inside the view resolve against THIS, not whatever catalog
    # the querying session happens to have selected (the reference's
    # ViewDefinition stores catalog+schema for the same reason)
    context_catalog: Optional[str] = None


class CatalogManager:
    def __init__(self):
        self._factories: Dict[str, ConnectorFactory] = {}
        self._catalogs: Dict[str, Connector] = {}

    def register_factory(self, factory: ConnectorFactory):
        self._factories[factory.name] = factory

    def create_catalog(self, name: str, connector_name: str, config: dict):
        factory = self._factories[connector_name]
        self._catalogs[name] = factory.create(name, config)

    def get(self, name: str) -> Connector:
        if name not in self._catalogs:
            raise KeyError(f"catalog not found: {name}")
        return self._catalogs[name]

    def names(self) -> List[str]:
        return list(self._catalogs)


class Metadata:
    """MetadataManager analog: resolution entry point for the analyzer."""

    def __init__(self, catalogs: CatalogManager):
        self.catalogs = catalogs
        # session-lived view registry keyed (catalog, view_name); the
        # reference delegates durability to connector metastores, which
        # the memory-connector-style store mirrors for every catalog
        self.views: Dict[Tuple[str, str], ViewDefinition] = {}
        # ANALYZE overlay for connectors without durable stats storage:
        # (catalog, table) -> (data_version at collection, stats).  Served
        # only while the connector's data_version still matches, so DML
        # invalidates overlay stats exactly like stored ones.
        self.analyzed: Dict[Tuple[str, str], Tuple[int, TableStatistics]] = {}

    def _qualify(self, parts, default_catalog: Optional[str]):
        if len(parts) == 3:
            return parts[0], parts[2]
        if len(parts) == 2:
            return default_catalog, parts[1]
        return default_catalog, parts[0]

    def lookup_view(
        self, parts, default_catalog: Optional[str]
    ) -> Optional[ViewDefinition]:
        catalog, name = self._qualify(parts, default_catalog)
        if catalog is None:
            return None
        return self.views.get((catalog, name.lower()))

    def create_view(self, view: ViewDefinition, replace: bool):
        key = (view.catalog, view.name.lower())
        if not replace and key in self.views:
            raise ValueError(f"view already exists: {view.name}")
        # a view must not shadow a real table (the reference raises
        # TABLE_ALREADY_EXISTS at analysis)
        try:
            tables = self.catalogs.get(view.catalog).metadata().list_tables()
        except (KeyError, NotImplementedError):
            tables = []
        if view.name.lower() in tables:
            raise ValueError(
                f"table with that name already exists: {view.name}"
            )
        self.views[key] = view

    def drop_view(self, parts, default_catalog, if_exists: bool):
        catalog, name = self._qualify(parts, default_catalog)
        key = (catalog, name.lower())
        if key not in self.views:
            if if_exists:
                return False
            raise KeyError(f"view not found: {'.'.join(parts)}")
        del self.views[key]
        return True

    def list_views(self, catalog: str) -> List[str]:
        return sorted(n for c, n in self.views if c == catalog)

    def resolve_table(
        self, parts, default_catalog: Optional[str]
    ) -> "tuple[str, TableSchema]":
        """parts: (table,) | (schema, table) | (catalog, schema, table)."""
        if len(parts) == 3:
            catalog, _schema, table = parts
        elif len(parts) == 2:
            catalog, table = default_catalog, parts[1]
        else:
            catalog, table = default_catalog, parts[0]
        if catalog is None:
            raise ValueError(f"no catalog specified for table {'.'.join(parts)}")
        conn = self.catalogs.get(catalog)
        md = conn.metadata()
        if parts[-1] not in md.list_tables():
            raise KeyError(f"table not found: {catalog}.{parts[-1]}")
        return catalog, md.get_table_schema(parts[-1])

    def resolve_new_table(
        self, parts, default_catalog: Optional[str]
    ) -> "tuple[str, str]":
        """(catalog, table) for a table that need not exist (DDL targets)."""
        if len(parts) == 3:
            catalog, _schema, table = parts
        elif len(parts) == 2:
            catalog, table = default_catalog, parts[1]
        else:
            catalog, table = default_catalog, parts[0]
        if catalog is None:
            raise ValueError(f"no catalog specified for table {'.'.join(parts)}")
        return catalog, table

    def table_statistics(self, catalog: str, table: str) -> TableStatistics:
        from .utils.metrics import counter

        conn = self.catalogs.get(catalog)
        entry = self.analyzed.get((catalog, table))
        if entry is not None:
            version, stats = entry
            if conn.data_version(table) == version:
                counter("trino_tpu_stats_served_total").inc()
                return stats
            del self.analyzed[(catalog, table)]
        stats = conn.metadata().get_table_statistics(table)
        # connector-stored ANALYZE results (histograms prove provenance)
        # count as serves too; bare row-count fallbacks count as misses
        if any(
            c.histogram is not None or c.distinct_count is not None
            for c in stats.columns.values()
        ):
            counter("trino_tpu_stats_served_total").inc()
        else:
            counter("trino_tpu_stats_missed_total").inc()
        return stats

    def store_table_statistics(
        self, catalog: str, table: str, stats: TableStatistics
    ) -> int:
        """Route ANALYZE output to the connector's durable store when it
        has one, else to the session overlay; either way keyed by the
        data_version snapshotted here.  Returns that version."""
        conn = self.catalogs.get(catalog)
        version = conn.data_version(table)
        # merge over whatever is currently served so a column-subset
        # ANALYZE refines rather than erases the other columns' stats
        try:
            base = self.table_statistics(catalog, table)
            merged = dict(base.columns)
            merged.update(stats.columns)
            stats = TableStatistics(stats.row_count, merged)
        except Exception:
            pass
        try:
            conn.metadata().store_table_statistics(table, stats, version)
            self.analyzed.pop((catalog, table), None)
        except NotImplementedError:
            self.analyzed[(catalog, table)] = (version, stats)
        return version
