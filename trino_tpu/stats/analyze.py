"""ANALYZE synthesis: statistics collection as ordinary SQL.

Reference parity: sql/rewrite/StatementRewrite turning ANALYZE into a
statistics-aggregation plan (QueryPlanner.planStatisticsAggregation)
— the collection query IS a distributed aggregation, so partial/final
HLL and KMV merges ride the normal exchange machinery and the
reductions themselves (count / min / max / approx_distinct /
approx_percentile) compile to on-device XLA like any query.

Per rangeable (numeric/date/timestamp) column c, one chunk query
contributes::

    count(c), approx_distinct(c),
    min(CAST(c AS DOUBLE)), max(CAST(c AS DOUBLE)),
    approx_percentile(CAST(c AS DOUBLE), j/b)  for j = 0..b

and ``assemble`` folds the single result row of each chunk into a
``TableStatistics`` (NDV clamped to the non-null count, quantile ends
clamped to the exact min/max, equi-height histogram from the
boundaries).  Non-rangeable columns (varchar, boolean, ...) get
count / NDV / null-fraction only.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

from .. import types as T
from ..spi import ColumnStatistics, TableSchema, TableStatistics
from .histogram import equi_height_from_quantiles

# columns per synthesized query; bounds the width of any one fragment
# (a b-bucket rangeable column contributes b+5 aggregates)
CHUNK_COLUMNS = 4


@dataclasses.dataclass(frozen=True)
class ColumnTask:
    """One column's slice of the collection plan."""

    name: str
    type_name: str
    rangeable: bool  # castable to DOUBLE with a meaningful order

    def expressions(self, buckets: int) -> List[str]:
        q = self.name
        exprs = [f"count({q})", f"approx_distinct({q})"]
        if self.rangeable:
            cast = f"CAST({q} AS DOUBLE)"
            exprs += [f"min({cast})", f"max({cast})"]
            exprs += [
                f"approx_percentile({cast}, {j / buckets!r})"
                for j in range(buckets + 1)
            ]
        return exprs


def _rangeable(t: T.Type) -> bool:
    return T.is_numeric(t) or t.name in ("date", "timestamp")


def column_tasks(
    schema: TableSchema, columns: Sequence[str] = ()
) -> List[ColumnTask]:
    """Tasks for the requested columns (all, when none named)."""
    known = {c.name for c in schema.columns}
    for name in columns:
        if name not in known:
            raise KeyError(
                f"Column '{name}' does not exist in table '{schema.name}'"
            )
    want = set(columns) if columns else known
    return [
        ColumnTask(c.name, c.type.name, _rangeable(c.type))
        for c in schema.columns
        if c.name in want
    ]


def analyze_queries(
    qualified: str,
    tasks: Sequence[ColumnTask],
    buckets: int,
) -> List[Tuple[str, Tuple[ColumnTask, ...]]]:
    """Chunked collection SQL: [(sql, tasks_in_chunk), ...].

    Every chunk leads with count(*) so each query is self-contained
    (and an empty table short-circuits identically in all of them).
    """
    out = []
    chunks = [
        tuple(tasks[i:i + CHUNK_COLUMNS])
        for i in range(0, len(tasks), CHUNK_COLUMNS)
    ] or [()]
    for chunk in chunks:
        exprs = ["count(*)"]
        for t in chunk:
            exprs.extend(t.expressions(buckets))
        sql = f"SELECT {', '.join(exprs)} FROM {qualified}"
        out.append((sql, chunk))
    return out


def _as_float(v) -> Optional[float]:
    return None if v is None else float(v)


def assemble(
    chunk_results: Sequence[Tuple[Tuple[ColumnTask, ...], Sequence]],
    buckets: int,
) -> TableStatistics:
    """Fold each chunk's single result row into TableStatistics."""
    row_count = 0.0
    columns = {}
    for tasks, row in chunk_results:
        vals = list(row)
        row_count = float(vals[0] or 0)
        pos = 1
        for t in tasks:
            nonnull = float(vals[pos] or 0)
            ndv = float(vals[pos + 1] or 0)
            pos += 2
            lo = hi = None
            hist = None
            if t.rangeable:
                lo = _as_float(vals[pos])
                hi = _as_float(vals[pos + 1])
                qs = [_as_float(v) for v in vals[pos + 2:pos + 3 + buckets]]
                pos += 2 + buckets + 1
                if lo is not None and hi is not None and None not in qs:
                    # KMV quantiles are approximate; the aggregation also
                    # carried the exact extremes, so pin the ends to them
                    qs = [min(max(q, lo), hi) for q in qs]
                    qs[0], qs[-1] = lo, hi
                    hist = equi_height_from_quantiles(qs) or None
            if row_count <= 0:
                columns[t.name] = ColumnStatistics(0.0, 0.0, None, None)
                continue
            columns[t.name] = ColumnStatistics(
                distinct_count=min(ndv, nonnull),
                null_fraction=min(1.0, max(0.0, 1.0 - nonnull / row_count)),
                min_value=lo,
                max_value=hi,
                histogram=hist,
            )
    return TableStatistics(row_count=row_count, columns=columns)
