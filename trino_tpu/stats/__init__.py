"""Table & column statistics subsystem.

Reference parity: the statistics half of the CBO loop — ANALYZE
(sql/rewrite/StatementRewrite -> QueryPlanner.planStatisticsAggregation
building a distributed aggregation over the table), the
spi/statistics surface (TableStatistics / ColumnStatistics /
TableStatisticsMetadata), and the StatsCalculator consumption side in
sql/planner/cost/.  Collection here is literally a synthesized SQL
aggregation run through the normal planner and executors, so the
on-device reductions (count / min / max / HLL NDV / KMV quantiles)
split PARTIAL/FINAL across workers like any other aggregation.
"""
from .analyze import ColumnTask, analyze_queries, assemble, column_tasks
from .histogram import (
    equi_height_from_quantiles,
    le_fraction,
    range_fraction,
)

__all__ = [
    "ColumnTask",
    "analyze_queries",
    "assemble",
    "column_tasks",
    "equi_height_from_quantiles",
    "le_fraction",
    "range_fraction",
]
