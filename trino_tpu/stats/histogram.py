"""Equi-height histograms and the selectivity math over them.

Reference parity: io.trino.cost.FilterStatsCalculator estimating range
predicates against a StatisticRange; we additionally carry an explicit
equi-height histogram (buckets of equal row fraction between quantile
boundaries) because the quantiles fall out of the same device sort the
ANALYZE aggregation already runs (approx_percentile over the KMV
sample), so per-bucket interpolation is free.

A histogram is a tuple of ``(low, high, fraction)`` buckets ordered by
``low``, fractions summing to ~1.0 over the non-null rows.  Plain
tuples keep ``ColumnStatistics`` hashable and trivially
JSON-serializable for the hive sidecar.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

Bucket = Tuple[float, float, float]
Histogram = Tuple[Bucket, ...]


def equi_height_from_quantiles(qs: Sequence[float]) -> Histogram:
    """Build an equi-height histogram from b+1 quantile boundaries.

    Each adjacent boundary pair becomes a bucket holding 1/b of the
    rows.  Repeated boundaries (heavy values spanning several
    quantiles) are merged into one fatter bucket so zero-width buckets
    only ever appear as genuine point masses.
    """
    qs = [float(q) for q in qs if q is not None]
    if len(qs) < 2:
        return ()
    b = len(qs) - 1
    frac = 1.0 / b
    buckets = []
    for lo, hi in zip(qs, qs[1:]):
        if buckets and buckets[-1][0] == lo and buckets[-1][1] == hi:
            prev = buckets[-1]
            buckets[-1] = (prev[0], prev[1], prev[2] + frac)
        else:
            buckets.append((lo, hi, frac))
    return tuple(buckets)


def le_fraction(hist: Histogram, v: float) -> Optional[float]:
    """Fraction of (non-null) rows with value <= v, by interpolation."""
    if not hist:
        return None
    total = 0.0
    acc = 0.0
    for lo, hi, frac in hist:
        total += frac
        if v >= hi:
            acc += frac
        elif v < lo:
            pass
        elif hi > lo:
            acc += frac * (v - lo) / (hi - lo)
        else:  # zero-width bucket: point mass at lo == hi
            acc += frac if v >= hi else 0.0
    if total <= 0.0:
        return None
    return min(1.0, max(0.0, acc / total))


def range_fraction(
    hist: Histogram,
    low: Optional[float],
    high: Optional[float],
) -> Optional[float]:
    """Fraction of rows in [low, high] (None = unbounded on that side)."""
    if not hist:
        return None
    hi_frac = le_fraction(hist, high) if high is not None else 1.0
    lo_frac = le_fraction(hist, low) if low is not None else 0.0
    if hi_frac is None or lo_frac is None:
        return None
    return min(1.0, max(0.0, hi_frac - lo_frac))
