"""Local-disk object store with S3 semantics + seeded chaos.

The backend maps slash-separated keys onto a root directory but keeps
object-store discipline: whole-object atomic PUT (temp file +
``os.replace``), ranged GET, idempotent DELETE, LIST-by-prefix, and
compare-and-swap under an ``fcntl`` advisory lock so concurrent
processes serialize exactly like S3 conditional writes.

Chaos: every operation passes the seeded fault sites
``objstore_latency`` (stall), ``objstore_error`` (transient 500 analog)
and ``objstore_throttle`` (503 SlowDown analog), keyed ``"{op}:{path}"``
so a rule's ``match`` can target e.g. only metadata-pointer reads.
Transient faults are retried with bounded exponential backoff — the
Dean & Barroso tail-tolerance brief applied to storage: a rule with
small ``times`` is invisible to callers (absorbed, counted), while a
persistent rule exhausts the budget and surfaces a structured
:class:`ObjectStoreError`.

Dot-prefixed names (``.xxx``) are internal (lock files, temp parts) and
never listed — the same hidden-file convention the hive connector uses
for its stats sidecars.
"""
from __future__ import annotations

import os
import time
import uuid
from typing import List, Optional

from ..utils.metrics import REGISTRY
from .filesystem import (
    FileEntry,
    ObjectStoreError,
    TransientObjectStoreError,
    TrinoFileSystem,
)

# bounded backoff: MAX_ATTEMPTS tries, BASE_BACKOFF_S * 2^i between them
MAX_ATTEMPTS = 5
BASE_BACKOFF_S = 0.005


def _ops_counter():
    return REGISTRY.counter(
        "trino_tpu_objstore_ops_total",
        "Object-store operations by op kind",
    )


class LocalObjectStore(TrinoFileSystem):
    """S3-style store on a local directory root.

    ``injector`` is an optional utils.faults.FaultInjector carrying
    objstore_* rules; None disables chaos entirely (zero overhead)."""

    def __init__(self, root: str, injector=None,
                 max_attempts: int = MAX_ATTEMPTS):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        self.injector = injector
        self.max_attempts = max(1, int(max_attempts))

    # -- key mapping ---------------------------------------------------
    def _local(self, path: str) -> str:
        p = os.path.normpath(path.strip("/"))
        if p.startswith("..") or os.path.isabs(p):
            raise ObjectStoreError(f"key escapes the store root: {path!r}")
        return os.path.join(self.root, p)

    def local_path(self, path: str) -> str:
        """Escape hatch for libraries that need a real file path
        (pyarrow parquet readers).  Local-backend only by design; a
        networked backend would download to a scratch file here."""
        return self._local(path)

    # -- chaos + retry --------------------------------------------------
    def _faults(self, op: str, path: str):
        inj = self.injector
        if inj is None:
            return
        key = f"{op}:{path}"
        inj.stall("objstore_latency", key)
        if inj.fires("objstore_throttle", key):
            raise TransientObjectStoreError(
                f"objstore throttled (injected): {op} {path}"
            )
        if inj.fires("objstore_error", key):
            raise TransientObjectStoreError(
                f"objstore transient error (injected): {op} {path}"
            )

    def _run(self, op: str, path: str, fn):
        """Retry loop: transient faults (and transient OS-level races)
        back off and retry; exhaustion raises a structured error."""
        _ops_counter().inc(op=op)
        last: Optional[Exception] = None
        for attempt in range(self.max_attempts):
            if attempt:
                REGISTRY.counter(
                    "trino_tpu_objstore_retries_total",
                    "Object-store retries after transient errors",
                ).inc(op=op)
                time.sleep(BASE_BACKOFF_S * (1 << (attempt - 1)))
            try:
                self._faults(op, path)
                return fn()
            except TransientObjectStoreError as exc:
                last = exc
        REGISTRY.counter(
            "trino_tpu_objstore_errors_total",
            "Object-store operations failed after retry exhaustion",
        ).inc(op=op)
        raise ObjectStoreError(
            f"{op} {path}: retries exhausted after "
            f"{self.max_attempts} attempts: {last}"
        )

    # -- operations -----------------------------------------------------
    def list_files(self, prefix: str = "") -> List[FileEntry]:
        def _list():
            base = self._local(prefix) if prefix else self.root
            out: List[FileEntry] = []
            if not os.path.isdir(base):
                # a prefix may name a single object (S3 LIST on a key)
                if os.path.isfile(base):
                    st = os.stat(base)
                    out.append(FileEntry(
                        prefix.strip("/"), st.st_size, st.st_mtime_ns
                    ))
                return out
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = sorted(
                    d for d in dirnames if not d.startswith(".")
                )
                for fn in sorted(filenames):
                    if fn.startswith("."):
                        continue  # internal: locks, temp parts
                    full = os.path.join(dirpath, fn)
                    st = os.stat(full)
                    out.append(FileEntry(
                        os.path.relpath(full, self.root).replace(
                            os.sep, "/"
                        ),
                        st.st_size, st.st_mtime_ns,
                    ))
            out.sort(key=lambda e: e.path)
            return out

        return self._run("list", prefix or "/", _list)

    def exists(self, path: str) -> bool:
        return self._run(
            "head", path, lambda: os.path.isfile(self._local(path))
        )

    def read_file(
        self, path: str, offset: int = 0, length: Optional[int] = None
    ) -> bytes:
        def _read():
            try:
                with open(self._local(path), "rb") as f:
                    if offset:
                        f.seek(offset)
                    return f.read(length) if length is not None else f.read()
            except FileNotFoundError:
                raise ObjectStoreError(f"no such object: {path}") from None

        return self._run("get", path, _read)

    def write_file(self, path: str, data: bytes) -> None:
        def _write():
            dest = self._local(path)
            os.makedirs(os.path.dirname(dest), exist_ok=True)
            tmp = os.path.join(
                os.path.dirname(dest),
                f".tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}",
            )
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, dest)  # atomic: readers see old or new bytes

        self._run("put", path, _write)
        REGISTRY.counter(
            "trino_tpu_objstore_written_bytes",
            "Bytes written to the object store",
        ).inc(len(data))

    def delete_file(self, path: str) -> None:
        def _delete():
            try:
                os.remove(self._local(path))
            except FileNotFoundError:
                pass  # S3 DELETE is idempotent

        self._run("delete", path, _delete)

    def compare_and_swap(
        self, path: str, expected: Optional[bytes], new: bytes
    ) -> bool:
        """The commit primitive: serialize via an advisory flock on a
        dot-prefixed sibling so concurrent WRITER PROCESSES (not just
        threads) observe read-compare-replace as one step."""
        import fcntl

        def _cas():
            dest = self._local(path)
            os.makedirs(os.path.dirname(dest) or self.root, exist_ok=True)
            lock = os.path.join(
                os.path.dirname(dest),
                f".{os.path.basename(dest)}.lock",
            )
            with open(lock, "a+b") as lf:
                fcntl.flock(lf.fileno(), fcntl.LOCK_EX)
                try:
                    try:
                        with open(dest, "rb") as f:
                            current: Optional[bytes] = f.read()
                    except FileNotFoundError:
                        current = None
                    if current != expected:
                        return False
                    tmp = dest + f".{os.getpid()}.casnew"
                    with open(tmp, "wb") as f:
                        f.write(new)
                        f.flush()
                        os.fsync(f.fileno())
                    os.replace(tmp, dest)
                    return True
                finally:
                    fcntl.flock(lf.fileno(), fcntl.LOCK_UN)

        return self._run("cas", path, _cas)
