"""Object-store filesystem abstraction (TrinoFileSystem analog).

Reference parity: lib/trino-filesystem's TrinoFileSystem — the engine
sees storage only through list / ranged-read / atomic-write / delete
plus one primitive object stores add over POSIX: compare-and-swap on a
small metadata pointer (S3 conditional PUT If-Match, GCS generation
preconditions), which is all a snapshot table format needs for ACID
commits (connectors/lakehouse.py).

The local-disk backend (:mod:`.objectstore`) keeps S3 semantics —
whole-object atomic writes, no partial visibility, transient
latency/error/throttle faults seeded through utils/faults.py and
absorbed by bounded-backoff retries — so every chaos scenario that runs
against it is reproducible from a spec + seed.
"""
from .filesystem import (  # noqa: F401
    FileEntry,
    ObjectStoreError,
    TransientObjectStoreError,
    TrinoFileSystem,
)
from .objectstore import LocalObjectStore  # noqa: F401
