"""The filesystem SPI: what the engine may assume about storage.

Reference parity: lib/trino-filesystem TrinoFileSystem /
TrinoInputFile / TrinoOutputFile, reduced to the five operations the
connectors actually use, plus compare-and-swap on a metadata pointer —
the one primitive that turns an eventually-listable object store into a
serializable commit log (Iceberg's commit protocol assumes exactly
this: atomic swap of the table-metadata pointer, everything else
immutable).

Contract every backend must honor:

- ``write_file`` is whole-object atomic: readers see the old bytes or
  the new bytes, never a torn prefix (S3 PUT semantics).
- ``read_file`` supports ranged reads (S3 GET Range) so footer-first
  formats stay one round trip per footer.
- ``compare_and_swap(path, expected, new)`` atomically replaces the
  object iff its current content equals ``expected`` (``None`` =
  "must not exist"); returns False on mismatch WITHOUT writing.
- transient errors (throttle, 5xx analogs) surface as
  :class:`TransientObjectStoreError` and are retried by the backend
  with bounded backoff; only exhaustion raises
  :class:`ObjectStoreError` to the caller.
"""
from __future__ import annotations

import dataclasses
from typing import List, Optional


@dataclasses.dataclass(frozen=True)
class FileEntry:
    """One listed object (TrinoFileSystem FileEntry analog)."""

    path: str
    size: int
    mtime_ns: int


class ObjectStoreError(IOError):
    """A storage operation failed for good (retries exhausted, missing
    object, permission)."""


class TransientObjectStoreError(ObjectStoreError):
    """A retryable failure (S3 500/503 analog) — raised internally by
    fault sites and absorbed by the retry loop; callers only see it
    wrapped in ObjectStoreError after the budget is spent."""


class TrinoFileSystem:
    """Abstract store: slash-separated keys, whole-object semantics."""

    def list_files(self, prefix: str = "") -> List[FileEntry]:
        """All objects under ``prefix``, sorted by path (S3 LIST)."""
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def read_file(
        self, path: str, offset: int = 0, length: Optional[int] = None
    ) -> bytes:
        """Ranged GET: ``length=None`` reads to the end."""
        raise NotImplementedError

    def write_file(self, path: str, data: bytes) -> None:
        """Atomic whole-object PUT (no partial visibility)."""
        raise NotImplementedError

    def delete_file(self, path: str) -> None:
        """DELETE; missing objects are not an error (S3 semantics)."""
        raise NotImplementedError

    def compare_and_swap(
        self, path: str, expected: Optional[bytes], new: bytes
    ) -> bool:
        """Atomically replace ``path`` iff its content is ``expected``
        (``None`` = the object must not exist).  True = swapped."""
        raise NotImplementedError
