"""Columnar data model: Column (Block analog) and Page.

Reference parity: core/trino-spi/src/main/java/io/trino/spi/Page.java:31 and
spi/block/Block.java:22 (sealed hierarchy ValueBlock / DictionaryBlock /
RunLengthEncodedBlock / LazyBlock).

TPU-first redesign: a Column is a fixed-dtype device array plus an optional
validity mask (SQL NULLs) and an optional host-side string dictionary.  All
device arrays are padded to static tile sizes before entering jit; the
``count`` field carries the true row count (rows beyond it are padding and
masked out of every kernel).  This replaces the reference's
position-count/SelectedPositions machinery with masks, which XLA fuses for
free, and replaces LazyBlock's deferred IO with deferred host->HBM upload
(numpy arrays stay on host until a kernel needs them; jnp.asarray is the
upload point).

Dictionary columns mirror DictionaryBlock.java:33: device array of int32
codes + a host-side numpy array of the distinct values.  Run-length columns
mirror RunLengthEncodedBlock.java:31 as (value, count) broadcast on demand.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence

import numpy as np

from . import types as T


@dataclasses.dataclass
class Column:
    """One column of a Page: values + optional validity + optional dictionary.

    values   : np.ndarray or jax.Array, shape (n,), dtype = type.np_dtype
    validity : None (all valid) or bool array shape (n,); True = non-null
    dictionary: for VarcharType columns, np.ndarray of distinct python strings
               (dtype=object or <U*); values are int32 indices into it.
               Code -1 is reserved for "not in dictionary" (never matches).
    """

    type: T.Type
    values: Any
    validity: Optional[Any] = None
    dictionary: Optional[np.ndarray] = None

    def __len__(self) -> int:
        return int(self.values.shape[0])

    @property
    def has_nulls(self) -> bool:
        return self.validity is not None

    def to_numpy(self) -> np.ndarray:
        return np.asarray(self.values)

    def to_python(self, count: Optional[int] = None) -> list:
        """Decode to python objects (strings/Decimals) for tests & client."""
        n = len(self) if count is None else count
        vals = np.asarray(self.values)[:n]
        valid = (
            np.ones(n, dtype=bool)
            if self.validity is None
            else np.asarray(self.validity)[:n]
        )
        out: list = []
        if getattr(self.type, "is_array", False):
            d = self.dictionary
            decode = _element_decoder(self.type.element)
            for v, ok in zip(vals, valid):
                if ok and int(v) >= 0:
                    out.append([decode(x) for x in d[int(v)]])
                else:
                    out.append(None)
        elif getattr(self.type, "is_map", False):
            d = self.dictionary
            dk = _element_decoder(self.type.key)
            dv = _element_decoder(self.type.value)
            for v, ok in zip(vals, valid):
                if ok and int(v) >= 0:
                    out.append({dk(k): dv(x) for k, x in d[int(v)]})
                else:
                    out.append(None)
        elif self.type.is_dictionary:
            d = self.dictionary
            for v, ok in zip(vals, valid):
                out.append(str(d[int(v)]) if (ok and int(v) >= 0) else None)
        elif self.type.is_decimal:
            scale = self.type.scale
            div = 10**scale
            if vals.ndim == 2:
                # wide (two-limb) decimal: exact python-int combine
                from decimal import Decimal

                lo = vals[:, 0].astype(np.uint64)
                hi = vals[:, 1].astype(np.int64)
                for l, h, ok in zip(lo, hi, valid):
                    if not ok:
                        out.append(None)
                        continue
                    u = (int(h) << 64) | int(l)
                    out.append(
                        Decimal(u).scaleb(-scale) if scale else u
                    )
                return out
            for v, ok in zip(vals, valid):
                if not ok:
                    out.append(None)
                else:
                    out.append(int(v) / div if scale else int(v))
        elif self.type.name == "date":
            epoch = np.datetime64("1970-01-01")
            for v, ok in zip(vals, valid):
                out.append(str(epoch + np.timedelta64(int(v), "D")) if ok else None)
        elif self.type.name == "boolean":
            for v, ok in zip(vals, valid):
                out.append(bool(v) if ok else None)
        elif self.type.name in ("double", "real"):
            for v, ok in zip(vals, valid):
                out.append(float(v) if ok else None)
        else:
            for v, ok in zip(vals, valid):
                out.append(int(v) if ok else None)
        return out


@dataclasses.dataclass
class Page:
    """A batch of rows as parallel columns (Page.java:31).

    count is the logical row count; column arrays may be longer (padding).
    ``names`` gives the output name of each column (symbol names in plans).
    """

    columns: list
    count: int
    names: Optional[list] = None

    def __post_init__(self):
        for c in self.columns:
            assert len(c) >= self.count, "column shorter than page count"

    @property
    def num_columns(self) -> int:
        return len(self.columns)

    @property
    def capacity(self) -> int:
        return int(self.columns[0].values.shape[0]) if self.columns else self.count

    def column(self, i: int) -> Column:
        return self.columns[i]

    def by_name(self, name: str) -> Column:
        return self.columns[self.names.index(name)]

    def to_pylist(self) -> list:
        """Rows as python tuples (decoded), for tests and the client."""
        cols = [c.to_python(self.count) for c in self.columns]
        return [tuple(vals) for vals in zip(*cols)] if cols else []


def _element_decoder(et: T.Type):
    """Array dictionary entries keep IR-constant conventions; decode to
    client python values (matching Column.to_python per-type rules)."""
    import numpy as _np

    if et.is_decimal and et.scale:
        div = 10 ** et.scale

        return lambda x: None if x is None else x / div
    if et.name == "date":
        epoch = _np.datetime64("1970-01-01")

        return lambda x: (
            None if x is None else str(epoch + _np.timedelta64(int(x), "D"))
        )
    return lambda x: x


def _element_encoder(et: T.Type):
    """Inverse of _element_decoder: client python values -> IR conventions."""
    import numpy as _np

    if et.is_decimal and et.scale:
        mul = 10 ** et.scale

        return lambda x: None if x is None else int(round(float(x) * mul))
    if et.name == "date":
        epoch = _np.datetime64("1970-01-01")

        return lambda x: (
            None
            if x is None
            else int((_np.datetime64(x, "D") - epoch).astype(int))
            if isinstance(x, str)
            else int(x)
        )
    return lambda x: x


def column_from_pylist(typ: T.Type, data: Sequence, dictionary=None) -> Column:
    """Build a Column from python values (None = NULL). Test helper."""
    if getattr(typ, "is_array", False):
        enc = _element_encoder(typ.element)
        data = [
            None if v is None else tuple(enc(x) for x in v) for v in data
        ]
    n = len(data)
    validity = None
    if any(v is None for v in data):
        validity = np.array([v is not None for v in data], dtype=bool)
    if typ.is_dictionary:
        if dictionary is None:
            seen: dict = {}
            for v in data:
                if v is not None and v not in seen:
                    seen[v] = len(seen)
            entries = list(seen.keys())
            # element-wise object array: np.array() would make equal-length
            # tuple entries (arrays) into a 2-D array
            dictionary = np.empty(len(entries), dtype=object)
            for _i, _v in enumerate(entries):
                dictionary[_i] = _v
        lookup = {v: i for i, v in enumerate(dictionary)}
        codes = np.array(
            [lookup.get(v, -1) if v is not None else -1 for v in data],
            dtype=np.int32,
        )
        return Column(typ, codes, validity, dictionary)
    if typ.is_decimal:
        scale = 10**typ.scale
        if getattr(typ, "wide", False):
            from decimal import ROUND_HALF_UP, Decimal

            from .ops.wide_decimal import from_python_int

            limbs = np.zeros((n, 2), dtype=np.int64)
            for i, v in enumerate(data):
                if v is None:
                    continue
                u = int(
                    (Decimal(str(v)) * scale).to_integral_value(
                        ROUND_HALF_UP
                    )
                )
                limbs[i, 0], limbs[i, 1] = from_python_int(u)
            return Column(typ, limbs, validity)
        from decimal import ROUND_HALF_UP, Decimal

        def enc(v):
            # ints stay exact (float64 would round >2^53, e.g. 18-digit
            # unscaled decimals); non-ints go through Decimal-of-str
            if isinstance(v, int):
                return v * scale
            return int(
                (Decimal(str(v)) * scale).to_integral_value(ROUND_HALF_UP)
            )

        vals = np.array(
            [0 if v is None else enc(v) for v in data], dtype=np.int64
        )
        return Column(typ, vals, validity)
    if typ.name == "date":
        epoch = np.datetime64("1970-01-01")
        vals = np.array(
            [
                0 if v is None else (np.datetime64(v, "D") - epoch).astype(int)
                for v in data
            ],
            dtype=np.int32,
        )
        return Column(typ, vals, validity)
    dt = typ.np_dtype
    vals = np.array([(0 if v is None else v) for v in data], dtype=dt)
    return Column(typ, vals, validity)


def page_from_pydict(schema: Sequence, data: dict) -> Page:
    """schema: list of (name, Type). data: name -> list of python values."""
    names = [n for n, _ in schema]
    cols = [column_from_pylist(t, data[n]) for n, t in schema]
    counts = {len(c) for c in cols}
    assert len(counts) == 1, "ragged columns"
    return Page(cols, counts.pop(), names)


def pad_to(arr: np.ndarray, capacity: int, fill=0) -> np.ndarray:
    """Pad an array to a static capacity along axis 0 (the tile-shape
    trick); trailing dims (wide-decimal limbs) are preserved."""
    n = arr.shape[0]
    if n == capacity:
        return arr
    assert n < capacity, (n, capacity)
    pad = np.full((capacity - n,) + arr.shape[1:], fill, dtype=arr.dtype)
    return np.concatenate([arr, pad])
