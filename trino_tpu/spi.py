"""Connector SPI — the plugin boundary.

Reference parity: core/trino-spi/src/main/java/io/trino/spi/connector/
(Plugin.java:36, ConnectorMetadata, ConnectorSplitManager,
ConnectorPageSourceProvider -> ConnectorPageSource.getNextPage:59).

The engine sees data sources only through these interfaces; connectors
(connectors/tpch.py, memory.py, blackhole.py) implement them.  Pages are
host-side numpy columns; upload to HBM happens at the operator boundary
(the LazyBlock analog — spi/block/LazyBlock.java:32).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from . import types as T
from .page import Page


@dataclasses.dataclass(frozen=True)
class ColumnSchema:
    name: str
    type: T.Type


@dataclasses.dataclass(frozen=True)
class TableSchema:
    name: str
    columns: Tuple[ColumnSchema, ...]

    def column_names(self) -> List[str]:
        return [c.name for c in self.columns]

    def column_type(self, name: str) -> T.Type:
        for c in self.columns:
            if c.name == name:
                return c.type
        raise KeyError(name)


@dataclasses.dataclass(frozen=True)
class ColumnStatistics:
    """Per-column stats for the CBO (spi/statistics/ColumnStatistics).

    ``histogram`` is an optional equi-height histogram — a tuple of
    ``(low, high, fraction)`` buckets over the non-null rows (plain
    tuples: hashable and JSON-round-trippable for persistence) —
    produced by ANALYZE from the device-sort quantiles."""

    distinct_count: Optional[float] = None
    null_fraction: float = 0.0
    min_value: Optional[float] = None
    max_value: Optional[float] = None
    histogram: Optional[Tuple[Tuple[float, float, float], ...]] = None


@dataclasses.dataclass(frozen=True)
class TableStatistics:
    """Reference: spi/statistics/TableStatistics via
    ConnectorMetadata.getTableStatistics (TpchMetadata supplies these
    for the reference's CBO)."""

    row_count: float
    columns: Dict[str, ColumnStatistics] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass(frozen=True)
class Split:
    """A unit of parallel scan work (spi/connector/ConnectorSplit)."""

    table: str
    ordinal: int
    total: int
    info: dict = dataclasses.field(default_factory=dict)


class ConnectorMetadata:
    def list_tables(self) -> List[str]:
        raise NotImplementedError

    def get_table_schema(self, table: str) -> TableSchema:
        raise NotImplementedError

    def get_table_statistics(self, table: str) -> TableStatistics:
        raise NotImplementedError

    def store_table_statistics(
        self, table: str, stats: TableStatistics, data_version: int
    ) -> None:
        """Persist ANALYZE results keyed by the table's data_version
        (ConnectorMetadata.finishStatisticsCollection analog).  A later
        get_table_statistics MUST NOT serve these once data_version has
        moved on — DML invalidates stats exactly like it invalidates the
        result cache.  Connectors without durable storage may leave this
        unimplemented; the engine keeps a session-side overlay instead."""
        raise NotImplementedError(
            f"{type(self).__name__} does not store statistics"
        )

    # -- writes (ConnectorMetadata.beginCreateTable/beginInsert/...; a
    # connector that leaves these unimplemented is read-only) ----------
    def create_table(self, schema: TableSchema) -> None:
        raise NotImplementedError(f"{type(self).__name__} is read-only")

    def drop_table(self, table: str) -> None:
        raise NotImplementedError(f"{type(self).__name__} is read-only")


class SplitManager:
    def get_splits(
        self, table: str, desired: int, constraint=None
    ) -> List[Split]:
        """constraint: optional per-column domains — entries are
        (column, lo, hi) inclusive ranges OR (column, lo, hi, values)
        where `values` is a sorted tuple of exactly-admissible values
        (discrete ValueSet / IN-list pushdown); unpack defensively
        (TupleDomain pushdown) — connectors MAY prune splits with it."""
        raise NotImplementedError


class PageSource:
    """Streaming page iterator (ConnectorPageSource.getNextPage)."""

    def pages(self) -> Iterator[Page]:
        raise NotImplementedError

    def dictionaries(self) -> Dict[str, np.ndarray]:
        """Host dictionaries for varchar columns produced by this source."""
        return {}


class PageSourceProvider:
    def create_page_source(
        self, split: Split, columns: Sequence[str]
    ) -> PageSource:
        raise NotImplementedError


class PageSink:
    """Write-side mirror of PageSource (spi/connector/ConnectorPageSink:
    appendPage/finish).  One sink per write operation; finish() commits
    and returns the row count."""

    def append(self, page: Page) -> None:
        raise NotImplementedError

    def finish(self) -> int:
        raise NotImplementedError


class PageSinkProvider:
    """spi/connector/ConnectorPageSinkProvider."""

    def create_sink(self, table: str, columns: Sequence[str],
                    overwrite: bool = False) -> PageSink:
        """overwrite=True replaces the table contents atomically at
        finish() — the rewrite slot used by DELETE (the reference routes
        row-level deletes through MergeWriterNode; here the engine computes
        the kept rows and rewrites)."""
        raise NotImplementedError


class Connector:
    """One mounted catalog (spi/connector/Connector)."""

    name: str
    # deterministic sources (generators, immutable tables) may be cached
    # across queries; mutable/live sources must set this False or bump
    # data_version() on every change
    cacheable: bool = True

    def data_version(self, table: Optional[str] = None) -> int:
        """Per-table data-version fingerprint: the cache-invalidation SPI.

        Every cache tier keys on this value — the device scan cache, the
        compiled-fragment cache and the fragment result cache all embed
        (catalog, table, data_version(table)) in their keys, so a version
        bump makes stale entries unaddressable without any explicit
        invalidation protocol.  Contract:

        - MUST change whenever the visible contents of ``table`` change
          (INSERT/DELETE/overwrite, external file mutation, ...).
        - SHOULD be scoped to ``table`` (an INSERT into A must not churn
          cached results scanning B); ``table=None`` asks for a whole-
          catalog version (any-table-changed counter).
        - MUST be stable within a process for unchanged data, and SHOULD
          be stable ACROSS processes (derive from content/mtimes, not
          from salted ``hash()``) so persistent compile-cache keys built
          from it survive restarts.

        The default (constant 0) is correct for immutable sources
        (generators, static files); mutable connectors either bump a
        counter per write (connectors/memory) or fingerprint the backing
        storage (connectors/hive walks the table directory's mtimes).
        Sources that cannot honor the contract must set ``cacheable``
        False instead."""
        return 0

    def session_property_metadata(self) -> dict:
        """Per-catalog session properties this connector understands
        (spi/session PropertyMetadata via Connector
        .getSessionProperties): name -> config.PropertyMetadata.
        SET SESSION <catalog>.<name> = value routes here."""
        return {}

    def set_session_property(self, name: str, value) -> None:
        """Apply a validated per-catalog session property (the
        ConnectorSession property bag; sessions own their
        CatalogManager, so connector instances are session-scoped)."""
        meta = self.session_property_metadata().get(name)
        if meta is not None and meta.parse is int and int(value) <= 0:
            raise ValueError(
                f"catalog session property {name} must be positive"
            )
        if not hasattr(self, "session_props"):
            self.session_props = {}
        self.session_props[name] = value

    def get_session_property(self, name: str):
        """Current value of a per-catalog session property, falling
        back to its declared metadata default — the single read path
        (no duplicated defaults at call sites)."""
        props = getattr(self, "session_props", {})
        if name in props:
            return props[name]
        meta = self.session_property_metadata().get(name)
        return meta.default if meta is not None else None

    def table_functions(self) -> dict:
        """Connector-provided polymorphic table functions
        (spi/function/table ConnectorTableFunction seam): name ->
        callable(*scalar_args) returning (schema, rows) where schema
        is [(column, Type), ...]."""
        return {}

    def metadata(self) -> ConnectorMetadata:
        raise NotImplementedError

    def split_manager(self) -> SplitManager:
        raise NotImplementedError

    def page_source_provider(self) -> PageSourceProvider:
        raise NotImplementedError

    def page_sink_provider(self) -> PageSinkProvider:
        raise NotImplementedError(f"connector {self.name} is read-only")


class Plugin:
    """Reference: spi/Plugin.java:36 — a factory of connector factories."""

    def connector_factories(self) -> Dict[str, "ConnectorFactory"]:
        return {}


class ConnectorFactory:
    name: str

    def create(self, catalog_name: str, config: dict) -> Connector:
        raise NotImplementedError
