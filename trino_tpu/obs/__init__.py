"""Observability subsystem: black-box flight recorder + HBM bandwidth ledger.

Two always-on production-profiling surfaces in the spirit of Kanev et al.
(*Profiling a Warehouse-Scale Computer*, ISCA 2015) and Dean & Barroso
(*The Tail at Scale*, CACM 2013):

- :mod:`.flight_recorder` — a per-process bounded ring of dispatch
  records (in-memory always; crash-safe mmap'd JSONL segments on disk
  when ``flight_recorder_dir`` is set) so the last N device dispatches
  survive a hard TPU crash and ``scripts/flightrec.py`` can bisect the
  culprit kernel offline;
- :mod:`.bandwidth` — per-kernel bytes-touched / device-wall accounting
  yielding effective GB/s and %-of-roofline per compiled program
  (``bandwidth_ledger`` session property), surfaced through EXPLAIN
  ANALYZE, ``/v1/query/{id}/profile``, ``system.runtime.kernel_bandwidth``
  and the ``trino_tpu_kernel_bandwidth_*`` histograms;
- :mod:`.opstats` — per-operator OperatorStats frames (rows/bytes/wall/
  blocked-time, estimated vs observed rows) rolled up pipeline -> task ->
  stage -> query into the EXPLAIN ANALYZE / ``system.runtime.operator_stats``
  timeline, plus the live wall-dispersion straggler detector feeding FTE
  hedging (``trino_tpu_straggler_*`` metrics);
- :mod:`.history` — crash-safe byte-bounded persisted query history
  (``query_history_dir``), same torn-tail-tolerant mmap'd JSONL shape as
  the flight recorder, backing ``system.runtime.completed_queries``;
- :mod:`.journal` — the engine-wide incident journal: every subsystem
  that bumps an anomaly metric also appends a typed query/task/node-
  correlated event (``event_journal_dir`` upgrades it to the crash-safe
  on-disk segments), backing ``system.runtime.events``;
- :mod:`.doctor` — the query doctor: deterministic ordered-rule
  correlation of the journal with the flight recorder, bandwidth
  ledger, timeline, and history into a ranked causal verdict (EXPLAIN
  ANALYZE "Diagnosis", ``system.runtime.diagnoses``,
  ``scripts/doctor.py``).
"""
from .bandwidth import BandwidthLedger, roofline_bytes_per_s
from .doctor import (
    DIAGNOSIS_FIELDS,
    classify_error,
    diagnose,
    diagnose_from_dir,
    diagnose_query,
    format_diagnosis,
    recent_diagnoses,
    record_diagnosis,
)
from .journal import (
    EVENT_FIELDS,
    EventJournal,
    get_journal,
    read_journal_dir,
)
from .flight_recorder import (
    RECORD_FIELDS,
    FlightRecorder,
    last_recorder,
    last_unmatched,
    read_dir,
)
from .history import (
    HISTORY_FIELDS,
    QueryHistoryStore,
    get_store,
    read_history_dir,
)
from .opstats import (
    OPERATOR_FIELDS,
    StragglerDetector,
    format_timeline,
    frames_from_plan,
    merge_frames,
    task_rollup,
    timeline_from_tasks,
)

__all__ = [
    "BandwidthLedger",
    "roofline_bytes_per_s",
    "DIAGNOSIS_FIELDS",
    "classify_error",
    "diagnose",
    "diagnose_from_dir",
    "diagnose_query",
    "format_diagnosis",
    "recent_diagnoses",
    "record_diagnosis",
    "EVENT_FIELDS",
    "EventJournal",
    "get_journal",
    "read_journal_dir",
    "RECORD_FIELDS",
    "FlightRecorder",
    "last_recorder",
    "last_unmatched",
    "read_dir",
    "HISTORY_FIELDS",
    "QueryHistoryStore",
    "get_store",
    "read_history_dir",
    "OPERATOR_FIELDS",
    "StragglerDetector",
    "format_timeline",
    "frames_from_plan",
    "merge_frames",
    "task_rollup",
    "timeline_from_tasks",
]
