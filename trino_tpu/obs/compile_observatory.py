"""Compile observatory: engine-wide, cross-query trace/compile ledger.

ROADMAP item 3 (compile-once fragment ABI, zero recompiles at p99 under
concurrent load) needs two inputs nobody records today: *why* each XLA
compile happened, and *which shapes* real traffic presents.  Recompiles
are exactly the rare many-millisecond events that dominate p99 under
load (Dean & Barroso, *The Tail at Scale*, CACM 2013), and choosing
padding buckets from an observed row-count distribution is the
equi-height-histogram problem (Ioannidis, *The History of Histograms*,
VLDB 2003) applied to cardinalities instead of values.

Every compile choke point — the in-memory/persistent compile-cache
tiers, exec/local's ``xla_compile`` path, the eager trace ladder, and
the mesh executor — reports here with a structured *cause*:

- ``first_compile``   — the kernel family had never been compiled
- ``ladder_rung``     — a capacity-overflow retry re-traced (attempt > 0)
- ``shape_miss``      — the family was warm but this shape signature
  was not: the retrace the zero-retrace gate hunts
- ``poisoned_recovery`` — recompile after ``evict_poisoned``
- ``persistent_load`` — re-trace whose XLA compile was served by the
  on-disk persistent tier (cheap, but still a trace)

Alongside the ledger a **shape census** accumulates per-kernel-family
row-count distributions as a bounded power-of-two sketch — mergeable
across workers via the announcement piggyback (the opstats pattern) —
and :func:`recommend_ladder` turns a census into a geometric padding
ladder with a predicted waste ratio (``scripts/bucket_ladder.py`` is the
CLI).  Storage is the mmap'd torn-tail-tolerant two-segment JSONL shape
the flight recorder proved out: memory-only by default (a bounded
mirror backs ``system.runtime.compiles``), crash-safe on-disk when
``compile_observatory_dir`` is set.  A sliding-window shape-miss rate
above threshold emits a RETRACE_STORM incident-journal event feeding
the query doctor.
"""
from __future__ import annotations

import glob
import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from .journal import (  # the proven segment shape; one implementation
    MAX_RECORD_BYTES,
    MIN_SEGMENT_BYTES,
    _Segment,
)
from ..exec.shapes import lane_align as _lane_align

# lowerCamelCase wire schema, linted by scripts/check_metric_names.py
COMPILE_FIELDS = (
    "compileId",
    "kernel",
    "family",
    "cause",
    "mode",
    "shapes",
    "actualRows",
    "paddedRows",
    "compileWallS",
    "queryId",
    "taskId",
    "nodeId",
    "ts",
)

CENSUS_FIELDS = (
    "family",
    "bucket",
    "count",
    "minRows",
    "maxRows",
    "totalRows",
)

# -- the cause taxonomy (classification precedence is top to bottom) ----
POISONED_RECOVERY = "poisoned_recovery"
LADDER_RUNG = "ladder_rung"
PERSISTENT_LOAD = "persistent_load"
SHAPE_MISS = "shape_miss"
FIRST_COMPILE = "first_compile"
CAUSES = (
    FIRST_COMPILE,
    LADDER_RUNG,
    SHAPE_MISS,
    POISONED_RECOVERY,
    PERSISTENT_LOAD,
)

DEFAULT_MAX_BYTES = 1 << 20
DEFAULT_MAX_FAMILIES = 64
# census snapshots flush every N observations (plus on sync())
_CENSUS_FLUSH_EVERY = 32
# shape-miss storm: >= STORM_MISSES shape_miss compiles inside
# STORM_WINDOW_S seconds emits one RETRACE_STORM journal event per window
STORM_WINDOW_S = 10.0
STORM_MISSES = 8
# a family is warm (so an unseen shape is a retrace, not a first
# compile) only once it has been known this long: a cold family's
# task partitions and concurrently-started sibling queries present
# their per-partition shapes within moments of the introduction
FAMILY_COLD_S = 5.0

_FILE_PREFIX = "co-"
_CENSUS_PREFIX = "census-"

_ID_LOCK = threading.Lock()
_NEXT_ID = 0


def _new_compile_id() -> int:
    global _NEXT_ID
    with _ID_LOCK:
        _NEXT_ID += 1
        return _NEXT_ID


def _pow2_bucket(rows: int) -> int:
    """The padding bucket a row count falls in: next power of two >= rows
    (floor 128, the TPU lane width — matching exec/shapes.lane_align's
    floor so census buckets and real padded shapes stay comparable).
    These are exactly the geometric PaddingLadder's rungs, so census
    sketches double as ladder-occupancy histograms."""
    rows = max(int(rows), 1)
    b = 128
    while b < rows:
        b <<= 1
    return b


class ShapeCensus:
    """Bounded per-kernel-family row-count sketch.

    Each family keeps a power-of-two histogram of observed row counts
    plus min/max/total — O(log max_rows) buckets per family, merged by
    summing counts, so worker sketches piggyback on announcements and
    the coordinator's union is exact.  Family overflow beyond
    ``max_families`` folds into ``__other__`` (never dropped: the waste
    predictor must see total mass)."""

    OTHER = "__other__"

    def __init__(self, max_families: int = DEFAULT_MAX_FAMILIES):
        self.max_families = max(int(max_families or DEFAULT_MAX_FAMILIES), 1)
        self.families: Dict[str, dict] = {}

    def observe(self, family: str, rows: int) -> None:
        family = str(family or "unknown")
        rows = max(int(rows), 0)
        fam = self.families.get(family)
        if fam is None:
            if len(self.families) >= self.max_families:
                family = self.OTHER
                fam = self.families.get(family)
            if fam is None:
                fam = self.families[family] = {
                    "count": 0,
                    "minRows": rows,
                    "maxRows": rows,
                    "totalRows": 0,
                    "buckets": {},
                }
        fam["count"] += 1
        fam["minRows"] = min(fam["minRows"], rows)
        fam["maxRows"] = max(fam["maxRows"], rows)
        fam["totalRows"] += rows
        b = str(_pow2_bucket(rows))
        fam["buckets"][b] = fam["buckets"].get(b, 0) + 1

    def merge(self, snapshot: Optional[dict]) -> None:
        """Union another census snapshot (a worker's piggyback) in."""
        for family, other in ((snapshot or {}).get("families") or {}).items():
            if not isinstance(other, dict):
                continue
            fam = self.families.get(family)
            if fam is None:
                if len(self.families) >= self.max_families:
                    family = self.OTHER
                fam = self.families.setdefault(family, {
                    "count": 0,
                    "minRows": int(other.get("minRows", 0)),
                    "maxRows": int(other.get("maxRows", 0)),
                    "totalRows": 0,
                    "buckets": {},
                })
            fam["count"] += int(other.get("count", 0))
            fam["minRows"] = min(fam["minRows"], int(other.get("minRows", 0)))
            fam["maxRows"] = max(fam["maxRows"], int(other.get("maxRows", 0)))
            fam["totalRows"] += int(other.get("totalRows", 0))
            for b, c in (other.get("buckets") or {}).items():
                fam["buckets"][str(b)] = fam["buckets"].get(str(b), 0) + int(c)

    def snapshot(self) -> dict:
        return {"families": {
            f: {
                "count": fam["count"],
                "minRows": fam["minRows"],
                "maxRows": fam["maxRows"],
                "totalRows": fam["totalRows"],
                "buckets": dict(fam["buckets"]),
            }
            for f, fam in self.families.items()
        }}

    def rows(self) -> List[dict]:
        """Flat (family, bucket) rows in the CENSUS_FIELDS wire shape
        (``system.runtime.shape_census``)."""
        out: List[dict] = []
        for family in sorted(self.families):
            fam = self.families[family]
            for b in sorted(fam["buckets"], key=int):
                out.append({
                    "family": family,
                    "bucket": int(b),
                    "count": int(fam["buckets"][b]),
                    "minRows": int(fam["minRows"]),
                    "maxRows": int(fam["maxRows"]),
                    "totalRows": int(fam["totalRows"]),
                })
        return out

    def top_families(self, n: int = 5) -> List[dict]:
        fams = sorted(
            self.families.items(),
            key=lambda kv: kv[1]["count"],
            reverse=True,
        )
        return [
            {"family": f, "count": fam["count"],
             "minRows": fam["minRows"], "maxRows": fam["maxRows"]}
            for f, fam in fams[:n]
        ]


class CompileObservatory:
    """Process-global cross-query ledger of every trace/compile.

    In-memory mirror (bounded deque) + optional mmap'd torn-tail-
    tolerant on-disk segments (``compile_observatory_dir``), the same
    crash-safety contract as the flight recorder and incident journal.
    Segment names carry the writing pid so concurrent processes sharing
    a directory never clobber each other; the census persists as an
    atomically-replaced per-writer JSON snapshot that an offline reader
    (``read_census_dir``) merges across writers."""

    def __init__(
        self,
        directory: Optional[str] = None,
        max_bytes: int = DEFAULT_MAX_BYTES,
        name: Optional[str] = None,
        max_events: int = 4096,
        census_max_families: int = DEFAULT_MAX_FAMILIES,
        storm_window_s: float = STORM_WINDOW_S,
        storm_misses: int = STORM_MISSES,
        family_cold_s: float = FAMILY_COLD_S,
    ):
        self.directory = str(directory or "").strip() or None
        self.max_bytes = max(int(max_bytes or DEFAULT_MAX_BYTES),
                             2 * MIN_SEGMENT_BYTES)
        self.name = name or str(os.getpid())
        self._lock = threading.Lock()
        self.mirror: deque = deque(maxlen=max_events)
        self.census = ShapeCensus(census_max_families)
        self.counts: Dict[str, int] = {c: 0 for c in CAUSES}
        self.compile_wall_s = 0.0
        # family digest -> shape signatures (kernel digests) seen; the
        # classifier's warm/cold memory.  Bounded like the census.
        self._families: Dict[str, set] = {}
        # family digest -> (introducing query, first-seen ts): every
        # partition of that query — and any compile inside the family's
        # cold window — is part of the first execution, so its shapes
        # are first compiles, not retraces
        self._family_intro: Dict[str, tuple] = {}
        self._family_cold_s = float(family_cold_s)
        self._storm_window_s = float(storm_window_s)
        self._storm_misses = max(int(storm_misses), 1)
        self._miss_times: deque = deque()
        self._storm_last_emit = 0.0
        self._census_dirty = 0
        # announcement cursor: local events not yet piggybacked
        self._announced_through = 0
        self._segments: List[_Segment] = []
        self._active = 0
        if self.directory:
            os.makedirs(self.directory, exist_ok=True)
            seg_bytes = max(MIN_SEGMENT_BYTES, self.max_bytes // 2)
            for i in range(2):
                path = os.path.join(
                    self.directory,
                    f"{_FILE_PREFIX}{self.name}-{i}.jsonl",
                )
                seg = _Segment(path, seg_bytes)
                seg.reset()  # a reused path must not replay stale events
                self._segments.append(seg)

    # -- classification -------------------------------------------------
    def classify(
        self,
        family: str,
        shape_sig: str,
        ladder_attempt: int = 0,
        poisoned: bool = False,
        persistent: bool = False,
        query_id: str = "",
    ) -> str:
        """Structured cause for one compile, in precedence order:
        poisoned recovery beats ladder rung beats persistent load beats
        the warm/cold family distinction (shape_miss vs first_compile).
        A family is only warm against queries that arrive after its
        cold window: the query that introduced it — and siblings that
        started alongside it — present their per-partition shapes
        moments later, and those are first compiles, not retraces."""
        if poisoned:
            return POISONED_RECOVERY
        if int(ladder_attempt or 0) > 0:
            return LADDER_RUNG
        if persistent:
            return PERSISTENT_LOAD
        with self._lock:
            seen = self._families.get(str(family))
            intro = self._family_intro.get(str(family))
        if seen is None or str(shape_sig) in seen:
            return FIRST_COMPILE
        if intro is not None:
            intro_query, intro_ts = intro
            if query_id and intro_query == str(query_id):
                return FIRST_COMPILE
            if time.time() - intro_ts < self._family_cold_s:
                return FIRST_COMPILE
        return SHAPE_MISS

    def _register(
        self, family: str, shape_sig: str, query_id: str = ""
    ) -> None:
        with self._lock:
            seen = self._families.get(str(family))
            if seen is None:
                if len(self._families) >= 4 * self.census.max_families:
                    return  # bounded: stop learning, never grow unbounded
                seen = self._families[str(family)] = set()
                self._family_intro[str(family)] = (
                    str(query_id or ""), time.time(),
                )
            if len(seen) < 256:
                seen.add(str(shape_sig))

    def seed_family(self, family: str, shape_sig: str) -> None:
        """Boot-time prewarm hook (CompileCache.prewarm): register a
        family/shape pair from the persistent-tier index WITHOUT a
        compile event.  Seeded families get the normal cold-window
        grace, so the first post-restart traffic that re-traces indexed
        programs classifies persistent_load / first_compile — a cold
        boot must never look like a retrace storm."""
        self._register(str(family), str(shape_sig), query_id="__prewarm__")

    # -- record ---------------------------------------------------------
    def record(
        self,
        kernel: str,
        family: str,
        cause: Optional[str] = None,
        mode: str = "jit",
        shapes: Optional[dict] = None,
        actual_rows: int = 0,
        padded_rows: int = 0,
        compile_wall_s: float = 0.0,
        query_id: str = "",
        task_id: str = "",
        node_id: str = "",
        ladder_attempt: int = 0,
        poisoned: bool = False,
        persistent: bool = False,
        scan_rows: Optional[List[int]] = None,
        shape_sig: Optional[str] = None,
    ) -> dict:
        """Append one compile event; returns the ledger record.  When
        ``cause`` is omitted it is classified from the flags and the
        family's warm/cold state.  ``shape_sig`` is the signature the
        warm/cold classifier keys on (defaults to the kernel digest,
        which embeds the padded buckets on the jit path); ``scan_rows``
        (per-scan actual row counts) feeds the shape census."""
        kernel = str(kernel)
        family = str(family or kernel)
        sig = str(shape_sig or kernel)
        if cause is None:
            cause = self.classify(
                family, sig, ladder_attempt=ladder_attempt,
                poisoned=poisoned, persistent=persistent,
                query_id=str(query_id or ""),
            )
        self._register(family, sig, query_id=str(query_id or ""))
        event = {
            "compileId": _new_compile_id(),
            "kernel": kernel,
            "family": family,
            "cause": cause,
            "mode": str(mode),
            "shapes": dict(shapes or {}),
            "actualRows": int(actual_rows),
            "paddedRows": int(padded_rows),
            "compileWallS": float(compile_wall_s),
            "queryId": str(query_id or ""),
            "taskId": str(task_id or ""),
            "nodeId": str(node_id or ""),
            "ts": time.time(),
        }
        for rows in (scan_rows if scan_rows is not None
                     else [actual_rows]):
            self.census.observe(family, rows)
        self._append(event)
        self._metrics(event)
        if cause == SHAPE_MISS:
            self._note_shape_miss(event)
        with self._lock:
            self._census_dirty += 1
            dirty = self._census_dirty
        if dirty >= _CENSUS_FLUSH_EVERY:
            self._flush_census()
        return event

    def _append(self, event: dict) -> None:
        data = json.dumps(event, separators=(",", ":"),
                          default=str).encode() + b"\n"
        if len(data) > MAX_RECORD_BYTES:
            event = dict(event, shapes={"truncated": True})
            data = json.dumps(event, separators=(",", ":"),
                              default=str).encode() + b"\n"
        with self._lock:
            self.mirror.append(event)
            self.counts[event["cause"]] = (
                self.counts.get(event["cause"], 0) + 1
            )
            self.compile_wall_s += float(event.get("compileWallS") or 0.0)
            if not self._segments:
                return
            seg = self._segments[self._active]
            if not seg.append(data):
                self._active = 1 - self._active
                seg = self._segments[self._active]
                seg.reset()
                seg.append(data)

    def _metrics(self, event: dict) -> None:
        from ..utils.metrics import REGISTRY

        REGISTRY.counter(
            "trino_tpu_compile_events_total",
            "Trace/compile events observed engine-wide, by cause",
        ).inc(cause=event["cause"], mode=event["mode"])
        REGISTRY.histogram(
            "trino_tpu_compile_wall_seconds",
            "Per-event compile (or trace) wall time from the observatory",
        ).observe(float(event.get("compileWallS") or 0.0))
        REGISTRY.gauge(
            "trino_tpu_compile_census_families_state",
            "Distinct kernel families in the shape census",
        ).set(len(self.census.families))

    def _note_shape_miss(self, event: dict) -> None:
        """Sliding-window storm detector: a burst of shape-miss retraces
        is a p99 incident (each one is many milliseconds of compile on
        the query path), so it lands in the incident journal where the
        doctor can cite it."""
        now = float(event.get("ts") or time.time())
        with self._lock:
            self._miss_times.append(now)
            while (self._miss_times
                   and now - self._miss_times[0] > self._storm_window_s):
                self._miss_times.popleft()
            n = len(self._miss_times)
            fire = (
                n >= self._storm_misses
                and now - self._storm_last_emit > self._storm_window_s
            )
            if fire:
                self._storm_last_emit = now
        if fire:
            from . import journal

            journal.emit(
                journal.RETRACE_STORM,
                query_id=event.get("queryId", ""),
                task_id=event.get("taskId", ""),
                node_id=event.get("nodeId", ""),
                severity=journal.WARN,
                misses=n,
                windowS=self._storm_window_s,
                family=event.get("family", ""),
                kernel=event.get("kernel", ""),
            )

    # -- cross-worker merge (announcement piggyback) --------------------
    def announce_snapshot(self, max_events: int = 256) -> dict:
        """The worker-side piggyback: per-cause counts, census sketch,
        and the ledger events appended since the last announcement
        (bounded; the counts stay exact even when events are elided)."""
        with self._lock:
            events = [
                e for e in self.mirror
                if e["compileId"] > self._announced_through
            ]
            if events:
                self._announced_through = events[-1]["compileId"]
            events = events[-max_events:]
            counts = dict(self.counts)
            wall = self.compile_wall_s
        return {
            "pid": os.getpid(),
            "counts": counts,
            "compileWallS": wall,
            "census": self.census.snapshot(),
            "events": events,
        }

    def ingest(self, node_id: str, snapshot: Optional[dict]) -> None:
        """Coordinator-side union of one worker's piggyback.  Counts and
        census are cumulative per worker, so they replace (keyed by
        node) and merge at read time; events append.  A same-pid
        announcement is this process's own ledger coming back around
        (in-process cluster: testing/runner.py) — ingesting it would
        double every count and compound the census, so it is a no-op."""
        if not isinstance(snapshot, dict):
            return
        try:
            if int(snapshot.get("pid") or -1) == os.getpid():
                return
        except (TypeError, ValueError):
            pass
        remote = getattr(self, "_remote", None)
        if remote is None:
            remote = self._remote = {}
        with self._lock:
            prev = remote.get(str(node_id)) or {}
            seen = set(prev.get("seen") or ())
            entry = {
                "counts": {
                    c: int((snapshot.get("counts") or {}).get(c, 0))
                    for c in CAUSES
                },
                "compileWallS": float(snapshot.get("compileWallS") or 0.0),
                "census": snapshot.get("census") or {},
                "seen": seen,
            }
            remote[str(node_id)] = entry
        for e in snapshot.get("events") or []:
            if not isinstance(e, dict) or "cause" not in e:
                continue
            eid = (str(node_id), e.get("compileId"))
            if eid in seen:
                continue
            seen.add(eid)
            e = dict(e, nodeId=e.get("nodeId") or str(node_id))
            with self._lock:
                self.mirror.append(e)

    # -- read / rollup --------------------------------------------------
    def tail(self, n: Optional[int] = None) -> List[dict]:
        with self._lock:
            events = list(self.mirror)
        return events[-n:] if n else events

    def counts_by_cause(self) -> Dict[str, int]:
        """Engine-wide per-cause totals: local counts plus every
        ingested worker's latest cumulative piggyback."""
        with self._lock:
            totals = dict(self.counts)
            for entry in (getattr(self, "_remote", None) or {}).values():
                for c, v in (entry.get("counts") or {}).items():
                    totals[c] = totals.get(c, 0) + int(v)
        return totals

    def total_compile_wall_s(self) -> float:
        with self._lock:
            wall = self.compile_wall_s
            for entry in (getattr(self, "_remote", None) or {}).values():
                wall += float(entry.get("compileWallS") or 0.0)
        return wall

    def node_family_map(
        self, local_node_id: str = "local"
    ) -> Dict[str, set]:
        """Which kernel-family digests each node holds warm compiles
        for: the local process's census plus every ingested worker's
        latest cumulative piggyback.  The serving observatory joins
        this against per-signature family digests to build
        ``system.runtime.signature_affinity``.  In an in-process
        cluster the workers share this very observatory, so all warmth
        appears under ``local_node_id`` — subprocess/remote workers
        each get their own row via announcements."""
        out: Dict[str, set] = {}
        with self._lock:
            local = set(self.census.families) | set(self._families)
            if local:
                out[str(local_node_id or "local")] = local
            for node_id, entry in (
                getattr(self, "_remote", None) or {}
            ).items():
                fams = set(
                    ((entry.get("census") or {}).get("families") or {})
                )
                if fams:
                    out[str(node_id)] = fams
        return out

    def merged_census(self) -> ShapeCensus:
        """Engine-wide census view: the local sketch plus each ingested
        worker's latest cumulative snapshot (snapshots replace per node,
        so re-announcement never compounds counts)."""
        merged = ShapeCensus(self.census.max_families)
        merged.merge(self.census.snapshot())
        with self._lock:
            remotes = [
                dict(entry.get("census") or {})
                for entry in (getattr(self, "_remote", None) or {}).values()
            ]
        for snap in remotes:
            merged.merge(snap)
        return merged

    def rollup(self, top: int = 5) -> dict:
        """The bench/profile attachment: per-cause counts, total compile
        wall, and the census's busiest families."""
        counts = self.counts_by_cause()
        census = self.merged_census()
        return {
            "byCause": counts,
            "compiles": sum(counts.values()),
            "compileWallS": self.total_compile_wall_s(),
            "censusFamilies": len(census.families),
            "topFamilies": census.top_families(top),
        }

    # -- durability -----------------------------------------------------
    def _census_path(self) -> Optional[str]:
        if not self.directory:
            return None
        return os.path.join(
            self.directory, f"{_CENSUS_PREFIX}{self.name}.json"
        )

    def _flush_census(self) -> None:
        path = self._census_path()
        with self._lock:
            self._census_dirty = 0
            if path is None:
                return
            snap = self.census.snapshot()
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(snap, f)
            os.replace(tmp, path)
        except OSError:
            pass

    def sync(self) -> None:
        """Flush segments + census snapshot (drain/shutdown barrier)."""
        self._flush_census()
        with self._lock:
            for seg in self._segments:
                seg.sync()

    def close(self) -> None:
        self._flush_census()
        with self._lock:
            for seg in self._segments:
                seg.close()
            self._segments = []


# -- the process-global observatory (hook sites have no session ref) ----

_GLOBAL_LOCK = threading.Lock()
_GLOBAL: Optional[CompileObservatory] = None


def get_observatory() -> CompileObservatory:
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = CompileObservatory(None)
        return _GLOBAL


def configure(
    directory,
    max_bytes=None,
    census_max_families=None,
) -> CompileObservatory:
    """Upgrade/re-point the global observatory
    (``compile_observatory_dir`` / ``compile_census_max_families``).
    The memory mirror and census carry over so compiles that fired
    before the owning session finished constructing are not lost."""
    global _GLOBAL
    directory = str(directory or "").strip() or None
    try:
        max_bytes = int(max_bytes or 0) or DEFAULT_MAX_BYTES
    except (TypeError, ValueError):
        max_bytes = DEFAULT_MAX_BYTES
    try:
        fams = int(census_max_families or 0) or DEFAULT_MAX_FAMILIES
    except (TypeError, ValueError):
        fams = DEFAULT_MAX_FAMILIES
    with _GLOBAL_LOCK:
        cur = _GLOBAL
        if (
            cur is not None
            and cur.directory == directory
            and cur.census.max_families == fams
            and (directory is None or cur.max_bytes == max_bytes)
        ):
            return cur
        nxt = CompileObservatory(
            directory, max_bytes=max_bytes, census_max_families=fams
        )
        if cur is not None:
            for event in cur.tail():
                nxt._append(event)
            nxt.census.merge(cur.census.snapshot())
            nxt._families = cur._families
            nxt._family_intro = getattr(cur, "_family_intro", None) or {}
            nxt._family_cold_s = getattr(
                cur, "_family_cold_s", FAMILY_COLD_S
            )
            nxt._remote = getattr(cur, "_remote", None) or {}
            cur.close()
        _GLOBAL = nxt
        return nxt


def record_compile(**kwargs) -> dict:
    """Module-level one-liner for the compile choke points."""
    return get_observatory().record(**kwargs)


def sync():
    global _GLOBAL
    with _GLOBAL_LOCK:
        o = _GLOBAL
    if o is not None:
        o.sync()


def _reset_observatory():
    """Test isolation: drop the process-global observatory."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is not None:
            _GLOBAL.close()
        _GLOBAL = None


# -- offline readers (scripts/bucket_ladder.py, kill -9 post-mortems) ---


def read_observatory_dir(directory: str) -> List[dict]:
    """Parse every ledger segment in ``directory`` (all writer pids)
    into events ordered by (ts, compileId).  Torn trailing lines and
    zeroed tail space are skipped, never an error."""
    events: List[dict] = []
    for path in sorted(
        glob.glob(os.path.join(directory, _FILE_PREFIX + "*.jsonl"))
    ):
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            continue
        for line in data.split(b"\n"):
            line = line.strip(b"\0").strip()
            if not line:
                continue
            try:
                event = json.loads(line)
            except ValueError:
                continue  # torn write: the crash interrupted this line
            if isinstance(event, dict) and "cause" in event:
                events.append(event)
    events.sort(key=lambda e: (e.get("ts", 0.0), e.get("compileId", 0)))
    return events


def read_census_dir(directory: str) -> ShapeCensus:
    """Merge every writer's census snapshot in ``directory`` into one
    sketch (the cross-process analog of the announcement piggyback)."""
    census = ShapeCensus(max_families=1 << 16)
    for path in sorted(
        glob.glob(os.path.join(directory, _CENSUS_PREFIX + "*.json"))
    ):
        try:
            with open(path) as f:
                census.merge(json.load(f))
        except (OSError, ValueError):
            continue
    return census


# -- padding-ladder recommendation (ROADMAP item 3 input) ----------------


def recommend_ladder(
    census: ShapeCensus,
    max_rungs: int = 8,
    lane: int = 128,
) -> dict:
    """Equi-height ladder over the censused row-count mass.

    Pools every family's power-of-two sketch into one weighted
    distribution of observed (bucketed) row counts, places up to
    ``max_rungs`` rung boundaries at equal-mass quantiles (Ioannidis's
    equi-height construction), rounds each rung up to a multiple of
    ``lane`` (the TPU lane width), and predicts the waste ratio
    (padded/actual rows) the ladder would produce against the same
    distribution.  Returns ``{"ladder", "wasteRatio", "observations",
    "perRung"}``; an empty census yields an empty ladder."""
    # pooled (cover_rows, weight) points.  A rung placed at a bucket
    # must COVER it, so the rung candidate is the bucket's ceiling —
    # clamped to the family's observed max for its top bucket (the pow2
    # sketch only bounds it from above).  The bucket's geometric
    # midpoint is kept separately as the actual-rows estimate.
    points: Dict[int, dict] = {}
    total_actual = 0.0
    observations = 0
    for fam in census.families.values():
        buckets = fam["buckets"]
        top_b = max((int(b) for b in buckets), default=0)
        for b, c in buckets.items():
            hi = int(b)
            c = int(c)
            lo = hi // 2 + 1 if hi > lane else 1
            rep = max(int((lo * hi) ** 0.5), 1)
            cover = hi
            if hi == top_b and int(fam.get("maxRows") or 0):
                cover = min(hi, int(fam["maxRows"]))
            cover = max(cover, 1)
            p = points.setdefault(cover, {"count": 0, "actual": 0.0})
            p["count"] += c
            p["actual"] += rep * c
            observations += c
        total_actual += float(fam.get("totalRows") or 0)
    if not observations:
        return {"ladder": [], "wasteRatio": 1.0,
                "observations": 0, "perRung": []}
    covers = sorted(points)
    # equi-height: rung boundaries at equal cumulative-mass quantiles
    rungs: List[int] = []
    mass = 0
    step = observations / float(max_rungs)
    threshold = step
    for cover in covers:
        mass += points[cover]["count"]
        if mass >= threshold or cover == covers[-1]:
            rung = _lane_align(cover, lane)
            if not rungs or rung > rungs[-1]:
                rungs.append(rung)
            while threshold <= mass:
                threshold += step
    # every observation must fit the top rung
    top = _lane_align(covers[-1], lane)
    if rungs[-1] < top:
        rungs.append(top)
    # predicted waste: each observation pads to the smallest rung that
    # covers its bucket (actualRows per rung is the midpoint estimate;
    # the global denominator is the census's exact totalRows)
    padded_total = 0.0
    per_rung = [
        {"rung": r, "count": 0, "actualRows": 0} for r in rungs
    ]
    for cover in covers:
        p = points[cover]
        for pr in per_rung:
            if pr["rung"] >= cover:
                pr["count"] += p["count"]
                pr["actualRows"] += int(p["actual"])
                padded_total += float(pr["rung"]) * p["count"]
                break
    waste = (padded_total / total_actual) if total_actual else 1.0
    return {
        "ladder": rungs,
        "wasteRatio": waste,
        "observations": observations,
        "perRung": per_rung,
    }
