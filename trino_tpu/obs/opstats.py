"""Per-operator execution timeline: OperatorStats frames + rollups.

Reference parity: operator/OperatorStats.java + execution/QueryStats.java
— every operator reports input/output rows+bytes, wall time split into
device vs host, and blocked time (memory / exchange); frames roll up per
pipeline -> task -> stage on workers, ride heartbeats to the coordinator,
and merge into one query timeline surfaced in EXPLAIN ANALYZE,
``GET /v1/query/{id}`` and ``system.runtime.operator_stats``.

The live straggler detector (Dean & Barroso, *The Tail at Scale*) scores
per-stage task wall dispersion from the same rollups: a task whose
elapsed wall sits ``straggler_dispersion_factor`` robust deviations above
the median of its completed siblings is flagged (and, in FTE, hedged with
a backup attempt) — dispersion-aware, unlike the previous fixed
age-vs-median trigger which ignored how tight the sibling distribution
actually was.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

# wire-document field names (lowerCamelCase, like the flight recorder's
# RECORD_FIELDS and the TaskInfo stats dict) — linted by
# scripts/check_metric_names.py against this tuple
OPERATOR_FIELDS = (
    "operatorId",
    "planNodeId",
    "operatorType",
    "inputRows",
    "inputBytes",
    "outputRows",
    "outputBytes",
    "wallS",
    "deviceWallS",
    "hostWallS",
    "blockedMemoryS",
    "blockedExchangeS",
    "estimatedRows",
    "calls",
)

# frame keys summed when merging sibling tasks' frames; estimatedRows and
# identity keys are carried, not summed
_SUMMED = (
    "inputRows",
    "inputBytes",
    "outputRows",
    "outputBytes",
    "wallS",
    "deviceWallS",
    "hostWallS",
    "blockedMemoryS",
    "blockedExchangeS",
    "calls",
)


def frames_from_plan(
    plan,
    node_stats: Dict[int, dict],
    costs: Optional[dict] = None,
    blocked_memory_s: float = 0.0,
    blocked_exchange_s: float = 0.0,
) -> List[dict]:
    """Convert an executor's ``node_stats`` (id(node) -> raw dict) into
    serializable OperatorStats frames in plan-walk (EXPLAIN print) order.

    Walls in ``node_stats`` are *inclusive* of children (the _TraceCtx
    brackets the whole visit); frames carry the *exclusive* own-wall so
    that summing frame walls reconciles against the query wall instead of
    multiply counting nested subtrees.
    """
    frames: List[dict] = []
    order: List = []

    def walk(n):
        order.append(n)
        for s in n.sources:
            walk(s)

    walk(plan)
    for i, node in enumerate(order):
        st = node_stats.get(id(node))
        if st is None:
            continue
        child_sts = [
            node_stats[id(s)] for s in node.sources
            if id(s) in node_stats
        ]
        own = max(
            st.get("wall_s", 0.0)
            - sum(c.get("wall_s", 0.0) for c in child_sts),
            0.0,
        )
        own_dev = max(
            st.get("device_wall_s", 0.0)
            - sum(c.get("device_wall_s", 0.0) for c in child_sts),
            0.0,
        )
        frame = {
            "operatorId": i,
            "planNodeId": str(i),
            "operatorType": type(node).__name__,
            "inputRows": sum(int(c.get("rows", 0)) for c in child_sts),
            "inputBytes": sum(int(c.get("bytes", 0)) for c in child_sts),
            "outputRows": int(st.get("rows", 0)),
            "outputBytes": int(st.get("bytes", 0)),
            "wallS": own,
            "deviceWallS": own_dev,
            "hostWallS": max(own - own_dev, 0.0),
            "blockedMemoryS": float(st.get("blocked_memory_s", 0.0)),
            "blockedExchangeS": float(st.get("blocked_exchange_s", 0.0)),
            "estimatedRows": None,
            "calls": int(st.get("calls", 0)),
        }
        if costs is not None and id(node) in costs:
            frame["estimatedRows"] = float(costs[id(node)].get("rows", 0.0))
        frames.append(frame)
    # executor-level blocked walls happen before/around the operator walk:
    # memory reservation blocks on behalf of the scan working set, the
    # exchange wait on behalf of the RemoteSource reads
    if frames:
        if blocked_memory_s:
            target = next(
                (f for f in frames if f["operatorType"] == "TableScan"),
                frames[0],
            )
            target["blockedMemoryS"] += float(blocked_memory_s)
        remotes = [
            f for f in frames if f["operatorType"] == "RemoteSource"
        ]
        if blocked_exchange_s:
            for f in remotes or frames[:1]:
                f["blockedExchangeS"] += (
                    float(blocked_exchange_s) / len(remotes or frames[:1])
                )
    return frames


def merge_frames(frame_lists: List[List[dict]]) -> List[dict]:
    """Merge sibling tasks' frames by (planNodeId, operatorType): rows,
    bytes and walls sum across tasks; estimatedRows is the whole-stage
    estimate, so it carries (max) rather than sums."""
    merged: Dict[Tuple[str, str], dict] = {}
    for frames in frame_lists:
        for f in frames or ():
            key = (str(f.get("planNodeId")), str(f.get("operatorType")))
            m = merged.get(key)
            if m is None:
                merged[key] = dict(f)
                continue
            for k in _SUMMED:
                m[k] = (m.get(k) or 0) + (f.get(k) or 0)
            est = f.get("estimatedRows")
            if est is not None:
                prev = m.get("estimatedRows")
                m["estimatedRows"] = est if prev is None else max(prev, est)
    return sorted(
        merged.values(), key=lambda f: int(f.get("operatorId") or 0)
    )


def task_rollup(
    frames: List[dict],
    wall_s: float = 0.0,
    blocked_memory_s: float = 0.0,
    blocked_exchange_s: float = 0.0,
) -> dict:
    """Pipeline -> task rollup: the per-task summary that rides TaskInfo
    stats and worker announcements."""
    return {
        "operators": list(frames or ()),
        "wallS": float(wall_s),
        "outputRows": int(frames[-1].get("outputRows", 0)) if frames else 0,
        "inputRows": sum(int(f.get("inputRows") or 0) for f in frames or ()),
        "blockedMemoryS": float(blocked_memory_s),
        "blockedExchangeS": float(blocked_exchange_s),
    }


def _stage_of(task_id: str) -> str:
    # task ids are {query}.{fragment}.{task_index}[.{attempt}] — three
    # parts from the pipelined scheduler, four from FTE; query ids never
    # contain dots
    parts = str(task_id).split(".")
    return parts[1] if len(parts) >= 3 else "0"


def timeline_from_tasks(tasks: List[dict], detector=None) -> dict:
    """Coordinator-side merge: per-task stats documents (each carrying an
    ``operatorStats`` rollup) -> one query timeline grouped by stage, with
    straggler flags when a detector is supplied."""
    stages: Dict[str, dict] = {}
    for t in tasks or ():
        # the pipelined scheduler flattens TaskInfo stats into the task
        # doc; the FTE path nests them under "stats" — accept both
        stats = t.get("stats") or t
        ops = stats.get("operatorStats") or {}
        task_id = t.get("taskId", "")
        stage_id = _stage_of(task_id)
        st = stages.setdefault(
            stage_id,
            {"stageId": stage_id, "tasks": [], "frameLists": []},
        )
        wall = float(
            ops.get("wallS")
            or (stats.get("wallMillis") or 0) / 1000.0
        )
        st["tasks"].append({
            "taskId": task_id,
            "nodeId": t.get("nodeId") or t.get("uri") or "",
            "wallS": wall,
            "outputRows": int(
                ops.get("outputRows") or stats.get("outputRows") or 0
            ),
            "blockedExchangeS": float(ops.get("blockedExchangeS") or 0.0),
            "blockedMemoryS": float(ops.get("blockedMemoryS") or 0.0),
        })
        st["frameLists"].append(ops.get("operators") or [])
    out_stages = []
    all_frames: List[List[dict]] = []
    for stage_id in sorted(stages, key=lambda s: int(s) if s.isdigit() else 0):
        st = stages[stage_id]
        frames = merge_frames(st["frameLists"])
        walls = [t["wallS"] for t in st["tasks"]]
        med = _median(walls) if walls else 0.0
        dispersion = (max(walls) / med) if med > 0 else 1.0
        entry = {
            "stageId": stage_id,
            "tasks": st["tasks"],
            "operators": frames,
            "medianWallS": med,
            "maxWallS": max(walls) if walls else 0.0,
            "dispersion": dispersion,
            "stragglers": [],
        }
        if detector is not None:
            entry["stragglers"] = detector.observe_stage(
                stage_id, st["tasks"]
            )
        out_stages.append(entry)
        # stage-qualify node ids for the query-level merge: plan node
        # indexes are per-fragment, so "3" in stage 0 and "3" in stage 1
        # are different operators
        all_frames.append([
            dict(f, planNodeId=f"{stage_id}.{f.get('planNodeId')}")
            for f in frames
        ])
    return {"stages": out_stages, "operators": merge_frames(all_frames)}


def mark_node_tasks_terminal(
    stage_map: Dict, node_id: str
) -> List[str]:
    """Node-death fan-out for the live timeline: every rollup entry the
    dead node reported is marked terminal so stage merges and the live
    straggler detector stop treating its tasks as in-flight siblings —
    a ghost task would otherwise hold the stage's wall dispersion open
    forever.  Returns the retired task ids (caller holds the ingest
    lock)."""
    retired: List[str] = []
    for entries in (stage_map or {}).values():
        for entry in entries or ():
            if entry.get("nodeId") != node_id or entry.get("terminal"):
                continue
            entry["terminal"] = True
            entry["terminalReason"] = "NODE_GONE"
            retired.append(str(entry.get("taskId") or ""))
    return retired


def format_timeline(
    frames: List[dict], total_wall_s: Optional[float] = None
) -> str:
    """EXPLAIN ANALYZE text block: one line per operator frame plus the
    wall-reconciliation footer the acceptance tests parse."""
    lines = ["Operator timeline (rows / bytes / wall / blocked):"]
    for f in frames or ():
        est = f.get("estimatedRows")
        est_s = "?" if est is None else f"{est:.0f}"
        lines.append(
            "  %-16s in=%d out=%d bytes=%d wall=%.3fms device=%.3fms "
            "blocked_mem=%.3fms blocked_exch=%.3fms est_rows=%s" % (
                f.get("operatorType", "?"),
                int(f.get("inputRows") or 0),
                int(f.get("outputRows") or 0),
                int(f.get("outputBytes") or 0),
                float(f.get("wallS") or 0.0) * 1000,
                float(f.get("deviceWallS") or 0.0) * 1000,
                float(f.get("blockedMemoryS") or 0.0) * 1000,
                float(f.get("blockedExchangeS") or 0.0) * 1000,
                est_s,
            )
        )
    op_wall = sum(float(f.get("wallS") or 0.0) for f in frames or ())
    if total_wall_s:
        pct = 100.0 * op_wall / total_wall_s if total_wall_s > 0 else 0.0
        lines.append(
            "  operator wall total %.3fs = %.1f%% of query wall %.3fs"
            % (op_wall, pct, total_wall_s)
        )
    else:
        lines.append("  operator wall total %.3fs" % op_wall)
    return "\n".join(lines)


def _median(xs: List[float]) -> float:
    s = sorted(xs)
    return s[len(s) // 2] if s else 0.0


def _mad(xs: List[float], med: float) -> float:
    return _median([abs(x - med) for x in xs])


class StragglerDetector:
    """Scores task wall dispersion per stage and flags outliers.

    A task is a straggler when its wall sits more than ``factor`` robust
    deviations above the median of its (completed) siblings — the
    deviation unit is max(MAD, 10% of the median) so a tight sibling
    distribution hedges aggressively while a naturally noisy stage does
    not.  ``min_s`` floors the elapsed wall so sub-second jitter never
    triggers a hedge.
    """

    def __init__(self, factor: float = 2.0, min_s: float = 0.5):
        self.factor = float(factor)
        self.min_s = float(min_s)
        self.flags: List[dict] = []
        self._lock = threading.Lock()

    def score(self, elapsed: float, sibling_walls: List[float]) -> float:
        """Robust z-score of ``elapsed`` against completed siblings; 0.0
        when there is nothing to compare against."""
        if not sibling_walls:
            return 0.0
        med = _median(sibling_walls)
        unit = max(_mad(sibling_walls, med), 0.1 * med, 1e-3)
        return (elapsed - med) / unit

    def should_hedge(
        self, elapsed: float, sibling_walls: List[float]
    ) -> bool:
        """Dispersion-aware FTE speculation trigger (replaces the fixed
        ``spec_factor * median`` age rule)."""
        if elapsed < self.min_s or not sibling_walls:
            return False
        return self.score(elapsed, sibling_walls) > self.factor

    def record_hedge(
        self, stage_id, task_id: str, uri: str,
        elapsed: float, sibling_walls: List[float],
    ) -> dict:
        from ..utils import metrics as M

        action = {
            "action": "hedge",
            "stage": str(stage_id),
            "task": str(task_id),
            "uri": str(uri),
            "elapsedS": float(elapsed),
            "medianS": _median(sibling_walls),
            "score": self.score(elapsed, sibling_walls),
        }
        with self._lock:
            self.flags.append(action)
        M.counter(
            "trino_tpu_straggler_hedge_total",
            "Dispersion-triggered FTE backup attempts launched",
        ).inc(stage=str(stage_id))
        from . import journal

        journal.emit(
            journal.HEDGE,
            query_id=str(task_id).split(".", 1)[0],
            task_id=str(task_id), severity=journal.WARN,
            stage=str(stage_id), uri=str(uri),
            elapsedS=float(elapsed), medianS=action["medianS"],
        )
        return action

    def observe_node_gone(
        self, node_id: str, retired: List[str]
    ) -> dict:
        """Record a node-death event in the flag stream: retired tasks
        must not be scored as stragglers (their walls stopped moving for
        a reason dispersion can't see), and the flag gives EXPLAIN
        ANALYZE / system.runtime a durable marker of why a stage's task
        roster shrank mid-query."""
        from ..utils import metrics as M

        flag = {
            "action": "node_gone",
            "node": str(node_id),
            "retiredTasks": [str(t) for t in retired or ()],
        }
        with self._lock:
            self.flags.append(flag)
        M.counter(
            "trino_tpu_straggler_node_gone_total",
            "Node-death events observed by the straggler detector",
        ).inc(node=str(node_id))
        return flag

    def observe_stage(self, stage_id, tasks: List[dict]) -> List[dict]:
        """Flag stragglers among a stage's completed tasks (the timeline
        merge path).  Returns the flag entries added."""
        from ..utils import metrics as M

        walls = [float(t.get("wallS") or 0.0) for t in tasks or ()]
        if not walls:
            return []
        med = _median(walls)
        M.gauge(
            "trino_tpu_straggler_dispersion_state",
            "max/median task wall dispersion of the last observed stage",
        ).set(
            (max(walls) / med) if med > 0 else 1.0, stage=str(stage_id)
        )
        flagged: List[dict] = []
        for t in tasks:
            wall = float(t.get("wallS") or 0.0)
            siblings = [
                float(o.get("wallS") or 0.0) for o in tasks if o is not t
            ]
            if wall < self.min_s or not siblings:
                continue
            sc = self.score(wall, siblings)
            if sc > self.factor:
                flag = {
                    "action": "flag",
                    "stage": str(stage_id),
                    "task": str(t.get("taskId", "")),
                    "node": str(t.get("nodeId", "")),
                    "wallS": wall,
                    "medianS": _median(siblings),
                    "score": sc,
                }
                flagged.append(flag)
                M.counter(
                    "trino_tpu_straggler_flagged_total",
                    "Tasks flagged as stragglers by wall dispersion",
                ).inc(stage=str(stage_id))
                M.histogram(
                    "trino_tpu_straggler_wall_seconds",
                    "Wall time of tasks flagged as stragglers",
                ).observe(wall)
        if flagged:
            with self._lock:
                self.flags.extend(flagged)
            from . import journal

            for flag in flagged:
                journal.emit(
                    journal.STRAGGLER_FLAG,
                    query_id=flag["task"].split(".", 1)[0],
                    task_id=flag["task"], node_id=flag["node"],
                    severity=journal.WARN,
                    stage=flag["stage"], wallS=flag["wallS"],
                    medianS=flag["medianS"], score=flag["score"],
                )
        return flagged
