"""Serving observatory: per-signature workload census, cache-affinity
map, and per-tenant SLO burn-rate monitor.

Three coordinator-resident components that turn "the engine measures
everything" into "the engine knows what to do next":

- :class:`SignatureCensus` — keyed by the canonical plan signature
  (``cache/signature.py``), a bounded rolling profile per recurring
  query shape: arrival count + EWMA inter-arrival rate, latency
  p50/p95/p99 (the fixed-bucket histogram from ``utils/metrics.py``),
  observed device/host cost, estimate-vs-observed row drift (Leis et
  al., *How Good Are Query Optimizers, Really?*, VLDB 2015 — track the
  drift per shape instead of trusting static estimates), and
  result-cache hit/miss tallies.  Fed from the coordinator's
  ``_finalize_query``; persisted through the same mmap'd
  torn-tail-tolerant two-segment pid-suffixed store contract as the
  journal and query history (``serving_observatory_dir``), merged
  across restarts and backfilled from the persisted query history.
- :class:`AffinityMap` view — per-node warmth per signature: which
  nodes hold compiled programs for the signature's kernel families
  (piggybacking the compile observatory's per-family census that
  already rides worker announcements) and which node holds its
  fragment-result-cache entry.  ``system.runtime.signature_affinity``
  and ``GET /v1/affinity`` are literally the input table a
  locality-aware dispatcher reads.
- :class:`SloMonitor` — per-tenant declared latency/error objectives
  with multi-window burn-rate computation (fast ~30 s + slow ~5 m,
  scaled for tests) in the spirit of Dean & Barroso's *The Tail at
  Scale*: watch the budget burn continuously instead of discovering
  the violation in a post-mortem.  A fast-window burn past threshold
  journals a throttled ``SLO_BURN`` event the query doctor ranks
  directly below overload.
"""
from __future__ import annotations

import glob
import json
import mmap
import os
import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..utils.metrics import Histogram, REGISTRY

# wire-document field names (lowerCamelCase, one naming regime with the
# journal/flight-recorder/WAL documents) — linted by
# scripts/check_metric_names.py against these tuples

# one persisted observation (JSONL record in the two-segment store)
OBSERVATION_FIELDS = (
    "queryId",
    "signature",
    "tenant",
    "latencyS",
    "deviceWallS",
    "hostWallS",
    "driftRatio",
    "cacheHit",
    "cacheStored",
    "families",
    "ts",
)

# one census row (system.runtime.plan_signatures / GET /v1/signatures)
SIGNATURE_FIELDS = (
    "signature",
    "tenant",
    "count",
    "ratePerS",
    "p50S",
    "p95S",
    "p99S",
    "deviceWallS",
    "hostWallS",
    "driftRatio",
    "cacheHits",
    "cacheMisses",
    "families",
    "lastTs",
)

# one affinity row (system.runtime.signature_affinity / GET /v1/affinity)
AFFINITY_FIELDS = (
    "signature",
    "nodeId",
    "warmFamilies",
    "familiesTotal",
    "resultCache",
    "score",
)

# one tenant objective row (system.runtime.slos / GET /v1/slo)
SLO_FIELDS = (
    "tenant",
    "latencyTargetS",
    "errorBudget",
    "fastWindowS",
    "slowWindowS",
    "fastBurnRate",
    "slowBurnRate",
    "peakFastBurn",
    "violationsTotal",
    "observedTotal",
    "burnEvents",
    "p50S",
    "p95S",
    "p99S",
)

DEFAULT_MAX_BYTES = 1 << 20
MAX_RECORD_BYTES = 4096
MIN_SEGMENT_BYTES = 1 << 16
_FILE_PREFIX = "so-"

# census bounding: past this many signatures new shapes fold into one
# overflow bucket (the compile observatory's ShapeCensus contract)
DEFAULT_MAX_SIGNATURES = 128
OTHER_KEY = "__other__"

# SLO defaults (overridable per tenant via resource-group spec keys
# sloLatencyTargetS/sloErrorBudget and session properties)
DEFAULT_LATENCY_TARGET_S = 1.0
DEFAULT_ERROR_BUDGET = 0.1
DEFAULT_FAST_WINDOW_S = 30.0
DEFAULT_SLOW_WINDOW_S = 300.0
DEFAULT_BURN_THRESHOLD = 2.0

_LATENCY_HIST_NAME = "trino_tpu_signature_latency_seconds"


class _Segment:
    """One preallocated mmap'd JSONL file; re-opens append at the end of
    the surviving records instead of zeroing them (restart survival —
    the query-history contract, not the flight recorder's)."""

    def __init__(self, path: str, size: int):
        self.path = path
        self.size = size
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            os.ftruncate(fd, size)
            self.mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self.offset = 0
        self.records = 0
        self.last_ts = 0.0

    def load(self) -> List[Dict]:
        """Parse surviving records and position the append offset after
        the last intact line (a torn trailing line is overwritten)."""
        out: List[Dict] = []
        data = self.mm[: self.size]
        pos = 0
        for line in data.split(b"\n"):
            raw = line.strip(b"\0").strip()
            if raw:
                try:
                    rec = json.loads(raw)
                except ValueError:
                    break  # torn write: stop; append resumes here
                if isinstance(rec, dict) and "signature" in rec:
                    out.append(rec)
                    self.records += 1
                    self.last_ts = max(
                        self.last_ts, float(rec.get("ts") or 0.0)
                    )
                    pos += len(line) + 1
                    continue
            break
        self.offset = pos
        return out

    def reset(self):
        self.mm[: self.size] = b"\0" * self.size
        self.offset = 0
        self.records = 0
        self.last_ts = 0.0

    def append(self, data: bytes) -> bool:
        if self.offset + len(data) > self.size:
            return False
        self.mm[self.offset : self.offset + len(data)] = data
        self.offset += len(data)
        self.records += 1
        return True

    def sync(self):
        try:
            self.mm.flush()
        except Exception:  # noqa: BLE001 — sync is advisory
            pass

    def close(self):
        try:
            self.mm.close()
        except Exception:  # noqa: BLE001
            pass


class SignatureCensus:
    """Bounded rolling profile per canonical plan signature.

    Pure rollup math — persistence, SLO accounting and journal emission
    live in :class:`ServingObservatory`.  Observations carry the query
    id so replays (disk merge at boot, history backfill) never double
    count a query the live path already saw."""

    def __init__(
        self,
        max_signatures: int = DEFAULT_MAX_SIGNATURES,
        ewma_alpha: float = 0.25,
        buckets: Optional[Sequence[float]] = None,
    ):
        self.max_signatures = max(int(max_signatures), 1)
        self.ewma_alpha = float(ewma_alpha)
        self.buckets = tuple(buckets) if buckets else None
        self.profiles: Dict[str, Dict] = {}
        self._seen: Set[str] = set()
        self._seen_order: deque = deque()
        self._seen_cap = 8192
        self._lock = threading.Lock()

    # -- feed -----------------------------------------------------------
    def seen(self, query_id: str) -> bool:
        with self._lock:
            return bool(query_id) and query_id in self._seen

    def observe(
        self,
        signature: str,
        tenant: str = "",
        query_id: str = "",
        latency_s: float = 0.0,
        device_wall_s: float = 0.0,
        host_wall_s: float = 0.0,
        drift_ratio: Optional[float] = None,
        cache_hit: Optional[bool] = None,
        cache_stored: bool = False,
        families: Iterable[str] = (),
        node_id: str = "",
        ts: Optional[float] = None,
    ) -> bool:
        """Fold one finished query into its signature's profile.

        Returns False (and folds nothing) when the query id was already
        observed — the dedup that makes disk replay + history backfill
        idempotent against the live feed."""
        signature = str(signature or "")
        if not signature:
            return False
        ts = float(ts if ts is not None else time.time())
        with self._lock:
            if query_id:
                if query_id in self._seen:
                    return False
                self._seen.add(query_id)
                self._seen_order.append(query_id)
                while len(self._seen_order) > self._seen_cap:
                    self._seen.discard(self._seen_order.popleft())
            prof = self.profiles.get(signature)
            if prof is None:
                if (
                    len(self.profiles) >= self.max_signatures
                    and signature != OTHER_KEY
                ):
                    signature = OTHER_KEY
                    prof = self.profiles.get(OTHER_KEY)
            if prof is None:
                prof = {
                    "count": 0,
                    "lastTs": 0.0,
                    "ewmaIntervalS": 0.0,
                    "hist": Histogram(
                        _LATENCY_HIST_NAME,
                        "per-signature end-to-end latency",
                        buckets=self.buckets,
                    ),
                    "deviceWallS": 0.0,
                    "hostWallS": 0.0,
                    "driftRatio": 0.0,
                    "cacheHits": 0,
                    "cacheMisses": 0,
                    "families": set(),
                    "tenants": {},
                    "resultCacheNodes": set(),
                }
                self.profiles[signature] = prof
            prof["count"] += 1
            if prof["lastTs"] > 0.0 and ts > prof["lastTs"]:
                interval = max(ts - prof["lastTs"], 1e-6)
                if prof["ewmaIntervalS"] <= 0.0:
                    prof["ewmaIntervalS"] = interval
                else:
                    a = self.ewma_alpha
                    prof["ewmaIntervalS"] = (
                        a * interval + (1.0 - a) * prof["ewmaIntervalS"]
                    )
            prof["lastTs"] = max(prof["lastTs"], ts)
            prof["hist"].observe(max(float(latency_s or 0.0), 0.0))
            prof["deviceWallS"] += float(device_wall_s or 0.0)
            prof["hostWallS"] += float(host_wall_s or 0.0)
            if drift_ratio is not None:
                prof["driftRatio"] = max(
                    prof["driftRatio"], float(drift_ratio)
                )
            if cache_hit is True:
                prof["cacheHits"] += 1
            elif cache_hit is False:
                prof["cacheMisses"] += 1
            for fam in families or ():
                prof["families"].add(str(fam))
            if tenant:
                t = prof["tenants"]
                t[tenant] = t.get(tenant, 0) + 1
            if node_id and (cache_hit or cache_stored):
                prof["resultCacheNodes"].add(str(node_id))
        REGISTRY.counter(
            "trino_tpu_signature_queries_total",
            "Finished queries folded into the signature census",
        ).inc()
        REGISTRY.gauge(
            "trino_tpu_signature_census_state",
            "Distinct plan signatures currently profiled",
        ).set(len(self.profiles))
        return True

    # -- read -----------------------------------------------------------
    def rows(self) -> List[Dict]:
        """Census rows in the SIGNATURE_FIELDS wire shape, busiest
        signature first."""
        out: List[Dict] = []
        with self._lock:
            items = list(self.profiles.items())
        for sig, prof in items:
            hist = prof["hist"]
            interval = prof["ewmaIntervalS"]
            tenants = prof["tenants"]
            dominant = (
                max(tenants.items(), key=lambda kv: kv[1])[0]
                if tenants
                else ""
            )
            out.append(
                {
                    "signature": sig,
                    "tenant": dominant,
                    "count": prof["count"],
                    "ratePerS": (1.0 / interval) if interval > 0 else 0.0,
                    "p50S": hist.quantile(0.50),
                    "p95S": hist.quantile(0.95),
                    "p99S": hist.quantile(0.99),
                    "deviceWallS": prof["deviceWallS"],
                    "hostWallS": prof["hostWallS"],
                    "driftRatio": prof["driftRatio"],
                    "cacheHits": prof["cacheHits"],
                    "cacheMisses": prof["cacheMisses"],
                    "families": sorted(prof["families"]),
                    "lastTs": prof["lastTs"],
                }
            )
        out.sort(key=lambda r: (-r["count"], r["signature"]))
        return out

    def families_of(self, signature: str) -> Set[str]:
        with self._lock:
            prof = self.profiles.get(signature)
            return set(prof["families"]) if prof else set()

    def result_cache_nodes(self, signature: str) -> Set[str]:
        with self._lock:
            prof = self.profiles.get(signature)
            return set(prof["resultCacheNodes"]) if prof else set()


class SloMonitor:
    """Per-tenant latency/error objectives with multi-window burn rates.

    ``burn rate = (violating fraction of the window) / error budget`` —
    1.0 means the tenant burns its budget exactly as fast as allowed; a
    fast-window burn past ``burn_threshold`` journals one throttled
    ``SLO_BURN`` event per window so a sustained breach is a handful of
    citable events, not a flood."""

    def __init__(
        self,
        latency_target_s: float = DEFAULT_LATENCY_TARGET_S,
        error_budget: float = DEFAULT_ERROR_BUDGET,
        fast_window_s: float = DEFAULT_FAST_WINDOW_S,
        slow_window_s: float = DEFAULT_SLOW_WINDOW_S,
        burn_threshold: float = DEFAULT_BURN_THRESHOLD,
        max_samples: int = 4096,
    ):
        self.latency_target_s = float(latency_target_s)
        self.error_budget = max(float(error_budget), 1e-6)
        self.fast_window_s = max(float(fast_window_s), 1e-3)
        self.slow_window_s = max(float(slow_window_s), self.fast_window_s)
        self.burn_threshold = float(burn_threshold)
        self.max_samples = int(max_samples)
        self._lock = threading.Lock()
        self._objectives: Dict[str, Dict] = {}
        self._samples: Dict[str, deque] = {}
        self._hist: Dict[str, Histogram] = {}
        self._violations: Dict[str, int] = {}
        self._observed: Dict[str, int] = {}
        self._burn_events: Dict[str, int] = {}
        self._peak_fast: Dict[str, float] = {}
        self._last_emit: Dict[str, float] = {}

    def set_defaults(
        self,
        latency_target_s=None,
        error_budget=None,
        fast_window_s=None,
        slow_window_s=None,
        burn_threshold=None,
    ):
        if latency_target_s is not None:
            self.latency_target_s = float(latency_target_s)
        if error_budget is not None:
            self.error_budget = max(float(error_budget), 1e-6)
        if fast_window_s is not None:
            self.fast_window_s = max(float(fast_window_s), 1e-3)
        if slow_window_s is not None:
            self.slow_window_s = max(float(slow_window_s), 1e-3)
        self.slow_window_s = max(self.slow_window_s, self.fast_window_s)
        if burn_threshold is not None:
            self.burn_threshold = float(burn_threshold)

    def set_objective(
        self, tenant: str, latency_target_s=None, error_budget=None
    ):
        """Declare one tenant's objective (resource-group spec keys
        ``sloLatencyTargetS``/``sloErrorBudget`` land here)."""
        tenant = str(tenant or "global")
        with self._lock:
            obj = self._objectives.setdefault(tenant, {})
            if latency_target_s is not None:
                obj["latencyTargetS"] = float(latency_target_s)
            if error_budget is not None:
                obj["errorBudget"] = max(float(error_budget), 1e-6)

    def objective(self, tenant: str) -> Tuple[float, float]:
        with self._lock:
            obj = self._objectives.get(str(tenant or "global")) or {}
        return (
            float(obj.get("latencyTargetS", self.latency_target_s)),
            float(obj.get("errorBudget", self.error_budget)),
        )

    # -- feed -----------------------------------------------------------
    def observe(
        self,
        tenant: str,
        latency_s: float,
        ok: bool = True,
        query_id: str = "",
        ts: Optional[float] = None,
        quiet: bool = False,
    ) -> Optional[int]:
        """Fold one finished query into its tenant's window; returns the
        journaled SLO_BURN event id when this observation tripped the
        fast-window threshold (None otherwise)."""
        tenant = str(tenant or "global")
        ts = float(ts if ts is not None else time.time())
        latency_s = max(float(latency_s or 0.0), 0.0)
        target, budget = self.objective(tenant)
        violated = (not ok) or latency_s > target
        with self._lock:
            samples = self._samples.get(tenant)
            if samples is None:
                samples = deque(maxlen=self.max_samples)
                self._samples[tenant] = samples
                self._hist[tenant] = Histogram(
                    "trino_tpu_slo_latency_seconds",
                    "per-tenant end-to-end latency under the SLO",
                )
            samples.append((ts, violated))
            self._hist[tenant].observe(latency_s)
            self._observed[tenant] = self._observed.get(tenant, 0) + 1
            if violated:
                self._violations[tenant] = (
                    self._violations.get(tenant, 0) + 1
                )
        if violated:
            REGISTRY.counter(
                "trino_tpu_slo_violations_total",
                "Queries that violated their tenant's SLO "
                "(late or failed)",
            ).inc(tenant=tenant)
        fast = self.burn_rate(tenant, self.fast_window_s, now=ts)
        slow = self.burn_rate(tenant, self.slow_window_s, now=ts)
        gauge = REGISTRY.gauge(
            "trino_tpu_slo_burn_rate_state",
            "Current SLO error-budget burn rate, by tenant and window",
        )
        gauge.set(fast, tenant=tenant, window="fast")
        gauge.set(slow, tenant=tenant, window="slow")
        with self._lock:
            self._peak_fast[tenant] = max(
                self._peak_fast.get(tenant, 0.0), fast
            )
        if quiet or fast <= self.burn_threshold:
            return None
        with self._lock:
            last = self._last_emit.get(tenant, 0.0)
            if ts - last < self.fast_window_s:
                return None  # throttle: one event per fast window
            self._last_emit[tenant] = ts
            self._burn_events[tenant] = (
                self._burn_events.get(tenant, 0) + 1
            )
            violations = self._violations.get(tenant, 0)
        from . import journal

        return journal.emit(
            journal.SLO_BURN,
            query_id=query_id,
            severity=journal.WARN,
            tenant=tenant,
            window="fast",
            windowS=self.fast_window_s,
            burnRate=round(fast, 4),
            slowBurnRate=round(slow, 4),
            latencyTargetS=target,
            errorBudget=budget,
            violations=violations,
        )

    # -- read -----------------------------------------------------------
    def burn_rate(
        self, tenant: str, window_s: float, now: Optional[float] = None
    ) -> float:
        tenant = str(tenant or "global")
        now = float(now if now is not None else time.time())
        _, budget = self.objective(tenant)
        with self._lock:
            samples = self._samples.get(tenant) or ()
            window = [v for (t, v) in samples if now - t <= window_s]
        if not window:
            return 0.0
        frac = sum(1 for v in window if v) / float(len(window))
        return frac / budget

    def tenants(self) -> List[str]:
        with self._lock:
            return sorted(set(self._samples) | set(self._objectives))

    def rows(self, now: Optional[float] = None) -> List[Dict]:
        """Per-tenant compliance rows in the SLO_FIELDS wire shape."""
        now = float(now if now is not None else time.time())
        out: List[Dict] = []
        for tenant in self.tenants():
            target, budget = self.objective(tenant)
            with self._lock:
                hist = self._hist.get(tenant)
                violations = self._violations.get(tenant, 0)
                observed = self._observed.get(tenant, 0)
                burns = self._burn_events.get(tenant, 0)
                peak = self._peak_fast.get(tenant, 0.0)
            out.append(
                {
                    "tenant": tenant,
                    "latencyTargetS": target,
                    "errorBudget": budget,
                    "fastWindowS": self.fast_window_s,
                    "slowWindowS": self.slow_window_s,
                    "fastBurnRate": self.burn_rate(
                        tenant, self.fast_window_s, now=now
                    ),
                    "slowBurnRate": self.burn_rate(
                        tenant, self.slow_window_s, now=now
                    ),
                    "peakFastBurn": peak,
                    "violationsTotal": violations,
                    "observedTotal": observed,
                    "burnEvents": burns,
                    "p50S": hist.quantile(0.50) if hist else 0.0,
                    "p95S": hist.quantile(0.95) if hist else 0.0,
                    "p99S": hist.quantile(0.99) if hist else 0.0,
                }
            )
        return out


class ServingObservatory:
    """The census + SLO monitor behind one crash-safe store.

    ``directory=None`` keeps observations memory-only; a directory
    upgrades the census feed to two pid-suffixed mmap'd segments that
    are merged back on the next open (including segments other writer
    pids left behind — the query-history restart contract)."""

    def __init__(
        self,
        directory: Optional[str] = None,
        max_bytes: int = DEFAULT_MAX_BYTES,
        name: Optional[str] = None,
        max_signatures: int = DEFAULT_MAX_SIGNATURES,
        buckets: Optional[Sequence[float]] = None,
        slo: Optional[Dict] = None,
    ):
        self.directory = str(directory or "").strip() or None
        self.max_bytes = max(
            int(max_bytes or DEFAULT_MAX_BYTES), 2 * MIN_SEGMENT_BYTES
        )
        self.name = name or str(os.getpid())
        self.census = SignatureCensus(
            max_signatures=max_signatures, buckets=buckets
        )
        self.slo = SloMonitor(**(slo or {}))
        self._lock = threading.Lock()
        # bounded raw-record mirror: configure() replays it into the
        # fresh segments so observations that land before the owning
        # coordinator finishes constructing are not lost
        self.mirror: deque = deque(maxlen=1024)
        self._segments: List[_Segment] = []
        self._active = 0
        if self.directory:
            os.makedirs(self.directory, exist_ok=True)
            seg_bytes = max(MIN_SEGMENT_BYTES, self.max_bytes // 2)
            own = set()
            for i in range(2):
                path = os.path.join(
                    self.directory,
                    f"{_FILE_PREFIX}{self.name}-{i}.jsonl",
                )
                own.add(os.path.abspath(path))
                self._segments.append(_Segment(path, seg_bytes))
            # survivors from OTHER writers (crashed pids, siblings)
            # replay into the census but are never appended to
            for rec in read_observatory_dir(self.directory, exclude=own):
                self._replay(rec)
            for seg in self._segments:
                for rec in seg.load():
                    self._replay(rec)
            self._active = max(
                range(2), key=lambda i: self._segments[i].last_ts
            )

    def _replay(self, rec: Dict):
        self.census.observe(
            rec.get("signature") or "",
            tenant=str(rec.get("tenant") or ""),
            query_id=str(rec.get("queryId") or ""),
            latency_s=float(rec.get("latencyS") or 0.0),
            device_wall_s=float(rec.get("deviceWallS") or 0.0),
            host_wall_s=float(rec.get("hostWallS") or 0.0),
            drift_ratio=rec.get("driftRatio"),
            cache_hit=rec.get("cacheHit"),
            cache_stored=bool(rec.get("cacheStored")),
            families=rec.get("families") or (),
            ts=rec.get("ts"),
        )

    # -- feed -----------------------------------------------------------
    def observe_query(
        self,
        signature: str = "",
        tenant: str = "",
        query_id: str = "",
        latency_s: float = 0.0,
        ok: bool = True,
        device_wall_s: float = 0.0,
        host_wall_s: float = 0.0,
        drift_ratio: Optional[float] = None,
        cache_hit: Optional[bool] = None,
        cache_stored: bool = False,
        families: Iterable[str] = (),
        node_id: str = "",
        ts: Optional[float] = None,
        quiet: bool = False,
    ) -> Optional[int]:
        """The one finalize hook: census + persistence + SLO in a single
        call.  Queries without a plan signature (coordinator-only
        statements, planning failures) still count against their
        tenant's SLO.  Returns the SLO_BURN event id if one fired."""
        ts = float(ts if ts is not None else time.time())
        rec = {
            "queryId": str(query_id or ""),
            "signature": str(signature or ""),
            "tenant": str(tenant or ""),
            "latencyS": float(latency_s or 0.0),
            "deviceWallS": float(device_wall_s or 0.0),
            "hostWallS": float(host_wall_s or 0.0),
            "driftRatio": drift_ratio,
            "cacheHit": cache_hit,
            "cacheStored": bool(cache_stored),
            "families": sorted(str(f) for f in (families or ())),
            "ok": bool(ok),
            "ts": ts,
        }
        if signature:
            fresh = self.census.observe(
                signature,
                tenant=rec["tenant"],
                query_id=rec["queryId"],
                latency_s=rec["latencyS"],
                device_wall_s=rec["deviceWallS"],
                host_wall_s=rec["hostWallS"],
                drift_ratio=drift_ratio,
                cache_hit=cache_hit,
                cache_stored=rec["cacheStored"],
                families=rec["families"],
                node_id=node_id,
                ts=ts,
            )
            if fresh:
                self._persist(rec)
        return self.slo.observe(
            tenant,
            latency_s,
            ok=ok,
            query_id=query_id,
            ts=ts,
            quiet=quiet,
        )

    def _persist(self, rec: Dict):
        with self._lock:
            self.mirror.append(rec)
            if not self._segments:
                return
            data = _encode(rec)
            if data is None:
                return
            seg = self._segments[self._active]
            if not seg.append(data):
                self._active = 1 - self._active
                seg = self._segments[self._active]
                seg.reset()
                seg.append(data)

    def backfill_from_history(self, records: Iterable[Dict]) -> int:
        """Rebuild census rows from persisted query-history records
        (``obs/history.py`` wire shape, carrying tenant/planSignature
        since round 19).  Already-observed query ids are skipped, so
        running after a disk merge only fills the gaps — and backfilled
        rows are not re-persisted (the history store is their durable
        home)."""
        count = 0
        for rec in records or ():
            if rec.get("state") not in ("FINISHED", "FAILED"):
                continue
            sig = str(rec.get("planSignature") or "")
            qid = str(rec.get("queryId") or "")
            if not sig or not qid:
                continue
            ts = rec.get("finished") or rec.get("ts")
            if self.census.observe(
                sig,
                tenant=str(rec.get("tenant") or ""),
                query_id=qid,
                latency_s=float(rec.get("wallS") or 0.0),
                ts=float(ts) if ts else None,
            ):
                count += 1
        return count

    # -- read -----------------------------------------------------------
    def signature_rows(self) -> List[Dict]:
        return self.census.rows()

    def affinity_rows(self, local_node_id: str = "") -> List[Dict]:
        """AFFINITY_FIELDS rows: per (signature, node) warmth, computed
        at read time by joining the census's kernel-family digests with
        the compile observatory's per-node family census (local process
        + every worker announcement) and the coordinator-local
        fragment-result-cache flag."""
        from . import compile_observatory as _co

        try:
            fam_map = _co.get_observatory().node_family_map(
                local_node_id=local_node_id
            )
        except Exception:  # noqa: BLE001 — affinity is best-effort
            fam_map = {}
        out: List[Dict] = []
        for row in self.census.rows():
            sig = row["signature"]
            if sig == OTHER_KEY:
                continue
            fams = set(row["families"])
            cache_nodes = self.census.result_cache_nodes(sig)
            nodes = set(fam_map) | cache_nodes
            total = len(fams)
            for node in sorted(nodes):
                warm = len(fams & fam_map.get(node, set()))
                has_cache = node in cache_nodes
                if warm == 0 and not has_cache:
                    continue
                score = (warm / total if total else 0.0) + (
                    1.0 if has_cache else 0.0
                )
                out.append(
                    {
                        "signature": sig,
                        "nodeId": node,
                        "warmFamilies": warm,
                        "familiesTotal": total,
                        "resultCache": bool(has_cache),
                        "score": round(score, 4),
                    }
                )
        out.sort(key=lambda r: (r["signature"], -r["score"], r["nodeId"]))
        return out

    def top_signatures(
        self, n: int = 10, local_node_id: str = ""
    ) -> List[Dict]:
        """The webui/bench block: busiest N census rows, each annotated
        with its warmest node from the affinity map."""
        warmest: Dict[str, Tuple[float, str]] = {}
        for a in self.affinity_rows(local_node_id=local_node_id):
            cur = warmest.get(a["signature"])
            if cur is None or a["score"] > cur[0]:
                warmest[a["signature"]] = (a["score"], a["nodeId"])
        out = []
        for row in self.census.rows()[: max(int(n), 0)]:
            row = dict(row)
            row["warmestNode"] = warmest.get(
                row["signature"], (0.0, "")
            )[1]
            row["families"] = len(row["families"])
            out.append(row)
        return out

    def slo_rows(self, now: Optional[float] = None) -> List[Dict]:
        return self.slo.rows(now=now)

    # -- durability ------------------------------------------------------
    def sync(self):
        with self._lock:
            for seg in self._segments:
                seg.sync()

    def close(self):
        with self._lock:
            for seg in self._segments:
                seg.close()
            self._segments = []


def _encode(rec: Dict) -> Optional[bytes]:
    data = json.dumps(rec, separators=(",", ":"), default=str).encode()
    data += b"\n"
    if len(data) > MAX_RECORD_BYTES:
        rec = dict(rec, families=[])
        data = json.dumps(rec, separators=(",", ":"), default=str).encode()
        data += b"\n"
        if len(data) > MAX_RECORD_BYTES:
            return None  # pathological; drop rather than corrupt
    return data


def read_observatory_dir(
    directory: str, exclude: Optional[set] = None
) -> List[Dict]:
    """Offline reader: every surviving observation in ``directory``
    ordered by ts.  Torn trailing lines and zeroed tail space are
    skipped, never an error — the kill -9 contract shared with the
    journal and query history."""
    records: List[Dict] = []
    for path in sorted(
        glob.glob(os.path.join(directory, _FILE_PREFIX + "*.jsonl"))
    ):
        if exclude and os.path.abspath(path) in exclude:
            continue
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            continue
        for line in data.split(b"\n"):
            line = line.strip(b"\0").strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn write
            if isinstance(rec, dict) and "signature" in rec:
                records.append(rec)
    records.sort(key=lambda r: (r.get("ts") or 0.0, r.get("queryId", "")))
    return records


# -- the process-global observatory (finalize + system tables + HTTP) ---

_GLOBAL_LOCK = threading.Lock()
_GLOBAL: Optional[ServingObservatory] = None


def get_observatory() -> ServingObservatory:
    """The process-global observatory (memory-only until configured)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = ServingObservatory(None)
        return _GLOBAL


def configure(
    directory=None,
    max_bytes=None,
    max_signatures=None,
    slo=None,
) -> ServingObservatory:
    """Upgrade/re-point the global observatory (coordinator boot:
    ``serving_observatory_dir`` + SLO session properties).  Observations
    already in the memory mirror are replayed into the fresh store, SLO
    defaults apply in place when the store itself is unchanged."""
    global _GLOBAL
    directory = str(directory or "").strip() or None
    try:
        max_bytes = int(max_bytes or 0) or DEFAULT_MAX_BYTES
    except (TypeError, ValueError):
        max_bytes = DEFAULT_MAX_BYTES
    with _GLOBAL_LOCK:
        cur = _GLOBAL
        if (
            cur is not None
            and cur.directory == directory
            and (directory is None or cur.max_bytes == max_bytes)
        ):
            if slo:
                cur.slo.set_defaults(**slo)
            if max_signatures:
                cur.census.max_signatures = max(int(max_signatures), 1)
            return cur
        nxt = ServingObservatory(
            directory,
            max_bytes=max_bytes,
            max_signatures=max_signatures or DEFAULT_MAX_SIGNATURES,
            slo=slo,
        )
        if cur is not None:
            for rec in list(cur.mirror):
                nxt.observe_query(
                    signature=rec.get("signature") or "",
                    tenant=rec.get("tenant") or "",
                    query_id=rec.get("queryId") or "",
                    latency_s=rec.get("latencyS") or 0.0,
                    ok=bool(rec.get("ok", True)),
                    device_wall_s=rec.get("deviceWallS") or 0.0,
                    host_wall_s=rec.get("hostWallS") or 0.0,
                    drift_ratio=rec.get("driftRatio"),
                    cache_hit=rec.get("cacheHit"),
                    cache_stored=bool(rec.get("cacheStored")),
                    families=rec.get("families") or (),
                    ts=rec.get("ts"),
                    quiet=True,
                )
            cur.close()
        _GLOBAL = nxt
        return nxt


def sync():
    """Flush the global observatory's segments (drain/shutdown walk)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        obs = _GLOBAL
    if obs is not None:
        obs.sync()


def _reset_observatory():
    """Test isolation: drop the process-global observatory."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is not None:
            _GLOBAL.close()
        _GLOBAL = None
