"""HBM bandwidth ledger: per-kernel bytes-touched over timed device wall.

ROADMAP open item 1 is a bandwidth gap — best Q6 runs at ~2 GB/s
effective against ~1.2 TB/s of HBM — and closing it needs a per-operator
accounting of where the bytes go.  Each supervised dispatch that runs
under the ``bandwidth_ledger`` session property is bracketed with
``block_until_ready`` in the executor, and the ledger folds

    input bytes   (unpadded host scan/exchange arrays fed to the program)
  + output bytes  (padded device output lanes + selection mask)
  + intermediate  (wide-decimal accumulator estimate from
                   ``estimate_program_bytes``)

over the measured device wall into effective GB/s and %-of-roofline per
kernel digest.  Entries surface in EXPLAIN ANALYZE, the query profile
endpoint, ``system.runtime.kernel_bandwidth``, and the
``trino_tpu_kernel_bandwidth_*`` histograms.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

from ..utils.metrics import BYTES_BUCKETS, REGISTRY

# one TPU v4 chip moves ~1228 GB/s from HBM2e; override for other parts
# (or to calibrate CPU-backend tests) via TRINO_TPU_ROOFLINE_GBPS
DEFAULT_ROOFLINE_GBPS = 1228.8


def roofline_bytes_per_s() -> float:
    try:
        gbps = float(
            os.environ.get("TRINO_TPU_ROOFLINE_GBPS", DEFAULT_ROOFLINE_GBPS)
        )
    except ValueError:
        gbps = DEFAULT_ROOFLINE_GBPS
    return gbps * 1e9


class BandwidthLedger:
    """Accumulates per-kernel byte/wall observations for one executor
    (one query — or one task in distributed mode)."""

    def __init__(self, roofline_gbps: Optional[float] = None):
        self.roofline_bytes_per_s = (
            float(roofline_gbps) * 1e9
            if roofline_gbps else roofline_bytes_per_s()
        )
        # remote-exchange input held by a FragmentExecutor: counted once
        # per task (the merged arrays also feed per-dispatch inputBytes)
        self.exchange_bytes = 0
        self._lock = threading.Lock()
        self._entries: Dict[str, Dict] = {}

    def record(
        self,
        digest: str,
        mode: str,
        input_bytes: int,
        output_bytes: int,
        intermediate_bytes: int,
        wall_s: float,
        task_id: str = "",
    ) -> Dict:
        total = (
            int(input_bytes) + int(output_bytes) + int(intermediate_bytes)
        )
        with self._lock:
            e = self._entries.get(digest)
            if e is None:
                e = self._entries[digest] = {
                    "kernel": digest,
                    "mode": mode,
                    "taskId": task_id,
                    "executions": 0,
                    "inputBytes": 0,
                    "outputBytes": 0,
                    "intermediateBytes": 0,
                    "totalBytes": 0,
                    "deviceWallS": 0.0,
                }
            e["executions"] += 1
            e["inputBytes"] += int(input_bytes)
            e["outputBytes"] += int(output_bytes)
            e["intermediateBytes"] += int(intermediate_bytes)
            e["totalBytes"] += total
            e["deviceWallS"] += float(wall_s)
        REGISTRY.histogram(
            "trino_tpu_kernel_bandwidth_bytes",
            "Bytes touched (input+output+intermediate) per supervised "
            "dispatch under the bandwidth ledger",
            buckets=BYTES_BUCKETS,
        ).observe(total)
        REGISTRY.histogram(
            "trino_tpu_kernel_bandwidth_seconds",
            "Timed device wall (block_until_ready bracketing) per "
            "supervised dispatch under the bandwidth ledger",
        ).observe(wall_s)
        return e

    def _annotate(self, e: Dict) -> Dict:
        wall = e["deviceWallS"]
        gbps = (e["totalBytes"] / wall / 1e9) if wall > 0 else 0.0
        out = dict(e)
        out["gbps"] = gbps
        out["rooflinePct"] = (
            100.0 * gbps * 1e9 / self.roofline_bytes_per_s
        )
        return out

    def entries(self) -> List[Dict]:
        """Per-kernel rows, heaviest byte movers first."""
        with self._lock:
            entries = [dict(e) for e in self._entries.values()]
        return sorted(
            (self._annotate(e) for e in entries),
            key=lambda e: e["totalBytes"],
            reverse=True,
        )

    def top(self, n: int) -> List[Dict]:
        return self.entries()[:n]

    def summary(self) -> Dict:
        with self._lock:
            total = sum(e["totalBytes"] for e in self._entries.values())
            wall = sum(e["deviceWallS"] for e in self._entries.values())
        gbps = (total / wall / 1e9) if wall > 0 else 0.0
        return {
            "totalBytes": total,
            "deviceWallS": wall,
            "exchangeBytes": self.exchange_bytes,
            "effectiveGbps": gbps,
            "rooflinePct": 100.0 * gbps * 1e9 / self.roofline_bytes_per_s,
            "rooflineGbps": self.roofline_bytes_per_s / 1e9,
        }
