"""Crash-safe persisted query history store.

Replaces the per-session in-memory ``session.query_history`` list: every
query's lifecycle (RUNNING -> FINISHED/FAILED, final stats + operator
timeline summary) is appended as one JSONL record to two preallocated
mmap'd segment files — the same torn-tail-tolerant reader shape as the
flight recorder (``obs/flight_recorder.py``), so a ``kill -9`` loses at
most the record being written.  Unlike the flight recorder, segments are
NOT reset on open: surviving records are re-read so completed-query
history outlives a coordinator restart and stays SQL-queryable via
``system.runtime.completed_queries``.

The store is bounded by *bytes*, not record count: the two segments
alternate at half the byte budget each, and the in-memory mirror evicts
oldest finished queries past the same budget.  Sessions without a
``query_history_dir`` share one process-global memory-only store, so
``system.runtime.queries`` sees queries from all sessions either way.
"""
from __future__ import annotations

import glob
import json
import mmap
import os
import threading
import time
from typing import Dict, List, Optional

# wire-document field names (lowerCamelCase, one naming regime with
# metrics/spans/flight-recorder records) — linted by
# scripts/check_metric_names.py against this tuple
HISTORY_FIELDS = (
    "queryId",
    "state",
    "sql",
    "user",
    "created",
    "finished",
    "rows",
    "wallS",
    "error",
    "errorCode",
    "tenant",
    "planSignature",
    "operators",
    "ts",
)

DEFAULT_MAX_BYTES = 1 << 20  # two 512 KiB segments

# one history record never exceeds this; an oversized operator timeline
# is dropped from the record rather than wedging the segment
MAX_RECORD_BYTES = 16384

MIN_SEGMENT_BYTES = 1 << 16

_FILE_PREFIX = "qh-"


class _Segment:
    """One preallocated mmap'd JSONL file; re-opens append at the end of
    the surviving records instead of zeroing them (restart survival)."""

    def __init__(self, path: str, size: int):
        self.path = path
        self.size = size
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            os.ftruncate(fd, size)
            self.mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self.offset = 0
        self.records = 0
        self.last_ts = 0.0

    def load(self) -> List[Dict]:
        """Parse surviving records and position the append offset after
        the last intact line (a torn trailing line is overwritten)."""
        out: List[Dict] = []
        data = self.mm[: self.size]
        pos = 0
        for line in data.split(b"\n"):
            raw = line.strip(b"\0").strip()
            if raw:
                try:
                    rec = json.loads(raw)
                except ValueError:
                    break  # torn write: stop; append resumes here
                if isinstance(rec, dict) and "queryId" in rec:
                    out.append(rec)
                    self.records += 1
                    self.last_ts = max(
                        self.last_ts, float(rec.get("ts") or 0.0)
                    )
                    pos += len(line) + 1
                    continue
            break
        self.offset = pos
        return out

    def reset(self):
        self.mm[: self.size] = b"\0" * self.size
        self.offset = 0
        self.records = 0
        self.last_ts = 0.0

    def append(self, data: bytes) -> bool:
        if self.offset + len(data) > self.size:
            return False
        self.mm[self.offset : self.offset + len(data)] = data
        self.offset += len(data)
        self.records += 1
        return True

    def close(self):
        try:
            self.mm.close()
        except Exception:  # noqa: BLE001
            pass


class QueryHistoryStore:
    """Byte-bounded, crash-safe query history (latest record per query).

    ``directory=None`` keeps history memory-only; with a directory the
    last ``max_bytes`` of records survive process death and are merged
    back on the next open (including records other processes left in the
    same directory)."""

    def __init__(
        self,
        directory: Optional[str] = None,
        max_bytes: int = DEFAULT_MAX_BYTES,
        name: str = "qh",
    ):
        self.directory = directory or None
        self.max_bytes = max(int(max_bytes), 2 * MIN_SEGMENT_BYTES)
        self.name = name
        self._lock = threading.Lock()
        # queryId -> latest record, insertion-ordered for byte eviction
        self._entries: Dict[str, Dict] = {}
        self._bytes = 0
        self._segments: List[_Segment] = []
        self._active = 0
        if self.directory:
            os.makedirs(self.directory, exist_ok=True)
            seg_bytes = max(MIN_SEGMENT_BYTES, self.max_bytes // 2)
            own = set()
            for i in range(2):
                path = os.path.join(
                    self.directory, f"{_FILE_PREFIX}{self.name}-{i}.jsonl"
                )
                own.add(os.path.abspath(path))
                self._segments.append(_Segment(path, seg_bytes))
            # survivors from OTHER writers (old pids, sibling sessions)
            # merge into the mirror but are never appended to
            for rec in read_history_dir(self.directory, exclude=own):
                self._absorb(rec)
            for seg in self._segments:
                for rec in seg.load():
                    self._absorb(rec)
            self._active = max(
                range(2), key=lambda i: self._segments[i].last_ts
            )

    # -- record plumbing ------------------------------------------------
    def _absorb(self, rec: Dict):
        qid = str(rec.get("queryId") or "")
        if not qid:
            return
        prev = self._entries.pop(qid, None)
        if prev is not None:
            self._bytes -= prev.get("_approxBytes", 0)
        rec["_approxBytes"] = len(
            json.dumps(rec, separators=(",", ":"), default=str)
        )
        self._entries[qid] = rec
        self._bytes += rec["_approxBytes"]
        while self._bytes > self.max_bytes and len(self._entries) > 1:
            oldest_qid = next(iter(self._entries))
            old = self._entries.pop(oldest_qid)
            self._bytes -= old.get("_approxBytes", 0)

    def put(self, entry: Dict):
        """Record (or update) one query's history entry.  ``entry`` uses
        the legacy session keys (query_id/sql/state/...); the persisted
        record is the lowerCamelCase wire shape."""
        rec = {
            "queryId": str(entry.get("query_id") or entry.get("queryId")),
            "state": entry.get("state", ""),
            "sql": str(entry.get("sql", ""))[:2000],
            "user": entry.get("user") or "user",
            "created": float(entry.get("created") or 0.0),
            "finished": entry.get("finished"),
            "rows": int(entry.get("rows") or 0),
            "wallS": float(entry.get("wall_s") or entry.get("wallS") or 0.0),
            "error": entry.get("error"),
            "errorCode": (
                entry.get("error_code") or entry.get("errorCode") or ""
            ),
            # round 19: survive finalize so the serving observatory's
            # census backfill at boot works from disk alone
            "tenant": str(entry.get("tenant") or ""),
            "planSignature": str(
                entry.get("plan_signature")
                or entry.get("planSignature")
                or ""
            ),
            "operators": entry.get("operators"),
            "ts": time.time(),
        }
        with self._lock:
            self._absorb(dict(rec))
            if not self._segments:
                return
            data = _encode(rec)
            if data is None:
                return
            seg = self._segments[self._active]
            if not seg.append(data):
                self._active = 1 - self._active
                seg = self._segments[self._active]
                seg.reset()
                seg.append(data)

    def entries(self) -> List[Dict]:
        """Latest record per query, oldest created first."""
        with self._lock:
            recs = [
                {k: v for k, v in r.items() if k != "_approxBytes"}
                for r in self._entries.values()
            ]
        recs.sort(key=lambda r: (r.get("created") or 0.0, r.get("queryId")))
        return recs

    def completed(self) -> List[Dict]:
        return [
            r for r in self.entries()
            if r.get("state") in ("FINISHED", "FAILED")
        ]

    def total_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def close(self):
        with self._lock:
            for seg in self._segments:
                seg.close()
            self._segments = []


def _encode(rec: Dict) -> Optional[bytes]:
    data = json.dumps(rec, separators=(",", ":"), default=str).encode()
    data += b"\n"
    if len(data) > MAX_RECORD_BYTES:
        rec = dict(rec, operators=None, sql=str(rec.get("sql", ""))[:200])
        data = json.dumps(rec, separators=(",", ":"), default=str).encode()
        data += b"\n"
        if len(data) > MAX_RECORD_BYTES:
            return None  # pathological; drop rather than corrupt
    return data


def read_history_dir(
    directory: str, exclude: Optional[set] = None
) -> List[Dict]:
    """Offline reader: every surviving record in ``directory`` ordered by
    ts.  Torn trailing lines and zeroed tail space are skipped, never an
    error — the ``kill -9`` contract shared with the flight recorder."""
    records: List[Dict] = []
    for path in sorted(
        glob.glob(os.path.join(directory, _FILE_PREFIX + "*.jsonl"))
    ):
        if exclude and os.path.abspath(path) in exclude:
            continue
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            continue
        for line in data.split(b"\n"):
            line = line.strip(b"\0").strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn write
            if isinstance(rec, dict) and "queryId" in rec:
                records.append(rec)
    records.sort(key=lambda r: (r.get("ts") or 0.0, r.get("queryId", "")))
    return records


# -- shared store registry ----------------------------------------------
# one store per history directory (sessions sharing a dir share the
# store), plus one process-global memory-only store for sessions that
# set no query_history_dir — that is what makes system.runtime.queries
# show queries from ALL sessions instead of the caller's own list.
_STORES_LOCK = threading.Lock()
_STORES: Dict[str, QueryHistoryStore] = {}


def get_store(
    directory: Optional[str] = None,
    max_bytes: int = DEFAULT_MAX_BYTES,
) -> QueryHistoryStore:
    key = os.path.abspath(directory) if directory else ""
    with _STORES_LOCK:
        store = _STORES.get(key)
        if store is None:
            store = QueryHistoryStore(
                directory=directory or None, max_bytes=max_bytes
            )
            _STORES[key] = store
        return store


def _reset_stores():
    """Test hook: drop cached stores (e.g. between tmpdir reuses)."""
    with _STORES_LOCK:
        for s in _STORES.values():
            s.close()
        _STORES.clear()
