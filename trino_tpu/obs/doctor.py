"""The query doctor: ranked cross-subsystem root-cause verdicts.

At query finalize (and on demand for crashed queries via the persisted
journal segments) the doctor correlates the incident journal
(:mod:`.journal`) with the flight recorder, the HBM bandwidth ledger,
the per-operator timeline, and the query history into one deterministic
causal verdict:

    ROOT_CAUSE: device_fault — device_loss on node-2/devgen:lineitem
    -> quarantine -> CPU degraded re-run [events 3,4,7]

Rule evaluation is an ordered table with explicit precedence — fault >
kill > node-churn > memory pressure > corruption heals >
straggler/hedge > fusion misses > estimate-drift (the last per Leis et al.,
*How Good Are Query Optimizers, Really?*: estimated-vs-observed rows
from the operator timeline) — so the same evidence always produces the
same ranking.  Every verdict cites the concrete event ids it derived
from, and a query with no anomalies gets an explicit ``HEALTHY``
verdict so absence of diagnosis is itself a signal.

Surfaces: a "Diagnosis" section in EXPLAIN ANALYZE, the
``system.runtime.diagnoses`` table, ``GET /v1/query/{id}/diagnosis``,
and ``scripts/doctor.py <query_id|--last-crash>`` for post-mortem use
after kill -9 (reconstruction from on-disk segments alone).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

from . import journal as J

# wire schema for one diagnosis document (system.runtime.diagnoses /
# /v1/query/{id}/diagnosis), linted by scripts/check_metric_names.py
DIAGNOSIS_FIELDS = (
    "queryId",
    "verdict",
    "rootCause",
    "summary",
    "findings",
    "eventIds",
    "wallS",
    "error",
    "errorCode",
    "ts",
)

HEALTHY = "HEALTHY"
ROOT_CAUSE = "ROOT_CAUSE"

# estimated-vs-observed row ratio above which an operator counts as
# estimate-drifted (Leis et al. report q-errors of 10^2..10^4 for real
# optimizers; 4x keeps ordinary stats noise out of the verdict)
ESTIMATE_DRIFT_RATIO = 4.0


# -- structured error codes (satellite: history post-mortem surface) -----


def classify_error(error) -> str:
    """Map an error (exception or rendered text) to a structured code.

    The coordinator renders errors as ``TypeName: message``, so type
    names are part of the searchable text."""
    if error is None:
        return ""
    text = str(error)
    if not text:
        return ""
    checks = (
        ("NO_NODES_AVAILABLE", "NO_NODES_AVAILABLE"),
        ("QUERY_QUEUE_FULL", "QUERY_QUEUE_FULL"),
        ("QueryKilledError", "QUERY_KILLED"),
        ("Query killed", "QUERY_KILLED"),
        ("device_wedge", "DEVICE_WEDGE"),
        ("device_loss", "DEVICE_LOSS"),
        ("DeviceFaultError", "DEVICE_FAULT"),
        ("REMOTE_HOST_GONE", "REMOTE_HOST_GONE"),
        ("COORDINATOR_RESTART", "COORDINATOR_RESTART"),
        ("ADMISSION_TIMEOUT", "ADMISSION_TIMEOUT"),
        ("shed after", "ADMISSION_TIMEOUT"),
        ("admission queue", "ADMISSION_TIMEOUT"),
        ("ExceededMemoryLimit", "EXCEEDED_MEMORY_LIMIT"),
        ("memory limit", "EXCEEDED_MEMORY_LIMIT"),
        ("PageIntegrityError", "PAGE_CORRUPTION"),
        ("SchedulerError", "SCHEDULER_ERROR"),
    )
    for needle, code in checks:
        if needle in text:
            return code
    return "INTERNAL_ERROR"


# -- evidence helpers ----------------------------------------------------


def _events_of(ctx: Dict, *types, sites: Optional[tuple] = None) -> List[Dict]:
    out = []
    for e in ctx.get("events") or []:
        if e.get("eventType") not in types:
            continue
        if sites is not None and e.get("eventType") == J.FAULT_INJECTED:
            if (e.get("detail") or {}).get("site") not in sites:
                continue
        out.append(e)
    return out


def _ids(events: List[Dict]) -> List[int]:
    return [int(e.get("eventId", 0)) for e in events]


def _finding(code: str, severity: str, summary: str,
             events: List[Dict]) -> Dict:
    return {
        "code": code,
        "severity": severity,
        "summary": summary,
        "eventIds": _ids(events),
    }


# -- the ordered rule table ----------------------------------------------


def _rule_device_fault(ctx) -> Optional[Dict]:
    faults = _events_of(ctx, J.DEVICE_FAULT)
    injected = _events_of(ctx, J.FAULT_INJECTED,
                          sites=("device_loss", "device_wedge"))
    if not faults and not injected:
        return None
    transitions = _events_of(ctx, J.DEVICE_QUARANTINE, J.DEVICE_BLACKLIST)
    fallbacks = _events_of(ctx, J.CPU_FALLBACK)
    first = faults[0] if faults else injected[0]
    detail = first.get("detail") or {}
    kind = detail.get("kind") or detail.get("site") or "device_fault"
    where = first.get("nodeId") or "local"
    kernel = detail.get("kernel") or ""
    summary = f"{kind} on {where}" + (f"/{kernel}" if kernel else "")
    if any(e.get("eventType") == J.DEVICE_BLACKLIST for e in transitions):
        summary += " -> blacklist"
    elif transitions:
        summary += " -> quarantine"
    if fallbacks:
        summary += " -> CPU degraded re-run"
    return _finding("device_fault", J.ERROR, summary,
                    faults + injected + transitions + fallbacks)


def _rule_memory_kill(ctx) -> Optional[Dict]:
    kills = _events_of(ctx, J.MEMORY_KILL)
    if not kills and ctx.get("errorCode") != "QUERY_KILLED":
        return None
    reason = ""
    if kills:
        reason = (kills[0].get("detail") or {}).get("reason", "")
    summary = "query killed by the memory killer"
    if reason:
        summary += f" ({reason[:120]})"
    revokes = _events_of(ctx, J.MEMORY_REVOKE)
    if revokes:
        summary += " after revoke cascade"
    return _finding("memory_kill", J.ERROR, summary, kills + revokes)


def _rule_host_gone(ctx) -> Optional[Dict]:
    """A host-sized capacity unit (a process owning a whole slice of the
    global device mesh) went GONE.  Ranked ABOVE plain node churn: the
    same death also fires NODE_GONE, but losing a host takes out every
    device in its slice plus its local spools at once — the host loss is
    the cause, the node transition its per-node shadow."""
    gone = _events_of(ctx, J.HOST_GONE)
    if not gone:
        return None
    reassigned = _events_of(ctx, J.FTE_REASSIGN)
    hosts = sorted({
        (e.get("detail") or {}).get("host") or e.get("nodeId") or "?"
        for e in gone
    })
    devices = sum(
        int((e.get("detail") or {}).get("localDevices") or 0) for e in gone
    )
    summary = (
        f"host loss: {','.join(hosts)} "
        f"({devices} local device(s)) left the cluster"
    )
    if reassigned:
        summary += f" -> {len(reassigned)} task attempt(s) reassigned"
    if ctx.get("errorCode") == "NO_NODES_AVAILABLE":
        summary += " -> no schedulable nodes left"
    return _finding("host_gone", J.ERROR if ctx.get("error") else J.WARN,
                    summary, gone + reassigned)


def _rule_node_churn(ctx) -> Optional[Dict]:
    # FTE_REASSIGN alone is a recovery *mechanism*, not churn evidence —
    # spool heals reassign too.  The rule needs an actual node signal.
    gone = _events_of(ctx, J.NODE_GONE, J.NODE_SUSPECT)
    deaths = _events_of(ctx, J.FAULT_INJECTED, sites=("worker_death",))
    if not gone and not deaths \
            and ctx.get("errorCode") not in ("NO_NODES_AVAILABLE",
                                             "REMOTE_HOST_GONE"):
        return None
    reassigned = _events_of(ctx, J.FTE_REASSIGN)
    churn = gone + reassigned
    nodes = sorted({e.get("nodeId") for e in gone + deaths
                    if e.get("nodeId")})
    summary = "worker death" if deaths else "node churn"
    if nodes:
        summary += f" on {','.join(nodes)}"
    elif gone:
        summary += " (node GONE mid-query)"
    if reassigned:
        summary += f" -> {len(reassigned)} task attempt(s) reassigned"
    if ctx.get("errorCode") == "NO_NODES_AVAILABLE":
        summary += " -> no schedulable nodes left"
    return _finding("node_churn", J.ERROR if ctx.get("error") else J.WARN,
                    summary, deaths + churn)


def _rule_snapshot_conflict(ctx) -> Optional[Dict]:
    """A lakehouse commit lost the metadata-pointer CAS to a concurrent
    writer and retried (optimistic concurrency doing its job), possibly
    under injected object-store faults.  WARN when the query still
    succeeded — the retry loop absorbed the race — ERROR only when the
    retry budget was exhausted and the query failed."""
    conflicts = _events_of(ctx, J.SNAPSHOT_CONFLICT)
    faults = _events_of(
        ctx, J.FAULT_INJECTED,
        sites=("objstore_error", "objstore_throttle", "objstore_latency"),
    )
    if not conflicts:
        return None
    tables = sorted({
        (e.get("detail") or {}).get("table") or "?" for e in conflicts
    })
    summary = (
        f"snapshot commit lost the metadata CAS {len(conflicts)} time(s) "
        f"on {','.join(tables)} -> re-read winner and retried"
    )
    if faults:
        summary += f" (under {len(faults)} injected object-store fault(s))"
    sev = J.ERROR if ctx.get("error") else J.WARN
    return _finding("snapshot_conflict", sev, summary, conflicts + faults)


def _rule_coordinator_restart(ctx) -> Optional[Dict]:
    """The coordinator itself died and came back: the query was either
    resumed from WAL-recorded committed spools (QUERY_RESUMED) or
    orphaned with the structured retryable error (QUERY_ORPHANED).
    Ranked below node churn — a dead WORKER loses spools and running
    tasks, while a dead coordinator loses only in-memory bookkeeping
    that the WAL reconstructs — and above mesh shrink."""
    restarts = _events_of(ctx, J.COORDINATOR_RESTART)
    resumed = _events_of(ctx, J.QUERY_RESUMED)
    orphaned = _events_of(ctx, J.QUERY_ORPHANED)
    deaths = _events_of(ctx, J.FAULT_INJECTED, sites=("coordinator_death",))
    if not (restarts or resumed or orphaned or deaths) \
            and ctx.get("errorCode") != "COORDINATOR_RESTART":
        return None
    parts = ["coordinator restarted mid-query"]
    if resumed:
        spools = sum(
            int((e.get("detail") or {}).get("reusedSpools") or 0)
            for e in resumed
        )
        parts.append(
            "resumed from the WAL"
            + (f" reusing {spools} committed spool(s)" if spools else "")
        )
    if orphaned or ctx.get("errorCode") == "COORDINATOR_RESTART":
        parts.append(
            "pipelined stream state lost -> orphaned with retryable "
            "COORDINATOR_RESTART (client re-submits)"
        )
    summary = " -> ".join(parts)
    sev = J.ERROR if ctx.get("error") else J.WARN
    return _finding("coordinator_restart", sev, summary,
                    deaths + restarts + resumed + orphaned)


def _rule_mesh_shrink(ctx) -> Optional[Dict]:
    """A quarantined device dropped out of the SPMD mesh and the query
    finished on the healthy subset — slower (fewer shards), but a
    query-level non-event.  Below node churn (a dead WORKER loses
    spools and tasks; a shrunk mesh loses only parallelism), above
    memory pressure (the shrink halves per-shard headroom, so it often
    *causes* the memory symptoms)."""
    shrinks = _events_of(ctx, J.MESH_SHRINK)
    if not shrinks:
        return None
    devs = sorted({
        str((e.get("detail") or {}).get("deviceId"))
        for e in shrinks
        if (e.get("detail") or {}).get("deviceId") is not None
    })
    last = shrinks[-1].get("detail") or {}
    summary = "mesh shrank to the healthy subset"
    if devs:
        summary = (
            "device(s) %s dropped out of the mesh" % ",".join(devs)
        )
    if last.get("fromSize") and last.get("toSize"):
        summary += " (%s -> %s shards)" % (
            last["fromSize"], last["toSize"]
        )
    return _finding("mesh_shrink", J.WARN, summary, shrinks)


def _rule_overload(ctx) -> Optional[Dict]:
    """The cluster shed load or scaled under offered-load pressure.
    Below node churn (a dead worker is a fault, not demand) and above
    memory pressure (an overloaded cluster's admission queue backs up,
    so overload routinely *causes* the memory-pressure symptoms)."""
    sheds = _events_of(ctx, J.QUERY_SHED)
    timeouts = _events_of(ctx, J.QUEUE_TIMEOUT)
    rescues = _events_of(ctx, J.STARVATION_AVERTED)
    scales = _events_of(ctx, J.SCALE_OUT, J.SCALE_IN)
    if not (sheds or timeouts or rescues) \
            and ctx.get("errorCode") not in ("QUERY_QUEUE_FULL",
                                             "ADMISSION_TIMEOUT"):
        return None
    parts = []
    if sheds:
        group = (sheds[0].get("detail") or {}).get("group", "")
        parts.append(
            f"{len(sheds)} query(s) shed past the queue deadline"
            + (f" in group {group}" if group else "")
        )
    if timeouts:
        parts.append(
            f"{len(timeouts)} admission wait(s) timed out"
        )
    if rescues:
        parts.append(
            f"{len(rescues)} aged query(s) rescued by fair-share "
            "arbitration"
        )
    if not parts:
        parts.append(
            "rejected at submit (queue full)"
            if ctx.get("errorCode") == "QUERY_QUEUE_FULL"
            else "timed out waiting for admission"
        )
    outs = sum(1 for e in scales if e.get("eventType") == J.SCALE_OUT)
    ins = len(scales) - outs
    if outs:
        parts.append(f"autoscaler added {outs} worker(s)")
    if ins:
        parts.append(f"autoscaler drained {ins} worker(s)")
    summary = "overload: " + ", ".join(parts)
    sev = J.ERROR if ctx.get("error") else J.WARN
    return _finding("overload", sev, summary,
                    sheds + timeouts + rescues + scales)


def _rule_slo_burn(ctx) -> Optional[Dict]:
    """A tenant burned its SLO error budget past the fast-window
    threshold while this query ran.  Ranked directly below overload:
    shed/queue pressure is usually *why* the budget burns, so when both
    fire the overload verdict stays the root cause and the burn rides
    along as a secondary finding."""
    burns = _events_of(ctx, J.SLO_BURN)
    if not burns:
        return None
    worst = max(
        burns,
        key=lambda e: float((e.get("detail") or {}).get("burnRate") or 0.0),
    )
    d = worst.get("detail") or {}
    summary = (
        "slo burn: tenant %s burning its error budget at %.1fx over the "
        "%.0fs fast window (target %.2fs, budget %.0f%%)" % (
            d.get("tenant") or "?",
            float(d.get("burnRate") or 0.0),
            float(d.get("windowS") or 0.0),
            float(d.get("latencyTargetS") or 0.0),
            float(d.get("errorBudget") or 0.0) * 100.0,
        )
    )
    return _finding("slo_burn", J.WARN, summary, burns)


def _rule_memory_pressure(ctx) -> Optional[Dict]:
    oom = _events_of(ctx, J.FAULT_INJECTED, sites=("oom",))
    revokes = _events_of(ctx, J.MEMORY_REVOKE)
    blocks = _events_of(ctx, J.ADMISSION_BLOCK)
    streamed = _events_of(ctx, J.FORCED_STREAMING)
    if not (oom or revokes or blocks or streamed) \
            and ctx.get("errorCode") not in ("EXCEEDED_MEMORY_LIMIT",
                                             "ADMISSION_TIMEOUT"):
        return None
    parts = []
    if oom:
        parts.append("oom at reservation")
    if revokes:
        parts.append(f"{len(revokes)} revoke(s)")
    if blocks:
        parts.append("blocked in admission queue")
    if streamed:
        parts.append("fell back to tiled streaming")
    if not parts:
        parts.append("memory limit exceeded")
    summary = "memory pressure: " + ", ".join(parts)
    sev = J.ERROR if ctx.get("error") else J.WARN
    return _finding("memory_pressure", sev, summary,
                    oom + revokes + blocks + streamed)


def _rule_retrace_storm(ctx) -> Optional[Dict]:
    """A burst of shape-miss recompiles (compile observatory sliding
    window) put many-millisecond XLA compiles on this query's path —
    the Tail-at-Scale rare-event p99 signature.  Ranked below memory
    pressure: an engine under memory churn re-traces as a *symptom*
    (evictions, capacity retreats), so pressure wins when both fire."""
    storms = _events_of(ctx, J.RETRACE_STORM)
    if not storms:
        return None
    misses = max(
        int((e.get("detail") or {}).get("misses") or 0) for e in storms
    )
    window = (storms[-1].get("detail") or {}).get("windowS")
    summary = (
        f"retrace storm: {misses} shape-miss compile(s) inside a "
        f"{window}s window — padding buckets do not fit this traffic "
        "shape (see system.runtime.shape_census / scripts/bucket_ladder.py)"
    )
    return _finding("retrace_storm", J.WARN, summary, storms)


def _rule_straggler(ctx) -> Optional[Dict]:
    flags = _events_of(ctx, J.STRAGGLER_FLAG)
    hedges = _events_of(ctx, J.HEDGE)
    if not flags and not hedges:
        return None
    parts = []
    if flags:
        worst = max(flags, key=lambda e: float(
            (e.get("detail") or {}).get("wallS", 0.0) or 0.0))
        d = worst.get("detail") or {}
        parts.append(
            "task %s straggled (%.2fs vs %.2fs median)"
            % (worst.get("taskId") or d.get("task", "?"),
               float(d.get("wallS", 0.0) or 0.0),
               float(d.get("medianS", 0.0) or 0.0))
        )
    if hedges:
        parts.append(f"{len(hedges)} hedge(s) dispatched")
    return _finding("straggler", J.WARN, "; ".join(parts), flags + hedges)


def _rule_spool_corruption(ctx) -> Optional[Dict]:
    heals = _events_of(ctx, J.SPOOL_HEAL)
    injected = _events_of(ctx, J.FAULT_INJECTED,
                          sites=("spool_write_corrupt", "spool_read"))
    if not heals and not injected:
        return None
    summary = "spool corruption"
    if heals:
        d = heals[0].get("detail") or {}
        frag = d.get("fragment")
        summary += (
            f" on fragment {frag}" if frag is not None else ""
        ) + " -> producer re-run, attempt healed"
    return _finding("spool_corruption", J.WARN, summary, injected + heals)


def _rule_cache_heal(ctx) -> Optional[Dict]:
    heals = _events_of(ctx, J.CACHE_HEAL)
    injected = _events_of(ctx, J.FAULT_INJECTED, sites=("cache_read",))
    if not heals and not injected:
        return None
    return _finding(
        "cache_corruption", J.WARN,
        f"{len(heals) or len(injected)} corrupt spilled cache "
        "frame(s) detected and healed (recomputed)",
        injected + heals,
    )


def _rule_fusion_reject(ctx) -> Optional[Dict]:
    rejects = _events_of(ctx, J.FUSION_REJECT)
    prof = ctx.get("profile") or {}
    if not rejects and not prof.get("fusionRejects"):
        return None
    reason = ""
    if rejects:
        reason = (rejects[0].get("detail") or {}).get("reason", "")
    elif prof.get("lastFusionReject"):
        reason = str(prof["lastFusionReject"])
    summary = "megakernel fusion rejected -> unfused path"
    if reason:
        summary += f" ({reason[:120]})"
    return _finding("fusion_reject", J.INFO, summary, rejects)


def _rule_estimate_drift(ctx) -> Optional[Dict]:
    injected = _events_of(ctx, J.FAULT_INJECTED, sites=("stats_estimate",))
    drifted = []
    for frame in (ctx.get("timeline") or {}).get("operators") or []:
        est = float(frame.get("estimatedRows", 0.0) or 0.0)
        obs = float(frame.get("outputRows", 0.0) or 0.0)
        if est <= 0 or obs <= 0:
            continue
        ratio = max(est / obs, obs / est)
        if ratio >= ESTIMATE_DRIFT_RATIO:
            drifted.append((ratio, frame))
    if not injected and not drifted:
        return None
    if drifted:
        ratio, frame = max(drifted, key=lambda p: p[0])
        summary = (
            "estimate drift: %s estimated %.0f rows, observed %.0f "
            "(%.1fx off)" % (
                frame.get("operator", "?"),
                float(frame.get("estimatedRows", 0.0) or 0.0),
                float(frame.get("outputRows", 0.0) or 0.0),
                ratio,
            )
        )
    else:
        d = injected[0].get("detail") or {}
        summary = (
            "estimate drift: seeded stats_estimate skewed %s"
            % (d.get("key") or "a fragment estimate")
        )
    return _finding("estimate_drift", J.INFO, summary, injected)


# precedence is the tentpole's mandated order: first hit is the root
# cause; later hits still appear as secondary findings
_RULES = (
    _rule_device_fault,
    _rule_memory_kill,
    # host loss directly above node churn: a GONE host also fires
    # NODE_GONE for its node, but the host verdict carries the real
    # blast radius (a whole device slice and its spools)
    _rule_host_gone,
    _rule_node_churn,
    # coordinator restart below node churn (a dead worker loses spools
    # and tasks; a dead coordinator loses only bookkeeping the WAL
    # reconstructs), above mesh shrink
    _rule_coordinator_restart,
    # snapshot conflicts below coordinator restart (a lost CAS is
    # absorbed by the commit retry loop; it only explains latency or, on
    # budget exhaustion, the failure) and above mesh shrink
    _rule_snapshot_conflict,
    _rule_mesh_shrink,
    # overload below node churn (a dead worker is a fault, not demand),
    # above memory pressure (a backed-up admission queue is usually the
    # overload's symptom, not an independent cause)
    _rule_overload,
    # slo burn directly below overload: shed/queue pressure is usually
    # why a tenant's budget burns, so when both fire the overload
    # verdict stays the root cause and the burn is secondary
    _rule_slo_burn,
    _rule_memory_pressure,
    # retrace storms directly below memory pressure: recompile bursts
    # under memory churn are usually the pressure's symptom (capacity
    # retreats re-trace), so the pressure verdict must outrank them
    _rule_retrace_storm,
    # corruption heals before straggler/hedge: a healed producer re-run
    # is slow, so corruption routinely *causes* a straggler flag — the
    # flag is the symptom, the corrupt frame is the cause
    _rule_spool_corruption,
    _rule_cache_heal,
    _rule_straggler,
    _rule_fusion_reject,
    _rule_estimate_drift,
)


# -- diagnosis -----------------------------------------------------------


def diagnose(
    query_id: str,
    events: List[Dict],
    timeline: Optional[Dict] = None,
    profile: Optional[Dict] = None,
    flight_records: Optional[List[Dict]] = None,
    error: Optional[str] = None,
    error_code: Optional[str] = None,
    wall_s: float = 0.0,
) -> Dict:
    """Run the ordered rule table over the evidence for one query.

    ``events`` should already be scoped to the query (see
    :func:`events_for_query` for the scoping policy)."""
    ctx = {
        "queryId": query_id,
        "events": events or [],
        "timeline": timeline,
        "profile": profile,
        "flight_records": flight_records,
        "error": str(error) if error else "",
        "errorCode": error_code if error_code is not None
        else classify_error(error),
    }
    findings = []
    for rule in _RULES:
        try:
            f = rule(ctx)
        except Exception:  # noqa: BLE001 — a broken rule must not mask
            continue       # the others (diagnosis is best-effort)
        if f is not None:
            findings.append(f)
    if findings:
        top = findings[0]
        verdict, root, summary = ROOT_CAUSE, top["code"], top["summary"]
        event_ids = top["eventIds"]
    else:
        verdict, root = HEALTHY, ""
        summary = "no anomalous events correlated with this query"
        event_ids = []
    diagnosis = {
        "queryId": query_id,
        "verdict": verdict,
        "rootCause": root,
        "summary": summary,
        "findings": findings,
        "eventIds": event_ids,
        "wallS": float(wall_s or 0.0),
        "error": ctx["error"],
        "errorCode": ctx["errorCode"],
        "ts": time.time(),
    }
    from ..utils.metrics import REGISTRY

    REGISTRY.counter(
        "trino_tpu_doctor_diagnoses_total",
        "Query-doctor verdicts, by verdict class",
    ).inc(verdict=verdict, cause=root or "none")
    return diagnosis


def events_for_query(
    query_id: str,
    events: Optional[List[Dict]] = None,
    window: Optional[tuple] = None,
    slack_s: float = 1.0,
) -> List[Dict]:
    """Scope journal events to one query: events tagged with its id,
    plus ambient events (no queryId — fault-injector firings, node
    churn) inside the query's wall-clock window.  Ambient attribution
    is a heuristic; concurrent queries can share ambient events."""
    if events is None:
        events = J.get_journal().tail()
    scoped, ambient = [], []
    for e in events:
        if e.get("queryId") == query_id:
            scoped.append(e)
        elif not e.get("queryId"):
            ambient.append(e)
    if window is None and scoped:
        ts = [e.get("ts", 0.0) for e in scoped]
        window = (min(ts), max(ts))
    if window is not None:
        t0, t1 = window
        scoped += [
            e for e in ambient
            if t0 - slack_s <= e.get("ts", 0.0) <= t1 + slack_s
        ]
    scoped.sort(key=lambda e: (e.get("ts", 0.0), e.get("eventId", 0)))
    return scoped


def diagnose_query(
    query_id: str,
    window: Optional[tuple] = None,
    timeline: Optional[Dict] = None,
    profile: Optional[Dict] = None,
    error: Optional[str] = None,
    error_code: Optional[str] = None,
    wall_s: float = 0.0,
) -> Dict:
    """Diagnose against the live process-global journal (query finalize)."""
    events = events_for_query(query_id, window=window)
    return diagnose(
        query_id, events, timeline=timeline, profile=profile,
        error=error, error_code=error_code, wall_s=wall_s,
    )


def diagnose_recent() -> Optional[Dict]:
    """Diagnose the most recent query seen in the global journal — the
    bench's crashed-config attach point, where no query object survives."""
    events = J.get_journal().tail()
    tagged = [e for e in events if e.get("queryId")]
    if not tagged:
        return None
    qid = tagged[-1]["queryId"]
    return diagnose(qid, events_for_query(qid, events=events))


# -- offline reconstruction (kill -9 post-mortem) ------------------------


def find_crashed_query(
    events: List[Dict], history: Optional[List[Dict]] = None
) -> Optional[str]:
    """The newest journal queryId whose history record is absent or not
    FINISHED — with no history at all, simply the newest queryId (the
    crash took the history writer down with it)."""
    finished = {
        h.get("queryId") for h in (history or [])
        if h.get("state") == "FINISHED"
    }
    for e in reversed(events):
        qid = e.get("queryId")
        if qid and qid not in finished:
            return qid
    return None


def diagnose_from_dir(
    journal_dir: str,
    query_id: Optional[str] = None,
    history_dir: Optional[str] = None,
) -> Optional[Dict]:
    """Reconstruct a verdict from persisted segments alone (the process
    that wrote them is gone)."""
    events = J.read_journal_dir(journal_dir)
    if not events:
        return None
    history = None
    error = error_code = None
    if history_dir:
        from .history import read_history_dir

        history = read_history_dir(history_dir)
    if query_id is None:
        query_id = find_crashed_query(events, history)
    if query_id is None:
        return None
    for h in history or []:
        if h.get("queryId") == query_id:
            error = h.get("error") or None
            error_code = h.get("errorCode") or None
    return diagnose(
        query_id, events_for_query(query_id, events=events),
        error=error, error_code=error_code,
    )


# -- rendering -----------------------------------------------------------


def format_diagnosis(diagnosis: Optional[Dict]) -> str:
    """The "Diagnosis" section of EXPLAIN ANALYZE / scripts/doctor.py."""
    if not diagnosis:
        return "Diagnosis:\n  (doctor disabled or no evidence)"
    lines = ["Diagnosis:"]
    if diagnosis.get("verdict") == HEALTHY:
        lines.append(f"  HEALTHY — {diagnosis.get('summary', '')}")
        return "\n".join(lines)
    for i, f in enumerate(diagnosis.get("findings") or []):
        label = "ROOT_CAUSE" if i == 0 else "      also"
        cited = ",".join(str(x) for x in f.get("eventIds") or [])
        cite = f" [events {cited}]" if cited else ""
        lines.append(
            f"  {label}: {f.get('code')} — {f.get('summary')}{cite}"
        )
    if diagnosis.get("errorCode"):
        lines.append(
            f"  error: {diagnosis['errorCode']}"
            + (f" — {diagnosis['error'][:160]}"
               if diagnosis.get("error") else "")
        )
    return "\n".join(lines)


# -- process-global diagnosis registry (system.runtime.diagnoses) --------

_DIAG_LOCK = threading.Lock()
_DIAGNOSES: deque = deque(maxlen=256)


def record_diagnosis(diagnosis: Dict):
    with _DIAG_LOCK:
        _DIAGNOSES.append(diagnosis)


def recent_diagnoses() -> List[Dict]:
    with _DIAG_LOCK:
        return list(_DIAGNOSES)


def _reset_diagnoses():
    with _DIAG_LOCK:
        _DIAGNOSES.clear()
