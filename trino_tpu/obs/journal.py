"""Engine-wide incident journal: typed, correlated, crash-safe events.

Every subsystem that bumps an anomaly metric also appends a typed event
here — device faults and quarantine transitions, memory revokes/kills,
admission blocks, cache and spool corruption heals, straggler flags and
hedges, node lifecycle churn, FTE reassignments, fusion rejects,
forced-streaming fallbacks, chaos-harness fault firings — each carrying
the query/task/node ids it happened under, so the query doctor
(:mod:`.doctor`) can join what today lives in five disjoint telemetry
streams.  Dean & Barroso (*The Tail at Scale*) argue the interesting
failures at scale are exactly these cross-component interactions; this
is the engine's single place where they become one narrative.

Storage is the mmap'd torn-tail-tolerant two-segment JSONL shape the
flight recorder proved out (obs/flight_recorder.py): memory-only by
default (a bounded mirror backs ``system.runtime.events``), upgraded to
crash-safe on-disk segments when ``event_journal_dir`` is set.  Segment
file names carry the writing pid, so a restarted process never clobbers
the segments a crashed process left behind — ``scripts/doctor.py
--last-crash`` reconstructs a verdict from those survivors alone.

Emitting is a module-level one-liner because most hook sites (fault
injector, memory pools, the discovery state machine) have no session
reference:

    from ..obs import journal
    journal.emit(journal.MEMORY_KILL, query_id=qid, node_id=self.node_id,
                 reason=reason)
"""
from __future__ import annotations

import glob
import json
import mmap
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

# the one naming regime shared with metrics/spans/flight-recorder wire
# documents: lowerCamelCase, linted by scripts/check_metric_names.py
EVENT_FIELDS = (
    "eventId",
    "eventType",
    "queryId",
    "taskId",
    "nodeId",
    "severity",
    "detail",
    "ts",
)

# -- event types (the typed vocabulary the doctor's rule table keys on) --
DEVICE_FAULT = "device_fault"
DEVICE_QUARANTINE = "device_quarantine"
DEVICE_BLACKLIST = "device_blacklist"
DEVICE_RECOVERED = "device_recovered"
CPU_FALLBACK = "cpu_fallback"
MEMORY_REVOKE = "memory_revoke"
MEMORY_KILL = "memory_kill"
ADMISSION_BLOCK = "admission_block"
CACHE_HEAL = "cache_heal"
SPOOL_HEAL = "spool_heal"
STRAGGLER_FLAG = "straggler_flag"
HEDGE = "hedge"
NODE_SUSPECT = "node_suspect"
NODE_GONE = "node_gone"
# multi-host: a node that announced itself as a host-sized capacity unit
# (a process owning a slice of the global device mesh) went GONE — its
# whole device slice left the cluster at once, distinct from per-node
# NODE_GONE which also fires for the same transition
HOST_GONE = "host_gone"
NODE_REJOIN = "node_rejoin"
NODE_DRAINING = "node_draining"
NODE_DRAINED = "node_drained"
MESH_SHRINK = "mesh_shrink"
FTE_REASSIGN = "fte_reassign"
FUSION_REJECT = "fusion_reject"
FORCED_STREAMING = "forced_streaming"
FAULT_INJECTED = "fault_injected"
QUERY_FAILED = "query_failed"
# compile observatory: sliding-window shape-miss retrace burst
RETRACE_STORM = "retrace_storm"
# multi-tenant serving: overload shedding and elasticity transitions
QUERY_SHED = "query_shed"
QUEUE_TIMEOUT = "queue_timeout"
SCALE_OUT = "scale_out"
SCALE_IN = "scale_in"
STARVATION_AVERTED = "starvation_averted"
# serving observatory: a tenant's fast-window SLO burn rate crossed its
# threshold (throttled: at most one event per fast window per tenant)
SLO_BURN = "slo_burn"
# coordinator crash recovery: restart scan + per-query WAL dispositions
COORDINATOR_RESTART = "coordinator_restart"
QUERY_RESUMED = "query_resumed"
QUERY_ORPHANED = "query_orphaned"
# lakehouse optimistic concurrency: a writer lost the metadata-pointer
# CAS to a concurrent commit and is re-reading + retrying
SNAPSHOT_CONFLICT = "snapshot_conflict"
# lakehouse maintenance: history pruned / unreferenced data reclaimed —
# both commit through the same metadata-pointer CAS as writers, so they
# are safe to run concurrently with appends
SNAPSHOT_EXPIRED = "snapshot_expired"
ORPHANS_REMOVED = "orphans_removed"

# severities
INFO = "info"
WARN = "warn"
ERROR = "error"

DEFAULT_MAX_BYTES = 1 << 20
# one event line never exceeds this; oversized details are truncated
MAX_RECORD_BYTES = 4096
MIN_SEGMENT_BYTES = 1 << 16
_FILE_PREFIX = "ej-"

# event ids are process-monotonic so verdicts can cite them and two
# journal reconfigurations never reuse an id
_ID_LOCK = threading.Lock()
_NEXT_ID = 0


def _new_event_id() -> int:
    global _NEXT_ID
    with _ID_LOCK:
        _NEXT_ID += 1
        return _NEXT_ID


class _Segment:
    """One preallocated mmap'd JSONL file of the on-disk journal."""

    def __init__(self, path: str, size: int):
        self.path = path
        self.size = size
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            os.ftruncate(fd, size)
            self.mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self.offset = 0
        self.records = 0

    def reset(self):
        self.mm[: self.size] = b"\0" * self.size
        self.offset = 0
        self.records = 0

    def append(self, data: bytes) -> bool:
        if self.offset + len(data) > self.size:
            return False
        self.mm[self.offset : self.offset + len(data)] = data
        self.offset += len(data)
        self.records += 1
        return True

    def sync(self):
        try:
            self.mm.flush()
        except Exception:  # noqa: BLE001 — sync is advisory
            pass

    def close(self):
        try:
            self.mm.close()
        except Exception:  # noqa: BLE001
            pass


class EventJournal:
    """Bounded incident-event ring: in-memory mirror + optional mmap'd
    on-disk segments.

    ``directory=None`` keeps the journal memory-only; a directory makes
    the most recent events survive process death.  Segment file names
    include ``name`` (default: the pid), so concurrent/successive
    processes sharing a directory never overwrite each other."""

    def __init__(
        self,
        directory: Optional[str] = None,
        max_bytes: int = DEFAULT_MAX_BYTES,
        name: Optional[str] = None,
        max_events: int = 4096,
    ):
        self.directory = str(directory or "").strip() or None
        self.max_bytes = max(int(max_bytes or DEFAULT_MAX_BYTES),
                             2 * MIN_SEGMENT_BYTES)
        self.name = name or str(os.getpid())
        self._lock = threading.Lock()
        self.mirror: deque = deque(maxlen=max_events)
        self._segments: List[_Segment] = []
        self._active = 0
        if self.directory:
            os.makedirs(self.directory, exist_ok=True)
            seg_bytes = max(MIN_SEGMENT_BYTES, self.max_bytes // 2)
            for i in range(2):
                path = os.path.join(
                    self.directory,
                    f"{_FILE_PREFIX}{self.name}-{i}.jsonl",
                )
                seg = _Segment(path, seg_bytes)
                seg.reset()  # a reused path must not replay stale events
                self._segments.append(seg)

    # -- emit ----------------------------------------------------------
    def emit(
        self,
        event_type: str,
        query_id: str = "",
        task_id: str = "",
        node_id: str = "",
        severity: str = INFO,
        **detail,
    ) -> int:
        """Append one typed event; returns its citable event id."""
        event = {
            "eventId": _new_event_id(),
            "eventType": str(event_type),
            "queryId": str(query_id or ""),
            "taskId": str(task_id or ""),
            "nodeId": str(node_id or ""),
            "severity": str(severity or INFO),
            "detail": detail or {},
            "ts": time.time(),
        }
        self.append(event)
        from ..utils.metrics import REGISTRY

        REGISTRY.counter(
            "trino_tpu_journal_events_total",
            "Incident-journal events appended, by event type",
        ).inc(type=event["eventType"])
        return event["eventId"]

    def append(self, event: Dict):
        data = self._encode(event)
        with self._lock:
            self.mirror.append(event)
            if not self._segments:
                return
            seg = self._segments[self._active]
            if not seg.append(data):
                self._active = 1 - self._active
                seg = self._segments[self._active]
                seg.reset()
                seg.append(data)

    @staticmethod
    def _encode(event: Dict) -> bytes:
        data = json.dumps(event, separators=(",", ":"),
                          default=str).encode() + b"\n"
        if len(data) > MAX_RECORD_BYTES:
            event = dict(event, detail={"truncated": True})
            data = json.dumps(event, separators=(",", ":"),
                              default=str).encode() + b"\n"
        return data

    # -- read ----------------------------------------------------------
    def tail(self, n: Optional[int] = None) -> List[Dict]:
        """Most recent events from the in-memory mirror (oldest first)."""
        with self._lock:
            events = list(self.mirror)
        return events[-n:] if n else events

    def events_for(self, query_id: str) -> List[Dict]:
        return [e for e in self.tail() if e.get("queryId") == query_id]

    # -- durability -----------------------------------------------------
    def sync(self):
        """Flush the mmap'd segments to disk (drain/shutdown path; the
        MAP_SHARED pages are already crash-safe against kill -9 — this
        additionally survives host power loss)."""
        with self._lock:
            for seg in self._segments:
                seg.sync()

    def close(self):
        with self._lock:
            for seg in self._segments:
                seg.close()
            self._segments = []


# -- the process-global journal (most emitters have no session ref) -----

_GLOBAL_LOCK = threading.Lock()
_GLOBAL: Optional[EventJournal] = None


def get_journal() -> EventJournal:
    """The process-global journal (memory-only until configured)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is None:
            _GLOBAL = EventJournal(None)
        return _GLOBAL


def configure(directory, max_bytes=None) -> EventJournal:
    """Upgrade/re-point the global journal (``event_journal_dir``).

    Events already in the memory mirror are replayed into the fresh
    segments, so anomalies that fired before the owning session finished
    constructing are not lost to the post-mortem reader."""
    global _GLOBAL
    directory = str(directory or "").strip() or None
    try:
        max_bytes = int(max_bytes or 0) or DEFAULT_MAX_BYTES
    except (TypeError, ValueError):
        max_bytes = DEFAULT_MAX_BYTES
    with _GLOBAL_LOCK:
        cur = _GLOBAL
        if (
            cur is not None
            and cur.directory == directory
            and (directory is None or cur.max_bytes == max_bytes)
        ):
            return cur
        nxt = EventJournal(directory, max_bytes=max_bytes)
        if cur is not None:
            for event in cur.tail():
                nxt.append(event)
            cur.close()
        _GLOBAL = nxt
        return nxt


def emit(
    event_type: str,
    query_id: str = "",
    task_id: str = "",
    node_id: str = "",
    severity: str = INFO,
    **detail,
) -> int:
    """Module-level one-liner: append to the process-global journal."""
    return get_journal().emit(
        event_type,
        query_id=query_id,
        task_id=task_id,
        node_id=node_id,
        severity=severity,
        **detail,
    )


def sync():
    """Flush the global journal's segments (worker drain walk)."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        j = _GLOBAL
    if j is not None:
        j.sync()


def _reset_journal():
    """Test isolation: drop the process-global journal."""
    global _GLOBAL
    with _GLOBAL_LOCK:
        if _GLOBAL is not None:
            _GLOBAL.close()
        _GLOBAL = None


# -- offline reader (scripts/doctor.py, kill -9 post-mortems) ------------


def read_journal_dir(directory: str) -> List[Dict]:
    """Parse every journal segment in ``directory`` (all writer pids)
    into events ordered by (ts, eventId).  Torn trailing lines (the
    event being written when the process died) and zeroed tail space are
    skipped, never an error."""
    events: List[Dict] = []
    for path in sorted(
        glob.glob(os.path.join(directory, _FILE_PREFIX + "*.jsonl"))
    ):
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            continue
        for line in data.split(b"\n"):
            line = line.strip(b"\0").strip()
            if not line:
                continue
            try:
                event = json.loads(line)
            except ValueError:
                continue  # torn write: the crash interrupted this line
            if isinstance(event, dict) and "eventType" in event:
                events.append(event)
    events.sort(key=lambda e: (e.get("ts", 0.0), e.get("eventId", 0)))
    return events
