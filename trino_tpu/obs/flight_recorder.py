"""Black-box flight recorder for supervised device dispatches.

Every ``DeviceSupervisor.dispatch`` writes a *dispatch* record BEFORE the
thunk runs and a *complete* (or *fault*) record after, so when the TPU
runtime kills the process mid-kernel (the BENCH_r05 failure mode: nothing
but ``UNAVAILABLE: TPU worker process crashed`` in the log) the last N
dispatches — kernel digest, input shapes/dtypes, HBM reservation, the
post-dispatch device-memory watermark, wall time, query/task id — survive
on disk and the unmatched tail names the culprit.

Crash-safety comes from ``mmap``: records are written into two
preallocated MAP_SHARED JSONL segment files, whose dirty pages belong to
the kernel page cache the moment the ``memoryview`` store completes — a
``kill -9`` (or the TPU runtime aborting the process) loses nothing, with
no per-record ``fsync`` on the dispatch hot path.  The ring is bounded:
the two segments alternate, each holding half of
``flight_recorder_max_records``, and rotation zeroes the older segment.

An in-memory mirror (bounded deque) is always on — it backs the
``system.runtime.flight_recorder`` table and bench crash forensics even
when no ``flight_recorder_dir`` is configured.
"""
from __future__ import annotations

import glob
import json
import mmap
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

# the one naming regime shared with metrics and spans: record fields are
# lowerCamelCase (like the Breadcrumb/TaskInfo wire documents), linted by
# scripts/check_metric_names.py against this tuple
RECORD_FIELDS = (
    "recordType",
    "seq",
    "kernel",
    "mode",
    "shapes",
    "queryId",
    "taskId",
    "nodeId",
    "hbmReservedBytes",
    "hbmPeakBytes",
    "wallS",
    "faultKind",
    "error",
    "ts",
)

# a single record line never exceeds this; oversized shape maps are
# dropped rather than letting one dispatch eat the whole segment
MAX_RECORD_BYTES = 4096

# floor for a segment file: even max_records=2 gets page-aligned room
MIN_SEGMENT_BYTES = 1 << 16

_FILE_PREFIX = "fr-"

_WATERMARK_LOCK = threading.Lock()
_WATERMARK_DEVICE = None  # cached jax device (or False when unavailable)


def device_memory_watermark() -> int:
    """Post-dispatch HBM high-water mark in bytes (0 when the backend
    exposes no ``memory_stats`` — the CPU backend, notably).  Never
    initializes jax itself: recording must not force a backend."""
    global _WATERMARK_DEVICE
    import sys

    if "jax" not in sys.modules:
        return 0
    with _WATERMARK_LOCK:
        dev = _WATERMARK_DEVICE
        if dev is None:
            try:
                import jax

                dev = _WATERMARK_DEVICE = jax.local_devices()[0]
            except Exception:  # noqa: BLE001 — backend not up yet
                return 0
        elif dev is False:
            return 0
    try:
        stats = dev.memory_stats() or {}
        return int(
            stats.get("peak_bytes_in_use")
            or stats.get("bytes_in_use")
            or 0
        )
    except Exception:  # noqa: BLE001 — CPU backend: no stats
        with _WATERMARK_LOCK:
            _WATERMARK_DEVICE = False
        return 0


def _reset_watermark_cache():
    """Test hook: forget the cached device between backend switches."""
    global _WATERMARK_DEVICE
    with _WATERMARK_LOCK:
        _WATERMARK_DEVICE = None


class _Segment:
    """One preallocated mmap'd JSONL file of the on-disk ring."""

    def __init__(self, path: str, size: int):
        self.path = path
        self.size = size
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            os.ftruncate(fd, size)
            self.mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self.offset = 0
        self.records = 0

    def reset(self):
        self.mm[: self.size] = b"\0" * self.size
        self.offset = 0
        self.records = 0

    def append(self, data: bytes) -> bool:
        if self.offset + len(data) > self.size:
            return False
        self.mm[self.offset : self.offset + len(data)] = data
        self.offset += len(data)
        self.records += 1
        return True

    def close(self):
        try:
            self.mm.close()
        except Exception:  # noqa: BLE001
            pass


# most recent recorder constructed in this process: bench.py and crash
# forensics read the tail without knowing which session/worker owns it
_LAST_LOCK = threading.Lock()
_LAST: Optional["FlightRecorder"] = None


def last_recorder() -> Optional["FlightRecorder"]:
    with _LAST_LOCK:
        return _LAST


class FlightRecorder:
    """Bounded dispatch ring: in-memory mirror + optional mmap'd disk ring.

    ``directory=None`` keeps the ring memory-only (the default supervisor
    wiring); a directory makes the last ``max_records`` dispatches survive
    process death."""

    def __init__(
        self,
        directory: Optional[str],
        max_records: int = 512,
        name: str = "",
    ):
        global _LAST
        self.directory = directory or None
        self.max_records = max(int(max_records), 2)
        self.name = name or str(os.getpid())
        self._lock = threading.Lock()
        self._seq = 0
        self.tail_ring: deque = deque(maxlen=self.max_records)
        self._segments: List[_Segment] = []
        self._active = 0
        self._seg_records = max(self.max_records // 2, 1)
        if self.directory:
            os.makedirs(self.directory, exist_ok=True)
            seg_bytes = max(
                MIN_SEGMENT_BYTES, self._seg_records * MAX_RECORD_BYTES // 4
            )
            for i in range(2):
                path = os.path.join(
                    self.directory, f"{_FILE_PREFIX}{self.name}-{i}.jsonl"
                )
                seg = _Segment(path, seg_bytes)
                seg.reset()  # a reused path must not replay stale records
                self._segments.append(seg)
        with _LAST_LOCK:
            _LAST = self

    # -- record construction -------------------------------------------
    def _base(self, record_type: str, bc) -> Dict:
        with self._lock:
            self._seq += 1
            seq = self._seq
        return {
            "recordType": record_type,
            "seq": seq,
            "kernel": getattr(bc, "kernel", ""),
            "mode": getattr(bc, "mode", ""),
            "shapes": dict(getattr(bc, "shapes", None) or {}),
            "queryId": getattr(bc, "query_id", ""),
            "taskId": getattr(bc, "task_id", ""),
            "nodeId": getattr(bc, "node_id", ""),
            "hbmReservedBytes": int(
                getattr(bc, "hbm_reserved_bytes", 0) or 0
            ),
            "ts": time.time(),
        }

    def record_dispatch(self, bc) -> int:
        """Pre-dispatch record; returns the seq the completion pairs with."""
        rec = self._base("dispatch", bc)
        self._emit(rec)
        return rec["seq"]

    def record_complete(self, seq: int, bc, wall_s: float,
                        hbm_peak_bytes: Optional[int] = None):
        rec = self._base("complete", bc)
        rec["seq"] = seq  # pair with the dispatch record
        rec["wallS"] = float(wall_s)
        rec["hbmPeakBytes"] = int(
            device_memory_watermark()
            if hbm_peak_bytes is None else hbm_peak_bytes
        )
        self._emit(rec)

    def record_fault(self, seq: int, bc, kind: str, error: str = ""):
        rec = self._base("fault", bc)
        rec["seq"] = seq
        rec["faultKind"] = kind
        rec["error"] = str(error)[:400]
        self._emit(rec)

    # -- ring mechanics -------------------------------------------------
    def _emit(self, rec: Dict):
        with self._lock:
            self.tail_ring.append(rec)
            if not self._segments:
                return
            data = json.dumps(rec, separators=(",", ":")).encode() + b"\n"
            if len(data) > MAX_RECORD_BYTES:
                rec = dict(rec, shapes={})
                data = (
                    json.dumps(rec, separators=(",", ":")).encode() + b"\n"
                )
                if len(data) > MAX_RECORD_BYTES:
                    return  # pathological; drop rather than corrupt
            seg = self._segments[self._active]
            if seg.records >= self._seg_records or not seg.append(data):
                self._active = 1 - self._active
                seg = self._segments[self._active]
                seg.reset()
                seg.append(data)

    def tail(self, n: Optional[int] = None) -> List[Dict]:
        """Most recent records from the in-memory mirror (oldest first)."""
        with self._lock:
            recs = list(self.tail_ring)
        return recs[-n:] if n else recs

    def sync(self):
        """Flush dirty segment pages to disk (drain/shutdown barrier).

        The MAP_SHARED pages already survive process death without this;
        sync() exists for the drain path, which promises that telemetry
        is durable before the worker announces DRAINED."""
        with self._lock:
            for seg in self._segments:
                try:
                    seg.mm.flush()
                except Exception:  # noqa: BLE001 — closed/readonly fs
                    pass

    def close(self):
        with self._lock:
            for seg in self._segments:
                seg.close()
            self._segments = []


# -- offline readers (used by scripts/flightrec.py and tests) -----------


def read_dir(directory: str) -> List[Dict]:
    """Parse every ring segment in ``directory`` into records ordered by
    (ts, seq).  Partial trailing lines (the record being written when the
    process died) and zeroed tail space are skipped, never an error."""
    records: List[Dict] = []
    for path in sorted(
        glob.glob(os.path.join(directory, _FILE_PREFIX + "*.jsonl"))
    ):
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            continue
        for line in data.split(b"\n"):
            line = line.strip(b"\0").strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue  # torn write: the crash interrupted this line
            if isinstance(rec, dict) and "recordType" in rec:
                records.append(rec)
    records.sort(key=lambda r: (r.get("ts", 0.0), r.get("seq", 0)))
    return records


def last_unmatched(records: List[Dict]) -> Optional[Dict]:
    """The culprit: the newest *dispatch* record with no paired complete/
    fault record — the kernel that was in flight when the process died.
    Falls back to the newest dispatch when every one settled."""
    settled = {
        (r.get("nodeId", ""), r.get("seq"))
        for r in records
        if r.get("recordType") in ("complete", "fault")
    }
    dispatches = [r for r in records if r.get("recordType") == "dispatch"]
    open_ = [
        r for r in dispatches
        if (r.get("nodeId", ""), r.get("seq")) not in settled
    ]
    pool = open_ or dispatches
    return pool[-1] if pool else None
