"""Interactive CLI (client/trino-cli Console + aligned output printer).

Local mode runs an in-process Session (StandaloneQueryRunner style);
--server mode speaks the statement protocol to a coordinator.

  python -m trino_tpu.cli --catalog tpch --sf 0.01
  python -m trino_tpu.cli --execute "select 1"
  python -m trino_tpu.cli --server http://127.0.0.1:8080 --execute "..."
"""
from __future__ import annotations

import argparse
import sys
import time


def _align(columns, rows) -> str:
    names = [c["name"] if isinstance(c, dict) else c for c in columns]
    cells = [[("NULL" if v is None else str(v)) for v in r] for r in rows]
    widths = [
        max([len(n)] + [len(r[i]) for r in cells]) for i, n in enumerate(names)
    ]
    sep = "-+-".join("-" * w for w in widths)
    out = [" | ".join(n.ljust(w) for n, w in zip(names, widths)), sep]
    for r in cells:
        out.append(" | ".join(v.ljust(w) for v, w in zip(r, widths)))
    return "\n".join(out)


def run_sql(args, sql: str) -> int:
    t0 = time.time()
    try:
        if args.server:
            from .client.client import StatementClient

            columns, rows = StatementClient(args.server).execute(sql)
        else:
            page = _local_session(args).execute(sql)
            columns = page.names
            rows = page.to_pylist()
    except Exception as e:
        print(f"Query failed: {e}", file=sys.stderr)
        return 1
    print(_align(columns, rows))
    print(f"({len(rows)} rows in {time.time() - t0:.2f}s)")
    return 0


_SESSION = None


def _local_session(args):
    global _SESSION
    if _SESSION is None:
        import trino_tpu

        if not args.tpu:
            trino_tpu.force_cpu()
        trino_tpu.enable_x64()
        from .session import Session, tpch_session, tpcds_session

        if args.catalog == "tpch":
            _SESSION = tpch_session(args.sf)
        elif args.catalog == "tpcds":
            _SESSION = tpcds_session(args.sf)
        else:
            _SESSION = Session()
            _SESSION.create_catalog(args.catalog, args.catalog, {})
    return _SESSION


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="trino-tpu")
    p.add_argument("--server", help="coordinator URI (default: in-process)")
    p.add_argument("--catalog", default="tpch")
    p.add_argument("--sf", type=float, default=0.01, help="tpch scale factor")
    p.add_argument("--tpu", action="store_true", help="use the TPU backend")
    p.add_argument("--execute", "-e", help="run one statement and exit")
    args = p.parse_args(argv)

    if args.execute:
        return run_sql(args, args.execute)

    print("trino-tpu CLI (end with ; — exit with 'quit')")
    buf = []
    while True:
        try:
            prompt = "trino> " if not buf else "    -> "
            line = input(prompt)
        except EOFError:
            break
        if line.strip().lower() in ("quit", "exit"):
            break
        buf.append(line)
        if line.rstrip().endswith(";"):
            sql = "\n".join(buf).rstrip().rstrip(";")
            buf = []
            if sql.strip():
                run_sql(args, sql)
    return 0


if __name__ == "__main__":
    sys.exit(main())
