"""Multi-host cluster topology: who owns which slice of the global mesh.

Reference parity: a Trino cluster is a set of node JVMs discovered via
announcements; a multi-host TPU pod is a set of *processes* each owning
a local slice of one global device mesh (jax.distributed.initialize,
``jax.process_index()`` / ``jax.local_devices()``).  This module is the
junction of the two views:

  - ``bootstrap()`` initialises the process's place in the global mesh.
    On a real multi-host backend (TPU pod slice, or any environment that
    exports a distributed coordinator address) it calls
    ``jax.distributed.initialize()`` so every process sees the global
    device set.  On the CPU tier-1 path there is no cross-process XLA
    runtime: each host process is an independent JAX runtime whose
    "local slice" is K virtual CPU devices
    (``XLA_FLAGS=--xla_force_host_platform_device_count=K``), and the
    topology identity (host id, process index) arrives via CLI flags —
    the subprocess harness in testing/runner.py is the bootstrapper.

  - ``ClusterTopology`` is the coordinator-side registry built from
    worker announcements: which node is which process, on which host,
    with how many local devices.  The scheduler, autoscaler and the
    HOST_GONE fault path all read the cluster's shape from here.

A lost host must be ordinary (Dean & Barroso, *The Tail at Scale*): the
topology layer's job is bookkeeping precise enough that when one process
dies, everything downstream — mesh shrink, FTE reassignment, doctor
verdict — knows exactly which slice of the mesh it took with it.
"""
from __future__ import annotations

import dataclasses
import os
import socket
import threading
from typing import Dict, List, Optional

# the topology document carried in worker announcements and surfaced in
# system.runtime.nodes: lowerCamelCase wire fields, linted by
# scripts/check_metric_names.py
TOPOLOGY_FIELDS = (
    "host",
    "processIndex",
    "localDevices",
    "globalDevices",
    "processCount",
)


@dataclasses.dataclass
class HostSlice:
    """One process's slice of the global mesh, as announced."""

    node_id: str
    uri: str
    host: str
    process_index: int
    local_devices: int

    def to_doc(self) -> dict:
        return {
            "host": self.host,
            "processIndex": self.process_index,
            "localDevices": self.local_devices,
        }


def local_topology(
    host: Optional[str] = None,
    process_index: Optional[int] = None,
    local_devices: Optional[int] = None,
) -> dict:
    """This process's topology document (the announcement payload).

    Explicit arguments (the subprocess harness / CLI flags) win; absent
    those, fall back to what the JAX runtime reports about itself —
    which, after a real ``jax.distributed.initialize()``, is the global
    truth, and in a single-process run degenerates to process 0 of 1.
    """
    import jax

    if process_index is None:
        try:
            process_index = int(jax.process_index())
        except Exception:
            process_index = 0
    if local_devices is None:
        try:
            local_devices = len(jax.local_devices())
        except Exception:
            local_devices = 1
    try:
        global_devices = len(jax.devices())
        process_count = int(jax.process_count())
    except Exception:
        global_devices = local_devices
        process_count = 1
    return {
        "host": host or socket.gethostname(),
        "processIndex": process_index,
        "localDevices": local_devices,
        "globalDevices": global_devices,
        "processCount": process_count,
    }


def bootstrap(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> dict:
    """Join (or stand up) the process's place in the global mesh.

    Real multi-host backends: when a distributed coordinator address is
    known — passed explicitly or via ``TRINO_TPU_DIST_COORDINATOR`` /
    JAX's own ``JAX_COORDINATOR_ADDRESS`` — call
    ``jax.distributed.initialize()`` so ``jax.devices()`` spans every
    host (SNIPPETS pattern: one global mesh across processes).  The
    call is idempotent-guarded: a second bootstrap in the same process
    is a no-op.

    CPU tier-1 path: no coordinator address is set; each process keeps
    its own single-controller runtime and the function only returns the
    local topology document.  Cross-process data movement then happens
    through the exchange layer, not XLA collectives — which is exactly
    the cross-host execution model.
    """
    addr = (
        coordinator_address
        or os.environ.get("TRINO_TPU_DIST_COORDINATOR")
        or os.environ.get("JAX_COORDINATOR_ADDRESS")
    )
    if addr:
        nproc = num_processes or int(
            os.environ.get("TRINO_TPU_DIST_PROCESSES", "0")
        ) or None
        pid = process_id
        if pid is None and "TRINO_TPU_DIST_PROCESS_ID" in os.environ:
            pid = int(os.environ["TRINO_TPU_DIST_PROCESS_ID"])
        import jax

        if not getattr(jax.distributed, "is_initialized", lambda: False)():
            try:
                jax.distributed.initialize(
                    coordinator_address=addr,
                    num_processes=nproc,
                    process_id=pid,
                )
            except RuntimeError:
                # already initialised by an outer harness: keep going
                pass
    return local_topology()


class ClusterTopology:
    """Coordinator-side registry of host slices, fed by announcements.

    Thread-safe: announcements arrive on HTTP handler threads while the
    scheduler and autoscaler read the shape concurrently.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._slices: Dict[str, HostSlice] = {}

    def register(self, node_id: str, uri: str, topology: Optional[dict]):
        """Record (or refresh) a node's announced slice.  Nodes that
        announce without topology (pre-multi-host workers) are simply
        not host-sized units — they never appear here."""
        if not topology:
            return
        try:
            hs = HostSlice(
                node_id=node_id,
                uri=uri,
                host=str(topology.get("host", "")),
                process_index=int(topology.get("processIndex", 0)),
                local_devices=int(topology.get("localDevices", 1)),
            )
        except (TypeError, ValueError):
            return
        with self._lock:
            self._slices[node_id] = hs

    def forget(self, node_id: str) -> Optional[HostSlice]:
        with self._lock:
            return self._slices.pop(node_id, None)

    def slice_for(self, node_id: str) -> Optional[HostSlice]:
        with self._lock:
            return self._slices.get(node_id)

    def slices(self) -> List[HostSlice]:
        with self._lock:
            return sorted(
                self._slices.values(), key=lambda s: s.process_index
            )

    def process_count(self) -> int:
        with self._lock:
            return len(self._slices)

    def global_device_count(self) -> int:
        """Total devices across every registered slice — the size the
        one global logical mesh would have."""
        with self._lock:
            return sum(s.local_devices for s in self._slices.values())

    def hosts(self) -> List[str]:
        with self._lock:
            return sorted({s.host for s in self._slices.values()})
