"""Multi-host cluster runtime: topology, bootstrap, host-sized units.

``P processes x their local device slices of one global logical mesh``
— see topology.py for the bootstrap/registry layer; the cross-host
execution mode itself lives in parallel/mesh_executor.py (per-host
shard_map fragments whose repartition/partial-aggregate merges travel
the network exchange instead of in-XLA collectives).
"""
from .topology import (  # noqa: F401
    TOPOLOGY_FIELDS,
    ClusterTopology,
    HostSlice,
    bootstrap,
    local_topology,
)
