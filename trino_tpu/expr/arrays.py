"""Array functions + lambda evaluation over dictionary-encoded arrays.

Reference parity: operator/scalar/ArrayFunctions + the lambda-taking
classes (ArrayTransformFunction, ArrayFilterFunction, ReduceFunction,
ArrayAnyMatchFunction ...), and scalar/UnnestOperator's value model.

Array columns are dictionary-encoded (types.ArrayType): int32 codes into a
host dictionary of distinct python tuples.  Every array function therefore
runs host-side once per DISTINCT array (the dictionary-projection trick the
string functions use), and the result reaches the device as either a
derived dictionary (array/varchar results) or a gathered numeric table.
Lambdas are evaluated by a small IR interpreter over python element values
(kept in IR-constant conventions: decimal -> unscaled int, date -> epoch
days) — the host-side stand-in for the reference's bytecode-compiled
lambda bodies.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np

from .. import types as T
from . import ir

# ---------------------------------------------------------------------
# IR interpreter (lambda bodies over python scalars)


class _EvalError(ValueError):
    pass


def eval_ir(e: ir.Expr, env: Dict[str, object], special=None):
    """Evaluate an IR expression over python values (env: param -> value).
    Values follow IR-constant conventions.  Returns None for NULL.
    `special(e, env)` may claim a node first (returns (True, value)); used
    by MATCH_RECOGNIZE navigation (ops/matcher.py)."""
    if special is not None:
        handled, v = special(e, env)
        if handled:
            return v
    if isinstance(e, ir.Constant):
        return e.value
    if isinstance(e, ir.ColumnRef):
        if e.name not in env:
            raise _EvalError(f"lambda body references unbound column {e.name}")
        return env[e.name]
    if isinstance(e, ir.Call):
        from ..sql.analyzer import _eval_const

        args = []
        for a in e.args:
            v = eval_ir(a, env, special)
            if v is None:
                return None  # scalar functions are null-propagating
            args.append(ir.Constant(a.type, v))
        try:
            return _eval_const(e.name, e.type, tuple(args))
        except NotImplementedError:
            raise _EvalError(f"{e.name}() is not supported inside lambdas")
    if isinstance(e, ir.Comparison):
        lv = eval_ir(e.left, env, special)
        rv = eval_ir(e.right, env, special)
        if e.op == "is_distinct":
            return _coerce(lv, e.left.type) != _coerce(rv, e.right.type)
        if lv is None or rv is None:
            return None
        a = _coerce(lv, e.left.type)
        b = _coerce(rv, e.right.type)
        return {
            "=": a == b, "<>": a != b, "!=": a != b,
            "<": a < b, "<=": a <= b, ">": a > b, ">=": a >= b,
        }[e.op]
    if isinstance(e, ir.Logical):
        vals = [eval_ir(t, env, special) for t in e.terms]
        if e.op == "and":
            if any(v is False for v in vals):
                return False
            return None if any(v is None for v in vals) else True
        if any(v is True for v in vals):
            return True
        return None if any(v is None for v in vals) else False
    if isinstance(e, ir.Not):
        v = eval_ir(e.term, env, special)
        return None if v is None else (not v)
    if isinstance(e, ir.IsNull):
        v = eval_ir(e.term, env, special)
        return (v is not None) if e.negate else (v is None)
    if isinstance(e, ir.Between):
        v = eval_ir(e.value, env, special)
        lo = eval_ir(e.low, env, special)
        hi = eval_ir(e.high, env, special)
        if v is None or lo is None or hi is None:
            return None
        r = (
            _coerce(lo, e.low.type) <= _coerce(v, e.value.type)
            <= _coerce(hi, e.high.type)
        )
        return (not r) if e.negate else r
    if isinstance(e, ir.In):
        v = eval_ir(e.value, env, special)
        if v is None:
            return None
        vv = _coerce(v, e.value.type)
        hit = any(
            i.value is not None and _coerce(eval_ir(i, env, special), i.type) == vv
            for i in e.items
        )
        return (not hit) if e.negate else hit
    if isinstance(e, ir.Case):
        for w in e.whens:
            if eval_ir(w.condition, env, special) is True:
                return eval_ir(w.result, env, special)
        return eval_ir(e.default, env, special) if e.default is not None else None
    if isinstance(e, ir.Cast):
        v = eval_ir(e.term, env, special)
        if v is None:
            return None
        return _cast_value(v, e.term.type, e.type)
    raise _EvalError(f"unsupported expression in lambda: {type(e).__name__}")


def _coerce(v, t: T.Type):
    """Value -> comparable form (decimals to true numeric value)."""
    if v is None:
        return None
    if t.is_decimal and t.scale:
        return v / 10 ** t.scale
    return v


def _cast_value(v, ft: T.Type, tt: T.Type):
    if tt.is_decimal:
        base = _coerce(v, ft) if not isinstance(v, str) else float(v)
        return int(round(float(base) * 10 ** tt.scale))
    if tt.name in ("double", "real"):
        return float(_coerce(v, ft))
    if tt.name in ("bigint", "integer", "smallint", "tinyint"):
        return int(_coerce(v, ft))
    if tt.is_dictionary and not getattr(tt, "is_array", False):
        return str(v)
    if tt.name == "boolean":
        return bool(v)
    return v


# ---------------------------------------------------------------------
# per-distinct-array evaluation helpers


def _array_dict(ctx, e: ir.Expr) -> np.ndarray:
    d = ctx.dict_for_expr(e)
    if d is None:
        raise NotImplementedError(
            "array expression has no dictionary (only dictionary-encoded "
            "arrays are supported)"
        )
    return d


def _table_fn(node, lanes, ctx, fn, out_dtype, null_value=0):
    """Numeric-result array function: compute fn(entry) per dictionary
    entry, gather per row.  fn returns python value or None."""
    from .functions import dict_gather

    src = _array_dict(ctx, node.args[0])
    vals = np.zeros(len(src), dtype=out_dtype)
    valid = np.zeros(len(src), dtype=bool)
    for i, entry in enumerate(src):
        r = fn(entry)
        if r is not None:
            vals[i] = r
            valid[i] = True
    cv, cok = lanes[0]
    out = dict_gather(vals, cv, 0)
    ok = cok & dict_gather(valid, cv, False)
    return out, ok


def _derived_fn(node, lanes, ctx, fn):
    """Dictionary-result array function (array->array, array->varchar):
    fn(entry) -> tuple | str | None; result is a derived dictionary."""
    from .functions import dict_gather, register_derived_dict

    src = _array_dict(ctx, node.args[0])
    remap = register_derived_dict(ctx, node, [fn(entry) for entry in src])
    cv, cok = lanes[0]
    return dict_gather(remap, cv, -1), cok


# ---------------------------------------------------------------------
# function lowerings (ctx-aware; registered into functions.FUNCTIONS)


def _cardinality(node, lanes, ctx):
    # arrays: element count; maps: entry count (entries are (k, v) pairs)
    return _table_fn(node, lanes, ctx, lambda a: len(a), np.int64)


def _element_at(node, lanes, ctx):
    coll_t = node.args[0].type
    idx = node.args[1]
    if not isinstance(idx, ir.Constant):
        raise NotImplementedError("element_at index/key must be constant")
    if getattr(coll_t, "is_map", False):
        key = idx.value

        def pick(entry):
            for k, v in entry:
                if k == key:
                    return v
            return None

        et = coll_t.value
    else:
        i = int(idx.value)

        def pick(entry):
            n = len(entry)
            if i == 0 or abs(i) > n:
                return None
            return entry[i - 1] if i > 0 else entry[n + i]

        et = coll_t.element
    if et.is_dictionary or getattr(et, "is_array", False):
        return _derived_fn(node, lanes, ctx, pick)
    return _table_fn(node, lanes, ctx, pick, et.np_dtype)


def _contains(node, lanes, ctx):
    x = node.args[1]
    if not isinstance(x, ir.Constant):
        raise NotImplementedError("contains() value must be constant")
    xv = x.value

    def hit(entry):
        if xv is None:
            return None
        if any(v == xv for v in entry if v is not None):
            return True
        if any(v is None for v in entry):
            return None
        return False

    return _table_fn(node, lanes, ctx, hit, np.bool_)


def _array_extreme(node, lanes, ctx, agg):
    et = node.args[0].type.element

    def fn(entry):
        vals = [v for v in entry if v is not None]
        if not vals or len(vals) != len(entry):
            return None  # Trino: null element -> NULL result
        return agg(vals)

    if et.is_dictionary:
        return _derived_fn(node, lanes, ctx, fn)
    return _table_fn(node, lanes, ctx, fn, et.np_dtype)


def _array_min(node, lanes, ctx):
    return _array_extreme(node, lanes, ctx, min)


def _array_max(node, lanes, ctx):
    return _array_extreme(node, lanes, ctx, max)


def _array_join(node, lanes, ctx):
    delim = node.args[1]
    if not isinstance(delim, ir.Constant):
        raise NotImplementedError("array_join delimiter must be constant")
    d = str(delim.value)
    et = node.args[0].type.element
    from ..page import _element_decoder

    dec = _element_decoder(et)

    def fn(entry):
        return d.join(str(dec(v)) for v in entry if v is not None)

    return _derived_fn(node, lanes, ctx, fn)


def _array_distinct(node, lanes, ctx):
    def fn(entry):
        seen = []
        for v in entry:
            if v not in seen:
                seen.append(v)
        return tuple(seen)

    return _derived_fn(node, lanes, ctx, fn)


def _array_sort(node, lanes, ctx):
    def fn(entry):
        vals = [v for v in entry if v is not None]
        nulls = [None] * (len(entry) - len(vals))
        return tuple(sorted(vals) + nulls)  # nulls last (Trino)

    return _derived_fn(node, lanes, ctx, fn)


def _array_reverse(node, lanes, ctx):
    return _derived_fn(node, lanes, ctx, lambda e: tuple(reversed(e)))


def _slice(node, lanes, ctx):
    start_c, len_c = node.args[1], node.args[2]
    if not (isinstance(start_c, ir.Constant) and isinstance(len_c, ir.Constant)):
        raise NotImplementedError("slice() bounds must be constant")
    start, length = int(start_c.value), int(len_c.value)

    def fn(entry):
        if start == 0 or length < 0:
            return None
        if start > 0:
            s = start - 1
        else:
            s = len(entry) + start
            if s < 0:
                return ()
        return tuple(entry[s : s + length])

    return _derived_fn(node, lanes, ctx, fn)


def _array_position(node, lanes, ctx):
    x = node.args[1]
    if not isinstance(x, ir.Constant):
        raise NotImplementedError("array_position value must be constant")
    xv = x.value

    def fn(entry):
        if xv is None:
            return None
        for i, v in enumerate(entry):
            if v == xv:
                return i + 1
        return 0

    return _table_fn(node, lanes, ctx, fn, np.int64)


def _split(node, lanes, ctx):
    from .functions import dict_gather, register_derived_dict

    delim = node.args[1]
    if not isinstance(delim, ir.Constant):
        raise NotImplementedError("split() delimiter must be constant")
    d = str(delim.value)
    src = _array_dict(ctx, node.args[0])  # varchar dictionary
    remap = register_derived_dict(
        ctx, node, [tuple(str(s).split(d)) for s in src]
    )
    cv, cok = lanes[0]
    return dict_gather(remap, cv, -1), cok


# -- lambda functions ---------------------------------------------------


def _lambda_of(node, i=1) -> ir.Lambda:
    lam = node.args[i]
    assert isinstance(lam, ir.Lambda), "expected a lambda argument"
    return lam


def _transform(node, lanes, ctx):
    lam = _lambda_of(node)
    p = lam.params[0]

    def fn(entry):
        return tuple(eval_ir(lam.body, {p: v}) for v in entry)

    return _derived_fn(node, lanes, ctx, fn)


def _filter(node, lanes, ctx):
    lam = _lambda_of(node)
    p = lam.params[0]

    def fn(entry):
        return tuple(v for v in entry if eval_ir(lam.body, {p: v}) is True)

    return _derived_fn(node, lanes, ctx, fn)


def _match(node, lanes, ctx, mode):
    lam = _lambda_of(node)
    p = lam.params[0]

    def fn(entry):
        results = [eval_ir(lam.body, {p: v}) for v in entry]
        if mode == "any":
            if any(r is True for r in results):
                return True
            return None if any(r is None for r in results) else False
        if mode == "all":
            if any(r is False for r in results):
                return False
            return None if any(r is None for r in results) else True
        # none
        if any(r is True for r in results):
            return False
        return None if any(r is None for r in results) else True

    return _table_fn(node, lanes, ctx, fn, np.bool_)


def _any_match(node, lanes, ctx):
    return _match(node, lanes, ctx, "any")


def _all_match(node, lanes, ctx):
    return _match(node, lanes, ctx, "all")


def _none_match(node, lanes, ctx):
    return _match(node, lanes, ctx, "none")


def _reduce(node, lanes, ctx):
    # reduce(arr, initial, (state, x) -> newstate, state -> result)
    init = node.args[1]
    if not isinstance(init, ir.Constant):
        raise NotImplementedError("reduce() initial state must be constant")
    step = _lambda_of(node, 2)
    out_lam = _lambda_of(node, 3)
    sp, xp = step.params
    op = out_lam.params[0]
    rt = node.type

    def fn(entry):
        state = init.value
        for v in entry:
            state = eval_ir(step.body, {sp: state, xp: v})
        return eval_ir(out_lam.body, {op: state})

    if rt.is_dictionary and not getattr(rt, "is_array", False):
        return _derived_fn(node, lanes, ctx, fn)
    return _table_fn(node, lanes, ctx, fn, rt.np_dtype)


# -- map functions ------------------------------------------------------


def _map_keys(node, lanes, ctx):
    return _derived_fn(node, lanes, ctx, lambda e: tuple(k for k, _ in e))


def _map_values(node, lanes, ctx):
    return _derived_fn(node, lanes, ctx, lambda e: tuple(v for _, v in e))


def _map_entry_count(node, lanes, ctx):
    return _table_fn(node, lanes, ctx, lambda e: len(e), np.int64)


def _map_concat(node, lanes, ctx):
    # map_concat(m1, m2): later keys win (reference MapConcatFunction)
    d2 = ctx.dict_for_expr(node.args[1])
    if d2 is None:
        raise NotImplementedError("map_concat requires dictionary maps")
    if len(d2) != 1:
        raise NotImplementedError(
            "map_concat second argument must be a constant map"
        )
    other = list(d2[0])

    def fn(entry):
        merged = {k: v for k, v in entry}
        for k, v in other:
            merged[k] = v
        return tuple(merged.items())

    return _derived_fn(node, lanes, ctx, fn)


ARRAY_FUNCTIONS = {
    "cardinality": _cardinality,
    "element_at": _element_at,
    "contains": _contains,
    "array_min": _array_min,
    "array_max": _array_max,
    "array_join": _array_join,
    "array_distinct": _array_distinct,
    "array_sort": _array_sort,
    "array_reverse": _array_reverse,
    "slice": _slice,
    "array_position": _array_position,
    "split": _split,
    "transform": _transform,
    "filter": _filter,
    "any_match": _any_match,
    "all_match": _all_match,
    "none_match": _none_match,
    "reduce": _reduce,
    "map_keys": _map_keys,
    "map_values": _map_values,
    "map_concat": _map_concat,
}
