"""Expression IR -> jax lowering.

This module is the TPU-native replacement for the reference's runtime
bytecode generation pipeline:
  sql/gen/ExpressionCompiler.java:56 (compilePageProcessor:102)
  sql/gen/PageFunctionCompiler.java:104 (compileProjection:167, compileFilter:374)

A fully-typed Expr tree lowers to a pure python function
    f(cols: dict[name -> Lane]) -> Lane
where Lane = (values: jnp.ndarray, valid: jnp.ndarray bool).  The function is
traced by jax inside the enclosing operator kernel, so XLA fuses the whole
filter/projection with its neighbours — the analog of the reference's
generated PageFilter/PageProjection classes, but with the fusion done by the
compiler rather than hand-rolled loops.

Null semantics (three-valued logic) follow the reference's codegen wasNull
protocol: every lane carries a validity mask; AND/OR use Kleene logic
(sql/ir/IrUtils + gen/LogicalBinaryExpression codegen).

Dictionary-encoded varchar comparisons against constants are resolved
host-side at *compile* time: the constant is looked up in the column's
dictionary and the comparison becomes an int32 code comparison — the analog
of the reference's DictionaryAwarePageFilter (operator/project/
DictionaryAwarePageProjection.java) which evaluates once per dictionary
entry instead of once per row.
"""
from __future__ import annotations

from typing import Callable, Dict, Tuple

import jax.numpy as jnp
import numpy as np

from .. import types as T
from . import ir
from .functions import FUNCTIONS, align_numeric, decimal_rescale, dict_gather, round_half_away

Lane = Tuple[jnp.ndarray, jnp.ndarray]  # (values, valid)


def _const_lane(e: ir.Constant, n_ref: Lane) -> Lane:
    """Broadcast a constant against the shape of any reference lane."""
    shape = (n_ref[0].shape[0],)
    if getattr(e.type, "wide", False):
        from ..ops.wide_decimal import from_python_int

        if e.value is None:
            return (
                jnp.zeros(shape + (2,), dtype=jnp.int64),
                jnp.zeros(shape, dtype=bool),
            )
        lo, hi = from_python_int(int(e.value))
        val = jnp.stack(
            [jnp.full(shape, lo, jnp.int64), jnp.full(shape, hi, jnp.int64)],
            axis=-1,
        )
        return val, jnp.ones(shape, dtype=bool)
    if e.value is None:
        return (
            jnp.zeros(shape, dtype=e.type.np_dtype),
            jnp.zeros(shape, dtype=bool),
        )
    val = jnp.full(shape, e.value, dtype=e.type.np_dtype)
    return val, jnp.ones(shape, dtype=bool)


def _all_valid(v: jnp.ndarray) -> jnp.ndarray:
    return jnp.ones(v.shape, dtype=bool)


class LoweringContext:
    """Per-compilation context: column dictionaries for dict-code rewrites.

    dictionaries: column name -> np.ndarray of strings (host side).  Used to
    turn varchar-vs-constant predicates into int32 code predicates at trace
    time.
    """

    def __init__(self, dictionaries: Dict[str, np.ndarray] | None = None):
        self.dictionaries = dictionaries or {}
        # dictionaries of *derived* string expressions (substring(col,..)
        # etc.), keyed by the (hashable, frozen) IR node that produced them
        self.expr_dicts: Dict[object, np.ndarray] = {}
        # decimal multiplies whose declared precision exceeds 18 digits run
        # the cheap int64 kernel first and flag potential overflow here
        # (traced scalars); the executor retries with the 128-bit kernel
        # only when a flag fires (DecimalOperators would use Int128 always;
        # real data almost never needs it and the wide kernel is costly)
        self.overflow_flags: list = []
        # set by the executor's retry ladder after a flagged overflow
        self.force_wide_mul: bool = False

    def dict_for_expr(self, e) -> np.ndarray | None:
        """Dictionary of a varchar-typed expression: source column's, or a
        derived one registered by a string function."""
        from . import ir as _ir

        if isinstance(e, _ir.ColumnRef):
            return self.dictionaries.get(e.name)
        return self.expr_dicts.get(e)

    # -- host-side dictionary predicate evaluation ---------------------
    def _dict_of(self, col_or_expr):
        if isinstance(col_or_expr, str):
            d = self.dictionaries.get(col_or_expr)
        else:
            d = self.dict_for_expr(col_or_expr)
        if d is None:
            raise KeyError(f"no dictionary for {col_or_expr}")
        return d

    def dict_code_for(self, col, s: str) -> int:
        d = self._dict_of(col)
        idx = np.nonzero(d == s)[0]
        return int(idx[0]) if len(idx) else -2  # -2: never matches any code

    def dict_mask(self, col, pred: Callable[[str], bool]) -> np.ndarray:
        """Boolean lookup table over dictionary entries (for LIKE etc.)."""
        d = self._dict_of(col)
        return np.array([bool(pred(str(x))) for x in d], dtype=bool)


def compile_expr(
    e: ir.Expr, ctx: LoweringContext | None = None
) -> Callable[[Dict[str, Lane]], Lane]:
    """Compile an Expr into a lane function. Pure; jit-traceable."""
    ctx = ctx or LoweringContext()

    def ev(node: ir.Expr, cols: Dict[str, Lane]) -> Lane:
        if isinstance(node, ir.ColumnRef):
            return cols[node.name]
        if isinstance(node, ir.Constant):
            ref = next(iter(cols.values()))
            if node.type.is_dictionary:
                shape = ref[0].shape
                if node.value is None:
                    ctx.expr_dicts[node] = np.array([], dtype=object)
                    return (
                        jnp.full(shape, -1, dtype=jnp.int32),
                        jnp.zeros(shape, dtype=bool),
                    )
                # safe 1-element object array (np.array([tuple]) would
                # build a 2-D array for array-typed constants)
                entry = np.empty(1, dtype=object)
                entry[0] = node.value
                ctx.expr_dicts[node] = entry
                return (
                    jnp.zeros(shape, dtype=jnp.int32),
                    jnp.ones(shape, dtype=bool),
                )
            return _const_lane(node, ref)
        if isinstance(node, ir.Call):
            return _lower_call(node, cols, ev, ctx)
        if isinstance(node, ir.Comparison):
            return _lower_comparison(node, cols, ev, ctx)
        if isinstance(node, ir.Logical):
            return _lower_logical(node, cols, ev)
        if isinstance(node, ir.Not):
            v, ok = ev(node.term, cols)
            return jnp.logical_not(v), ok
        if isinstance(node, ir.IsNull):
            _, ok = ev(node.term, cols)
            res = ok if node.negate else jnp.logical_not(ok)
            return res, _all_valid(res)
        if isinstance(node, ir.Between):
            v, vok = ev(node.value, cols)
            lo, lok = ev(node.low, cols)
            hi, hok = ev(node.high, cols)
            # align each bound against the ORIGINAL value lane independently
            v_lo, lo2 = align_numeric(node.value.type, v, node.low.type, lo)
            v_hi, hi2 = align_numeric(node.value.type, v, node.high.type, hi)
            res = jnp.logical_and(
                _cmp("<=", lo2, v_lo), _cmp("<=", v_hi, hi2)
            )
            if node.negate:
                res = jnp.logical_not(res)
            return res, vok & lok & hok
        if isinstance(node, ir.In):
            return _lower_in(node, cols, ev, ctx)
        if isinstance(node, ir.Case):
            return _lower_case(node, cols, ev, ctx)
        if isinstance(node, ir.Cast):
            return _lower_cast(node, cols, ev, ctx)
        raise NotImplementedError(type(node).__name__)

    return lambda cols: ev(e, cols)


# ----------------------------------------------------------------------
# helpers


def _lower_comparison(node: ir.Comparison, cols, ev, ctx: LoweringContext) -> Lane:
    lt, rt = node.left.type, node.right.type
    # dictionary-aware string comparison against constant
    if lt.is_dictionary and isinstance(node.right, ir.Constant):
        return _dict_const_cmp(node.left, node.op, node.right.value, cols, ev, ctx)
    if rt.is_dictionary and isinstance(node.left, ir.Constant):
        flip = {"<": ">", ">": "<", "<=": ">=", ">=": "<="}
        op = flip.get(node.op, node.op)
        return _dict_const_cmp(node.right, op, node.left.value, cols, ev, ctx)
    if lt.is_dictionary and rt.is_dictionary:
        # codes are only comparable when both columns share one dictionary
        # (same scan); ordered comparison additionally needs a sorted dict.
        ln = node.left.name if isinstance(node.left, ir.ColumnRef) else None
        rn = node.right.name if isinstance(node.right, ir.ColumnRef) else None
        da, db = ctx.dictionaries.get(ln), ctx.dictionaries.get(rn)
        shared = da is not None and db is not None and np.array_equal(da, db)
        if not (shared and node.op in ("=", "<>", "!=", "is_distinct")):
            raise NotImplementedError(
                "varchar column-vs-column comparison requires a shared "
                f"dictionary and equality op (got {node.op})"
            )
    lv, lok = ev(node.left, cols)
    rv, rok = ev(node.right, cols)
    lv, rv = align_numeric(lt, lv, rt, rv)
    res = _cmp(node.op, lv, rv)
    if node.op == "is_distinct":
        both_null = jnp.logical_not(lok) & jnp.logical_not(rok)
        neq = jnp.where(
            lok & rok, _cmp("is_distinct", lv, rv),
            jnp.logical_not(both_null),
        )
        return neq, _all_valid(neq)
    return res, lok & rok


def _cmp(op: str, lv, rv):
    if lv.ndim == 2 or rv.ndim == 2:
        from ..ops import wide_decimal as wd

        wide_op = {"=": "==", "<>": "!=", "is_distinct": "!="}.get(op, op)
        return wd.compare(wd.promote(lv), wd.promote(rv), wide_op)
    if op == "=":
        return lv == rv
    if op in ("<>", "!="):
        return lv != rv
    if op == "<":
        return lv < rv
    if op == "<=":
        return lv <= rv
    if op == ">":
        return lv > rv
    if op == ">=":
        return lv >= rv
    if op == "is_distinct":
        return lv != rv
    raise NotImplementedError(op)


def _dict_const_cmp(col_expr, op, const_val, cols, ev, ctx: LoweringContext) -> Lane:
    """Lower dict-expr <op> string-constant via host dictionary lookup."""
    cv, cok = ev(col_expr, cols)
    if ctx.dict_for_expr(col_expr) is None:
        raise NotImplementedError("dict comparison requires a dictionary")
    if op in ("=", "<>", "!="):
        code = ctx.dict_code_for(col_expr, const_val)
        res = cv == code if op == "=" else cv != code
        return res, cok
    if op == "is_distinct":
        code = ctx.dict_code_for(col_expr, const_val)
        # null IS DISTINCT FROM 'x' -> true; result is never null
        res = jnp.where(cok, cv != code, True)
        return res, _all_valid(res)
    # ordered comparison on strings: precompute per-code truth table
    import operator as _op

    fns = {"<": _op.lt, "<=": _op.le, ">": _op.gt, ">=": _op.ge}
    table = ctx.dict_mask(col_expr, lambda s: fns[op](s, const_val))
    res = dict_gather(table, cv)
    return res, cok


def _lower_logical(node: ir.Logical, cols, ev) -> Lane:
    """Kleene AND/OR over n terms."""
    lanes = [ev(t, cols) for t in node.terms]
    v, ok = lanes[0]
    for v2, ok2 in lanes[1:]:
        if node.op == "and":
            # null AND false = false; null AND true = null
            res = jnp.where(ok, v, True) & jnp.where(ok2, v2, True)
            resok = (ok & ok2) | (ok & jnp.logical_not(v)) | (
                ok2 & jnp.logical_not(v2)
            )
        else:
            res = jnp.where(ok, v, False) | jnp.where(ok2, v2, False)
            resok = (ok & ok2) | (ok & v) | (ok2 & v2)
        v, ok = res, resok
    return v, ok


def _lower_in(node: ir.In, cols, ev, ctx: LoweringContext) -> Lane:
    vt = node.value.type
    if vt.is_dictionary:
        # evaluate first: derived-string functions register their
        # dictionaries during evaluation
        cv, cok = ev(node.value, cols)
        if ctx.dict_for_expr(node.value) is None:
            raise NotImplementedError("IN on varchar requires a dictionary")
        vals = {it.value for it in node.items if isinstance(it, ir.Constant)}
        table = ctx.dict_mask(node.value, lambda s: s in vals)
        res = dict_gather(table, cv)
        if node.negate:
            res = jnp.logical_not(res)
        return res, cok
    v, vok = ev(node.value, cols)
    n = v.shape[0]
    res = jnp.zeros(n, dtype=bool)
    anynull = jnp.zeros(n, dtype=bool)
    for it in node.items:
        iv, iok = ev(it, cols)
        a, b = align_numeric(node.value.type, v, it.type, iv)
        res = res | jnp.where(iok, _cmp("=", a, b), False)
        anynull = anynull | jnp.logical_not(iok)
    # x IN (...) is null if no match and some item was null
    ok = vok & (res | jnp.logical_not(anynull))
    if node.negate:
        res = jnp.logical_not(res)
    return res, ok


def _lower_case(node: ir.Case, cols, ev, ctx: LoweringContext) -> Lane:
    if node.type.is_dictionary:
        return _lower_case_dict(node, cols, ev, ctx)
    wide_out = getattr(node.type, "wide", False)

    def branch_value(e: ir.Expr, bv):
        """Coerce one branch lane to the CASE output representation."""
        if wide_out or bv.ndim == 2:
            from ..ops import wide_decimal as wd

            fs = e.type.scale if e.type.is_decimal else 0
            w = wd.decimal_rescale_wide(
                wd.promote(bv.astype(jnp.int64) if bv.ndim == 1 else bv),
                fs, node.type.scale,
            )
            return w if wide_out else wd.narrow(w)
        bv = bv.astype(node.type.np_dtype)
        if e.type.is_decimal and node.type.is_decimal:
            bv = decimal_rescale(bv, e.type.scale, node.type.scale)
        return bv

    # evaluate all branches, select backwards (XLA fuses the selects)
    if node.default is not None:
        v, ok = ev(node.default, cols)
        v = branch_value(node.default, v)
    else:
        ref = next(iter(cols.values()))
        n = ref[0].shape[0]
        shape = (n, 2) if wide_out else (n,)
        dt = jnp.int64 if wide_out else node.type.np_dtype
        v = jnp.zeros(shape, dtype=dt)
        ok = jnp.zeros(n, dtype=bool)
    for w in reversed(node.whens):
        cv, cok = ev(w.condition, cols)
        rv, rok = ev(w.result, cols)
        rv = branch_value(w.result, rv)
        take = cok & cv
        v = jnp.where(take[..., None] if v.ndim == 2 else take, rv, v)
        ok = jnp.where(take, rok, ok)
    return v, ok


def _lower_case_dict(node: ir.Case, cols, ev, ctx: LoweringContext) -> Lane:
    """CASE producing varchar: union the branch dictionaries, remap each
    branch's codes into the union space, then select — the multi-branch
    generalisation of the DictionaryAwarePageProjection trick."""
    union_index: Dict[str, int] = {}
    union_vals: list = []

    def remap_codes(e: ir.Expr, lane: Lane):
        d = ctx.dict_for_expr(e)
        if d is None:
            raise NotImplementedError(
                "varchar CASE requires dictionary-encoded branches"
            )
        remap = np.empty(len(d), dtype=np.int32)
        for i, s in enumerate(d):
            s = str(s)
            if s not in union_index:
                union_index[s] = len(union_vals)
                union_vals.append(s)
            remap[i] = union_index[s]
        v, ok = lane
        codes = dict_gather(remap, v, -1).astype(jnp.int32)
        return codes, ok & (codes >= 0)

    if node.default is not None:
        v, ok = remap_codes(node.default, ev(node.default, cols))
    else:
        ref = next(iter(cols.values()))
        v = jnp.full(ref[0].shape, -1, dtype=jnp.int32)
        ok = jnp.zeros(ref[0].shape, dtype=bool)
    for w in reversed(node.whens):
        cv, cok = ev(w.condition, cols)
        rv, rok = remap_codes(w.result, ev(w.result, cols))
        take = cok & cv
        v = jnp.where(take, rv, v)
        ok = jnp.where(take, rok, ok)
    ctx.expr_dicts[node] = np.array(union_vals, dtype=object)
    return v, ok


def _lower_cast(node: ir.Cast, cols, ev, ctx: LoweringContext) -> Lane:
    v, ok = ev(node.term, cols)
    ft, tt = node.term.type, node.type
    if ft == tt:
        return v, ok
    if ft.is_dictionary and tt.is_dictionary:
        # varchar(n) truncation: lengths are advisory; keep codes but
        # re-register the dictionary under the cast node for downstream
        # dictionary consumers (comparisons, derived string functions)
        d = ctx.dict_for_expr(node.term)
        if d is not None:
            ctx.expr_dicts[node] = d
        return v, ok
    if ft.is_dictionary:
        return _cast_varchar_parse(node, v, ok, ctx)
    wide_src = v.ndim == 2
    wide_tgt = getattr(tt, "wide", False)
    if wide_src or wide_tgt:
        from ..ops import wide_decimal as wd

        if ft.is_decimal and tt.is_decimal:
            w = wd.decimal_rescale_wide(wd.promote(v), ft.scale, tt.scale)
            return (w if wide_tgt else wd.narrow(w)), ok
        if wide_src and tt.name == "double":
            return wd.to_double(v) / (10**ft.scale), ok
        if wide_src and T.is_integral(tt):
            w = wd.decimal_rescale_wide(v, ft.scale, 0)
            return wd.narrow(w).astype(tt.np_dtype), ok
        if T.is_integral(ft) and wide_tgt:
            return wd.rescale(wd.widen(v.astype(jnp.int64)), tt.scale), ok
        if ft.name in ("double", "real") and wide_tgt:
            # via float: beyond 2^53 the double itself has no more digits
            n = round_half_away(v * (10**tt.scale))
            return wd.widen(n.astype(jnp.int64)), ok
        raise NotImplementedError(f"cast {ft} -> {tt} (wide decimal)")
    if ft.is_decimal and tt.is_decimal:
        return decimal_rescale(v, ft.scale, tt.scale), ok
    if ft.is_decimal and tt.name == "double":
        return v.astype(jnp.float64) / (10**ft.scale), ok
    if ft.name in ("double", "real") and tt.is_decimal:
        return round_half_away(v * (10**tt.scale)).astype(jnp.int64), ok
    if ft.is_decimal and T.is_integral(tt):
        return decimal_rescale(v, ft.scale, 0).astype(tt.np_dtype), ok
    if T.is_integral(ft) and tt.is_decimal:
        return v.astype(jnp.int64) * (10**tt.scale), ok
    return v.astype(tt.np_dtype), ok


def _cast_varchar_parse(node: ir.Cast, v, ok, ctx: LoweringContext) -> Lane:
    """CAST(varchar AS numeric/date): parse each dictionary entry host-side,
    gather values + a validity table (bad parses -> NULL, TRY semantics)."""
    d = ctx.dict_for_expr(node.term)
    if d is None:
        raise NotImplementedError("varchar cast requires a dictionary input")
    tt = node.type
    vals = np.zeros(len(d), dtype=tt.np_dtype)
    valid = np.ones(len(d), dtype=bool)
    for i, s in enumerate(d):
        s = str(s).strip()
        try:
            if tt.name == "date":
                import datetime

                from .functions import days_from_civil

                dt = datetime.date.fromisoformat(s)
                vals[i] = days_from_civil(dt.year, dt.month, dt.day)
            elif tt.is_decimal:
                from decimal import Decimal

                vals[i] = int(Decimal(s).scaleb(tt.scale).to_integral_value())
            elif tt.name in ("double", "real"):
                vals[i] = float(s)
            elif tt.name == "boolean":
                low = s.lower()
                if low in ("true", "t", "1"):
                    vals[i] = True
                elif low in ("false", "f", "0"):
                    vals[i] = False
                else:
                    valid[i] = False
            else:
                vals[i] = int(s)
        except (ValueError, ArithmeticError):
            valid[i] = False
    res = dict_gather(vals, v, 0)
    okt = dict_gather(valid, v, False)
    return res, ok & okt


# functions whose FIRST argument is consumed through its dictionary
# (dict_for_expr); a constant string argument must still get a lane +
# single-entry dictionary
DICT_INPUT_FNS = frozenset({
    "split", "json_extract_scalar", "json_extract", "json_array_length",
    "json_size", "json_array_contains", "json_format",
    "url_extract_host", "url_extract_path", "url_extract_query",
    "url_extract_protocol", "url_extract_fragment", "url_extract_port",
    "url_extract_parameter", "url_encode", "url_decode",
    "md5", "sha1", "sha256", "sha512", "crc32",
    "to_base64", "from_base64", "to_hex", "levenshtein_distance",
})


def _lower_call(node: ir.Call, cols, ev, ctx: LoweringContext) -> Lane:
    fn = FUNCTIONS.get(node.name)
    if fn is None:
        raise NotImplementedError(f"function {node.name}")
    # string constants (LIKE patterns etc.) and lambdas are consumed
    # host-side from the node itself; they have no device lane — except a
    # constant FIRST argument of dictionary-transforming functions like
    # split(), which needs a real (single-entry-dictionary) lane
    lanes = []
    for i, a in enumerate(node.args):
        if isinstance(a, ir.Lambda):
            lanes.append(None)
        elif (isinstance(a, ir.Constant) and isinstance(a.value, str)
                and not (i == 0 and node.name in DICT_INPUT_FNS)):
            lanes.append(None)
        else:
            lanes.append(ev(a, cols))
    return fn(node, lanes, ctx)
