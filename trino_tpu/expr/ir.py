"""Typed logical expression IR.

Reference parity: core/trino-main/src/main/java/io/trino/sql/ir/ (29 nodes:
Call, Comparison, Constant, Logical, Case, Cast, In, Between, IsNull, ...)
and the relational RowExpression IR (sql/relational/) that feeds bytecode
codegen (sql/gen/ExpressionCompiler.java:56).

Here the IR is the input to jax tracing (expr/lower.py) instead of bytecode
generation: an Expr tree lowers to a pure function over (values, validity)
array pairs, which XLA then fuses into the surrounding operator kernel.

Every node carries its result Type; the analyzer (sql/analyzer.py) produces
fully-typed trees, and lowering is type-directed (decimal rescaling, dict
code comparison, 3-valued logic).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Sequence, Tuple

from .. import types as T

# Functions whose value is not a pure function of their arguments (the
# reference's FunctionMetadata.isDeterministic bit).  One registry consulted
# by constant folding (sql/analyzer._fold), the plan-signature determinism
# analysis (cache/signature.py), and the optimizer: `now`-class functions
# fold to per-query Constants carrying nondeterministic_origin; `rand`-class
# functions stay as Calls and are never folded or result-cached.  `uuid` is
# registered for tagging even though this engine has no runtime kernel yet.
NONDETERMINISTIC_FUNCTIONS = frozenset({
    "now", "current_timestamp", "current_date", "localtimestamp",
    "rand", "random", "uuid",
})


class Expr:
    type: T.Type

    def children(self) -> Sequence["Expr"]:
        return ()


@dataclasses.dataclass(frozen=True)
class Constant(Expr):
    """Literal. value is a python scalar; for varchar it is the python str,
    for decimal it is the *unscaled* int, for date the epoch-day int.

    nondeterministic_origin marks constants produced by folding a
    nondeterministic function at analysis time (now()/current_timestamp
    evaluate once per query): the value is a legitimate constant for THIS
    query but must never be shared across queries, so plan/result caches
    refuse plans containing one."""

    type: T.Type
    value: Any  # None = NULL literal
    nondeterministic_origin: bool = False

    def __repr__(self):
        return f"Const({self.value}:{self.type})"


@dataclasses.dataclass(frozen=True)
class ColumnRef(Expr):
    """Reference to an input column by name (symbol)."""

    type: T.Type
    name: str

    def __repr__(self):
        return f"Col({self.name}:{self.type})"


@dataclasses.dataclass(frozen=True)
class Call(Expr):
    """Scalar function call (arithmetic, string fns, date fns, ...)."""

    type: T.Type
    name: str
    args: Tuple[Expr, ...]

    def children(self):
        return self.args

    def __repr__(self):
        return f"{self.name}({', '.join(map(repr, self.args))})"


@dataclasses.dataclass(frozen=True)
class Comparison(Expr):
    """=, <>, <, <=, >, >=, IS DISTINCT FROM."""

    op: str
    left: Expr
    right: Expr
    type: T.Type = T.BOOLEAN

    def children(self):
        return (self.left, self.right)

    def __repr__(self):
        return f"({self.left!r} {self.op} {self.right!r})"


@dataclasses.dataclass(frozen=True)
class Logical(Expr):
    """AND / OR over 2+ terms with Kleene 3-valued semantics."""

    op: str  # 'and' | 'or'
    terms: Tuple[Expr, ...]
    type: T.Type = T.BOOLEAN

    def children(self):
        return self.terms


@dataclasses.dataclass(frozen=True)
class Not(Expr):
    term: Expr
    type: T.Type = T.BOOLEAN

    def children(self):
        return (self.term,)


@dataclasses.dataclass(frozen=True)
class IsNull(Expr):
    term: Expr
    negate: bool = False
    type: T.Type = T.BOOLEAN

    def children(self):
        return (self.term,)


@dataclasses.dataclass(frozen=True)
class Between(Expr):
    value: Expr
    low: Expr
    high: Expr
    negate: bool = False
    type: T.Type = T.BOOLEAN

    def children(self):
        return (self.value, self.low, self.high)


@dataclasses.dataclass(frozen=True)
class In(Expr):
    value: Expr
    items: Tuple[Expr, ...]  # constants only for now (value list)
    negate: bool = False
    type: T.Type = T.BOOLEAN

    def children(self):
        return (self.value,) + self.items


@dataclasses.dataclass(frozen=True)
class WhenClause:
    condition: Expr
    result: Expr


@dataclasses.dataclass(frozen=True)
class Case(Expr):
    """Searched CASE: WHEN cond THEN res ... ELSE default."""

    type: T.Type
    whens: Tuple[WhenClause, ...]
    default: Optional[Expr]

    def children(self):
        out = []
        for w in self.whens:
            out += [w.condition, w.result]
        if self.default is not None:
            out.append(self.default)
        return tuple(out)


@dataclasses.dataclass(frozen=True)
class Cast(Expr):
    type: T.Type
    term: Expr

    def children(self):
        return (self.term,)


@dataclasses.dataclass(frozen=True)
class Lambda(Expr):
    """Lambda argument of a higher-order function (sql/ir/Lambda).
    `type` is the body's result type; params resolve as ColumnRefs inside
    the body and are bound per element by the array-function evaluator."""

    type: T.Type
    params: Tuple[str, ...]
    body: Expr

    def children(self):
        return (self.body,)


def walk(e: Expr):
    yield e
    for c in e.children():
        yield from walk(c)


def is_deterministic(e: Expr) -> bool:
    """True when the expression is a pure function of its column inputs —
    no `rand()`-class Calls and no constants folded from `now()`-class
    functions.  The single flag the result cache, the plan cache, and
    constant folding all consult."""
    for n in walk(e):
        if isinstance(n, Call) and n.name in NONDETERMINISTIC_FUNCTIONS:
            return False
        if isinstance(n, Constant) and n.nondeterministic_origin:
            return False
    return True


def referenced_columns(e: Expr) -> list:
    seen = []
    for n in walk(e):
        if isinstance(n, ColumnRef) and n.name not in seen:
            seen.append(n.name)
    return seen


def replace_refs(e: Expr, mapping: dict) -> Expr:
    """Rewrite ColumnRefs by name (symbol substitution in plan rewrites)."""
    if isinstance(e, ColumnRef):
        return mapping.get(e.name, e)
    if isinstance(e, Call):
        return Call(e.type, e.name, tuple(replace_refs(a, mapping) for a in e.args))
    if isinstance(e, Comparison):
        return Comparison(
            e.op, replace_refs(e.left, mapping), replace_refs(e.right, mapping)
        )
    if isinstance(e, Logical):
        return Logical(e.op, tuple(replace_refs(t, mapping) for t in e.terms))
    if isinstance(e, Not):
        return Not(replace_refs(e.term, mapping))
    if isinstance(e, IsNull):
        return IsNull(replace_refs(e.term, mapping), e.negate)
    if isinstance(e, Between):
        return Between(
            replace_refs(e.value, mapping),
            replace_refs(e.low, mapping),
            replace_refs(e.high, mapping),
            e.negate,
        )
    if isinstance(e, In):
        return In(
            replace_refs(e.value, mapping),
            tuple(replace_refs(i, mapping) for i in e.items),
            e.negate,
        )
    if isinstance(e, Case):
        return Case(
            e.type,
            tuple(
                WhenClause(
                    replace_refs(w.condition, mapping), replace_refs(w.result, mapping)
                )
                for w in e.whens
            ),
            None if e.default is None else replace_refs(e.default, mapping),
        )
    if isinstance(e, Cast):
        return Cast(e.type, replace_refs(e.term, mapping))
    return e
