"""SQL type system for the TPU-native engine.

Reference parity: /root/reference/core/trino-spi/src/main/java/io/trino/spi/type/
(BigintType, IntegerType, DoubleType, DecimalType, VarcharType, DateType,
BooleanType, TimestampType...).  Unlike the reference's MethodHandle-based
``TypeOperators`` (TypeOperators.java:70), type operations here lower directly
to jax/XLA ops over fixed-dtype device arrays.

TPU-first representation choices:
  - BIGINT   -> int64  (jax x64 enabled; TPU emulates int64 as 2x int32)
  - INTEGER  -> int32
  - DOUBLE   -> float64 (CPU-exact; TPC-H money math avoids doubles entirely)
  - DECIMAL(p,s) -> scaled int64 fixed point (p<=18).  The reference uses
    Int128 two-limb math (spi/type/Int128Math.java); TPC-H needs only p<=15
    for stored columns, and aggregate sums stay within int64 at SF100.
  - VARCHAR  -> dictionary codes (int32) on device + host-side dictionary,
    the analog of the reference's DictionaryBlock (spi/block/DictionaryBlock.java:33)
  - DATE     -> int32 days since 1970-01-01
  - BOOLEAN  -> bool_
  - TIMESTAMP -> int64 microseconds since epoch (reference uses ps precision;
    us is enough for TPC-H/DS and fits one limb)
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np


@dataclasses.dataclass(frozen=True)
class Type:
    """Base SQL type. Instances are interned-ish via module constants."""

    name: str

    def __str__(self) -> str:  # pragma: no cover
        return self.name

    # --- device representation ---------------------------------------
    @property
    def np_dtype(self) -> np.dtype:
        raise NotImplementedError(self.name)

    @property
    def is_dictionary(self) -> bool:
        return False

    @property
    def is_decimal(self) -> bool:
        return False

    @property
    def comparable(self) -> bool:
        return True

    @property
    def orderable(self) -> bool:
        return True

    @property
    def is_array(self) -> bool:
        return False

    @property
    def is_map(self) -> bool:
        return False


@dataclasses.dataclass(frozen=True)
class FixedWidthType(Type):
    dtype: str = "int64"

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(self.dtype)


@dataclasses.dataclass(frozen=True)
class DecimalType(Type):
    """Fixed-point decimal, scaled by 10**scale.

    p <= 18 stores one int64 lane; p > 18 ("wide") stores a two-limb
    (n, 2) int64 lane — the Int128ArrayBlock analog
    (spi/block/Int128ArrayBlock.java:28, spi/type/Int128Math.java)."""

    precision: int = 18
    scale: int = 0

    def __post_init__(self):
        if self.precision > 38:
            raise ValueError("decimal precision exceeds 38")

    @property
    def wide(self) -> bool:
        return self.precision > 18

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype("int64")

    @property
    def is_decimal(self) -> bool:
        return True

    def __str__(self) -> str:
        return f"decimal({self.precision},{self.scale})"


@dataclasses.dataclass(frozen=True)
class ArrayType(Type):
    """ARRAY(element).  Device representation: dictionary-encoded — int32
    codes into a host-side dictionary of distinct python tuples (the
    DictionaryBlock-over-ArrayBlock analog; reference spi/block/
    ArrayBlock.java stores offsets+flat values, which here live host-side
    since array columns are off the hot TPC path).  Element values inside
    dictionary entries use IR-constant conventions (decimal -> unscaled
    int, date -> epoch days, varchar -> str)."""

    element: "Type" = None

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype("int32")  # dictionary code

    @property
    def is_dictionary(self) -> bool:
        return True

    @property
    def is_array(self) -> bool:
        return True

    @property
    def orderable(self) -> bool:
        return False

    def __str__(self) -> str:
        return f"array({self.element})"


@dataclasses.dataclass(frozen=True)
class MapType(Type):
    """MAP(key, value) — dictionary-encoded like ArrayType: entries are
    tuples of (key, value) pairs in IR-constant conventions (the
    DictionaryBlock-over-MapBlock analog of spi/block/MapBlock.java)."""

    key: "Type" = None
    value: "Type" = None

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype("int32")

    @property
    def is_dictionary(self) -> bool:
        return True

    @property
    def is_map(self) -> bool:
        return True

    @property
    def orderable(self) -> bool:
        return False

    def __str__(self) -> str:
        return f"map({self.key}, {self.value})"


@dataclasses.dataclass(frozen=True)
class VarcharType(Type):
    """Dictionary-encoded varchar. length is advisory (like VARCHAR(n))."""

    length: Optional[int] = None

    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype("int32")  # dictionary code

    @property
    def is_dictionary(self) -> bool:
        return True

    def __str__(self) -> str:
        return "varchar" if self.length is None else f"varchar({self.length})"


BOOLEAN = FixedWidthType("boolean", "bool_")
TINYINT = FixedWidthType("tinyint", "int8")
SMALLINT = FixedWidthType("smallint", "int16")
INTEGER = FixedWidthType("integer", "int32")
BIGINT = FixedWidthType("bigint", "int64")
DOUBLE = FixedWidthType("double", "float64")
REAL = FixedWidthType("real", "float32")
DATE = FixedWidthType("date", "int32")
TIMESTAMP = FixedWidthType("timestamp", "int64")
VARCHAR = VarcharType("varchar")
UNKNOWN = FixedWidthType("unknown", "int8")  # type of NULL literal


def decimal(precision: int, scale: int) -> DecimalType:
    return DecimalType("decimal", precision, scale)


def array_of(element: Type) -> ArrayType:
    return ArrayType("array", element)


def map_of(key: Type, value: Type) -> MapType:
    return MapType("map", key, value)


def varchar(length: Optional[int] = None) -> VarcharType:
    return VarcharType("varchar", length)


_NUMERIC_ORDER = {
    "tinyint": 0,
    "smallint": 1,
    "integer": 2,
    "bigint": 3,
    "real": 5,
    "double": 6,
}


def is_numeric(t: Type) -> bool:
    return t.name in _NUMERIC_ORDER or t.is_decimal


def is_integral(t: Type) -> bool:
    return t.name in ("tinyint", "smallint", "integer", "bigint")


def common_super_type(a: Type, b: Type) -> Type:
    """Result type of mixing a and b in arithmetic/comparison.

    Mirrors the reference's TypeCoercion (sql/analyzer/TypeCoercion.java)
    for the numeric subset we support.
    """
    if a == b:
        return a
    if a.name == "unknown":
        return b
    if b.name == "unknown":
        return a
    if a.is_decimal and b.is_decimal:
        scale = max(a.scale, b.scale)
        intd = max(a.precision - a.scale, b.precision - b.scale)
        return decimal(min(38, intd + scale), scale)
    if a.is_decimal and is_integral(b):
        return common_super_type(a, decimal(18, 0))
    if b.is_decimal and is_integral(a):
        return common_super_type(decimal(18, 0), b)
    if (a.is_decimal and b.name in ("double", "real")) or (
        b.is_decimal and a.name in ("double", "real")
    ):
        return DOUBLE
    if a.name in _NUMERIC_ORDER and b.name in _NUMERIC_ORDER:
        return a if _NUMERIC_ORDER[a.name] >= _NUMERIC_ORDER[b.name] else b
    if a.name == "date" and b.name == "timestamp":
        return TIMESTAMP
    if a.name == "timestamp" and b.name == "date":
        return TIMESTAMP
    if getattr(a, "is_array", False) or getattr(b, "is_array", False):
        if (
            getattr(a, "is_array", False)
            and getattr(b, "is_array", False)
        ):
            return array_of(common_super_type(a.element, b.element))
        raise TypeError(f"no common type for {a} and {b}")
    if a.is_dictionary and b.is_dictionary:
        return VARCHAR
    raise TypeError(f"no common type for {a} and {b}")


def parse_type(s: str) -> Type:
    """Parse a SQL type name like 'decimal(12,2)' or 'array(bigint)'."""
    s = s.strip().lower()
    if s.startswith("array"):
        inner = s[s.index("(") + 1 : s.rindex(")")]
        return array_of(parse_type(inner))
    if s.startswith("map"):
        inner = s[s.index("(") + 1 : s.rindex(")")]
        depth = 0
        for i, ch in enumerate(inner):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
            elif ch == "," and depth == 0:
                return map_of(
                    parse_type(inner[:i]), parse_type(inner[i + 1:])
                )
        raise ValueError(f"bad map type: {s}")
    if s.startswith("decimal"):
        if "(" in s:
            inner = s[s.index("(") + 1 : s.rindex(")")]
            parts = [p.strip() for p in inner.split(",")]
            p = int(parts[0])
            sc = int(parts[1]) if len(parts) > 1 else 0
            return decimal(p, sc)
        return decimal(18, 0)
    if s.startswith("varchar") or s.startswith("char"):
        if "(" in s:
            return varchar(int(s[s.index("(") + 1 : s.rindex(")")]))
        return VARCHAR
    simple = {
        "boolean": BOOLEAN,
        "tinyint": TINYINT,
        "smallint": SMALLINT,
        "integer": INTEGER,
        "int": INTEGER,
        "bigint": BIGINT,
        "double": DOUBLE,
        "real": REAL,
        "date": DATE,
        "timestamp": TIMESTAMP,
        "unknown": UNKNOWN,
    }
    if s in simple:
        return simple[s]
    raise ValueError(f"unknown type: {s}")
