"""Wire serialization: page frames (data plane) and plan JSON (control plane).

Reference parity:
  - Page frame layout mirrors execution/buffer/PagesSerdeUtil.java:48-52:
    ``positionCount | codecMarkers | uncompressedSize | compressedSize |
    checksum | payload`` where payload is blockCount + per-block encodings
    (PagesSerdeUtil.writeRawPage:64 / readRawPage:72).  Compression is
    applied only when the ratio beats 0.8 (PageSerializer.java:100); we use
    zstandard where the reference offers LZ4/ZSTD (CompressionCodec.java:18).
    The checksum plays PagesSerde's XXH64 role (PageSerializer.java:116 /
    PageDeserializer checksum verification) with CRC32: `TPG2` frames carry
    a CRC of header fields + body and `deserialize_page` raises a typed
    `PageIntegrityError` on any mismatch, so a flipped bit anywhere on the
    data plane is tamper-evident instead of silently wrong answers.
    Legacy `TPG1` frames (no checksum) stay readable for mixed-version
    spools.
  - Plan JSON mirrors the reference's Jackson-serialized PlanFragment
    shipped in TaskUpdateRequest (server/remotetask/HttpRemoteTask.java:722):
    every plan node / expression / type is a dataclass encoded by class name.

TPU-first notes: column payloads are raw little-endian numpy buffers that
deserialize zero-copy into np.frombuffer views, ready for device upload —
there is no row-wise encode/decode step anywhere on the data plane.
"""
from __future__ import annotations

import dataclasses
import io
import json
import struct
import zlib
from decimal import Decimal
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

try:
    import threading as _threading

    import zstandard as _zstd

    _ZSTD_LOCAL = _threading.local()

    def _compressor():
        # zstd (de)compressor objects are NOT thread-safe; tasks serialize
        # pages concurrently, so keep one per thread
        c = getattr(_ZSTD_LOCAL, "c", None)
        if c is None:
            c = _ZSTD_LOCAL.c = _zstd.ZstdCompressor(level=1)
        return c

    def _decompressor():
        d = getattr(_ZSTD_LOCAL, "d", None)
        if d is None:
            d = _ZSTD_LOCAL.d = _zstd.ZstdDecompressor()
        return d

except ImportError:  # pragma: no cover
    _compressor = None
    _decompressor = None

from . import types as T
from .expr import ir
from .ops.sort import SortKey
from .page import Column, Page
from .plan import nodes as P
from .spi import Split

MAGIC = b"TPG2"  # checksummed frames (CRC32 of header fields + body)
MAGIC_V1 = b"TPG1"  # legacy read-compat: same layout, no checksum
MARKER_COMPRESSED = 1
MIN_COMPRESS_BYTES = 4096
COMPRESS_RATIO = 0.8
_HEADER_FIELDS = struct.Struct("<iBII")
HEADER_V2 = 4 + _HEADER_FIELDS.size + 4  # magic + fields + crc32
HEADER_V1 = 4 + _HEADER_FIELDS.size


class PageIntegrityError(RuntimeError):
    """A page frame failed structural or checksum validation (the
    reference's PagesSerde checksum mismatch -> GENERIC_INTERNAL_ERROR
    "Checksum verification failure").  Typed so schedulers can treat it
    as retriable: the bytes are wrong, not the query."""

# ---------------------------------------------------------------------------
# Page binary serde (the data plane)
# ---------------------------------------------------------------------------


def _w_bytes(buf: io.BytesIO, b: bytes):
    buf.write(struct.pack("<I", len(b)))
    buf.write(b)


def _r_bytes(mv: memoryview, off: int) -> Tuple[bytes, int]:
    (n,) = struct.unpack_from("<I", mv, off)
    off += 4
    return bytes(mv[off : off + n]), off + n


def serialize_page(page: Page) -> bytes:
    """Page -> one wire frame (only the first ``count`` rows are shipped)."""
    n = page.count
    payload = io.BytesIO()
    payload.write(struct.pack("<I", page.num_columns))
    names = page.names or [f"c{i}" for i in range(page.num_columns)]
    for name, col in zip(names, page.columns):
        _w_bytes(payload, name.encode())
        _w_bytes(payload, str(col.type).encode())
        vals = np.ascontiguousarray(np.asarray(col.values)[:n])
        flags = 0
        if col.validity is not None:
            flags |= 1
        if col.dictionary is not None:
            flags |= 2
        if vals.ndim == 2:
            flags |= 4  # wide (two-limb) decimal rows
        payload.write(struct.pack("<B", flags))
        _w_bytes(payload, vals.dtype.str.encode())
        _w_bytes(payload, vals.tobytes())
        if col.validity is not None:
            ok = np.ascontiguousarray(
                np.asarray(col.validity)[:n].astype(np.uint8)
            )
            _w_bytes(payload, np.packbits(ok).tobytes())
        if col.dictionary is not None:
            entries = [str(s) for s in col.dictionary]
            payload.write(struct.pack("<I", len(entries)))
            for s in entries:
                _w_bytes(payload, s.encode())
    raw = payload.getvalue()
    markers = 0
    body = raw
    if _compressor is not None and len(raw) >= MIN_COMPRESS_BYTES:
        comp = _compressor().compress(raw)
        if len(comp) < len(raw) * COMPRESS_RATIO:
            markers |= MARKER_COMPRESSED
            body = comp
    fields = _HEADER_FIELDS.pack(n, markers, len(raw), len(body))
    crc = zlib.crc32(body, zlib.crc32(fields)) & 0xFFFFFFFF
    return MAGIC + fields + struct.pack("<I", crc) + body


def deserialize_page(frame: bytes) -> Page:
    magic = bytes(frame[:4])
    if magic == MAGIC:
        if len(frame) < HEADER_V2:
            raise PageIntegrityError(
                f"truncated page frame: {len(frame)} bytes < header"
            )
        fields = bytes(frame[4 : 4 + _HEADER_FIELDS.size])
        n, markers, usize, csize = _HEADER_FIELDS.unpack(fields)
        (crc,) = struct.unpack_from("<I", frame, 4 + _HEADER_FIELDS.size)
        body = bytes(frame[HEADER_V2 : HEADER_V2 + csize])
        if len(body) != csize:
            raise PageIntegrityError(
                f"truncated page frame body: {len(body)}/{csize} bytes"
            )
        actual = zlib.crc32(body, zlib.crc32(fields)) & 0xFFFFFFFF
        if actual != crc:
            raise PageIntegrityError(
                f"page frame CRC mismatch: stored {crc:#010x}, "
                f"computed {actual:#010x}"
            )
    elif magic == MAGIC_V1:
        # mixed-version spools fail soft: accept the unchecksummed layout
        n, markers, usize, csize = _HEADER_FIELDS.unpack_from(frame, 4)
        body = bytes(frame[HEADER_V1 : HEADER_V1 + csize])
        if len(body) != csize:
            raise PageIntegrityError(
                f"truncated TPG1 frame body: {len(body)}/{csize} bytes"
            )
    else:
        raise PageIntegrityError(f"bad page frame magic {magic!r}")
    if markers & MARKER_COMPRESSED:
        body = _decompressor().decompress(body, max_output_size=usize)
    mv = memoryview(body)
    (ncols,) = struct.unpack_from("<I", mv, 0)
    off = 4
    names: List[str] = []
    cols: List[Column] = []
    for _ in range(ncols):
        nm, off = _r_bytes(mv, off)
        ts, off = _r_bytes(mv, off)
        (flags,) = struct.unpack_from("<B", mv, off)
        off += 1
        dt, off = _r_bytes(mv, off)
        vb, off = _r_bytes(mv, off)
        vals = np.frombuffer(vb, dtype=np.dtype(dt.decode())).copy()
        if flags & 4:
            vals = vals.reshape(-1, 2)
        validity = None
        if flags & 1:
            bb, off = _r_bytes(mv, off)
            validity = np.unpackbits(
                np.frombuffer(bb, dtype=np.uint8), count=n
            ).astype(bool)
        dictionary = None
        if flags & 2:
            (nd,) = struct.unpack_from("<I", mv, off)
            off += 4
            entries = []
            for _ in range(nd):
                e, off = _r_bytes(mv, off)
                entries.append(e.decode())
            dictionary = np.array(entries, dtype=object)
        names.append(nm.decode())
        cols.append(Column(T.parse_type(ts.decode()), vals, validity, dictionary))
    return Page(cols, n, names)


def serialize_pages(pages: List[Page]) -> bytes:
    buf = io.BytesIO()
    buf.write(struct.pack("<I", len(pages)))
    for p in pages:
        _w_bytes(buf, serialize_page(p))
    return buf.getvalue()


def pages_stats(data: bytes) -> Tuple[int, int]:
    """(rows, uncompressed bytes) of a serialized page stream, read from
    the frame HEADERS only — no decompression.  The adaptive planner's
    OutputStatsEstimator input: compressed file sizes lie about relative
    volumes (zstd flattens monotone int columns ~10x)."""
    mv = memoryview(data)
    (n,) = struct.unpack_from("<I", mv, 0)
    off = 4
    rows = 0
    ubytes = 0
    for _ in range(n):
        frame, off = _r_bytes(mv, off)
        # TPG1 and TPG2 share the field layout at offset 4; TPG2 only
        # appends the CRC32 after the fields, which stats never read
        if frame[:4] not in (MAGIC, MAGIC_V1):
            raise PageIntegrityError(
                f"bad page frame magic {bytes(frame[:4])!r}"
            )
        cnt, _markers, usize, _csize = _HEADER_FIELDS.unpack_from(frame, 4)
        rows += cnt
        ubytes += usize
    return rows, ubytes


def deserialize_pages(data: bytes) -> List[Page]:
    mv = memoryview(data)
    (n,) = struct.unpack_from("<I", mv, 0)
    off = 4
    out = []
    for _ in range(n):
        frame, off = _r_bytes(mv, off)
        out.append(deserialize_page(frame))
    return out


# ---------------------------------------------------------------------------
# Plan JSON serde (the control plane)
# ---------------------------------------------------------------------------

_REGISTRY: Dict[str, type] = {}


def _register(module, prefix: str):
    for name, obj in vars(module).items():
        if isinstance(obj, type) and dataclasses.is_dataclass(obj):
            _REGISTRY[f"{prefix}.{name}"] = obj


_register(T, "type")
_register(ir, "ir")
_register(P, "plan")
_REGISTRY["sort.SortKey"] = SortKey
_REGISTRY["spi.Split"] = Split


def _cls_key(obj) -> str:
    cls = type(obj)
    for key, c in _REGISTRY.items():
        if c is cls:
            return key
    raise TypeError(f"unregistered dataclass {cls.__name__}")


def encode_value(v: Any) -> Any:
    if v is None or isinstance(v, (bool, str)):
        return v
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, (int, float)):
        return v
    if isinstance(v, Decimal):
        return {"$dec": str(v)}
    if isinstance(v, (list, tuple)):
        return [encode_value(x) for x in v]
    if isinstance(v, dict):
        return {"$dict": [[encode_value(k), encode_value(x)] for k, x in v.items()]}
    if dataclasses.is_dataclass(v):
        doc = {"$": _cls_key(v)}
        for f in dataclasses.fields(v):
            doc[f.name] = encode_value(getattr(v, f.name))
        return doc
    raise TypeError(f"cannot encode {type(v).__name__}: {v!r}")


def decode_value(v: Any) -> Any:
    if v is None or isinstance(v, (bool, str, int, float)):
        return v
    if isinstance(v, list):
        return tuple(decode_value(x) for x in v)
    if isinstance(v, dict):
        if "$dec" in v:
            return Decimal(v["$dec"])
        if "$dict" in v:
            return {decode_value(k): decode_value(x) for k, x in v["$dict"]}
        cls = _REGISTRY[v["$"]]
        kwargs = {k: decode_value(x) for k, x in v.items() if k != "$"}
        return cls(**kwargs)
    raise TypeError(f"cannot decode {v!r}")


def plan_to_json(node: P.PlanNode) -> str:
    return json.dumps(encode_value(node))


def plan_from_json(s: str) -> P.PlanNode:
    return decode_value(json.loads(s))
