"""trino_tpu — a TPU-native distributed SQL analytics engine.

A from-scratch re-design of the capabilities of the reference Trino engine
(/root/reference, Java MPP SQL engine) for TPU hardware: columnar pages as
device arrays, expression bytecode-codegen replaced by jax tracing + XLA
compilation, HTTP page shuffle replaced by XLA collectives over an ICI mesh,
with a host-side async control plane.

Layer map (mirrors SURVEY.md §1):
  types.py / page.py        — type system + columnar Page/Block model
  expr/                     — typed expression IR + jax lowering (codegen slot)
  sql/                      — lexer/parser/analyzer (SQL frontend)
  plan/                     — logical plan nodes, optimizer, fragmenter
  ops/                      — physical operators as jax kernels
  exec/                     — local execution: fragment -> jitted pipeline
  parallel/                 — mesh, collectives, distributed exchanges
  connectors/               — tpch generator, memory, blackhole + SPI
  server/ client/           — coordinator/worker control plane + protocol
"""

__version__ = "0.1.0"


def force_cpu(n_devices: int = 8) -> None:
    """Force the CPU backend with n virtual devices (test/dev mode).

    The environment registers an 'axon' TPU plugin at interpreter start and
    overrides jax_platforms; this undoes that before any backend init.
    """
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    opt = f"--xla_force_host_platform_device_count={n_devices}"
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = f"{flags} {opt}".strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_enable_x64", True)


def enable_x64() -> None:
    import jax

    jax.config.update("jax_enable_x64", True)
