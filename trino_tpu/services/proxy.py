"""Statement-protocol proxy.

Reference parity: service/trino-proxy — an HTTP proxy in front of a
coordinator that forwards the statement protocol (POST /v1/statement +
nextUri GETs + DELETE cancels), preserving identity/authorization headers
so the backend performs the real authentication.
"""
from __future__ import annotations

import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_FORWARD_HEADERS = (
    "X-Trino-User", "X-Trino-Source", "Authorization", "Content-Type",
)


class _ProxyHandler(BaseHTTPRequestHandler):
    backend: str = ""

    def log_message(self, fmt, *args):  # quiet
        pass

    def _forward(self, method: str):
        body = None
        n = int(self.headers.get("Content-Length", 0) or 0)
        if n:
            body = self.rfile.read(n)
        req = urllib.request.Request(
            self.backend + self.path, data=body, method=method
        )
        for h in _FORWARD_HEADERS:
            v = self.headers.get(h)
            if v:
                req.add_header(h, v)
        try:
            with urllib.request.urlopen(req) as resp:
                payload = resp.read()
                self.send_response(resp.status)
                for k, v in resp.headers.items():
                    if k.lower() in ("content-type",):
                        self.send_header(k, v)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)
        except urllib.error.HTTPError as e:
            payload = e.read()
            self.send_response(e.code)
            self.send_header("Content-Length", str(len(payload)))
            self.end_headers()
            self.wfile.write(payload)

    def do_POST(self):
        self._forward("POST")

    def do_GET(self):
        self._forward("GET")

    def do_DELETE(self):
        self._forward("DELETE")


class ProxyServer:
    """Forwarding proxy handle (TestingTrinoProxy analog)."""

    def __init__(self, backend_uri: str, port: int = 0):
        handler = type(
            "Handler", (_ProxyHandler,), {"backend": backend_uri.rstrip("/")}
        )
        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), handler)
        self.port = self.httpd.server_address[1]
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )

    def start(self) -> "ProxyServer":
        self.thread.start()
        return self

    def stop(self):
        self.httpd.shutdown()

    @property
    def uri(self) -> str:
        return f"http://127.0.0.1:{self.port}"
