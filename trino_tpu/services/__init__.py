"""Auxiliary services (reference service/ modules: trino-verifier,
trino-proxy)."""
