"""Verifier: A/B correctness comparison of two engines.

Reference parity: service/trino-verifier (Validator, VerifierDao): replays
each query against a *control* and a *test* endpoint and compares
normalized results — the harness used to validate new engine versions
against known-good ones.  Endpoints are either server URIs (driven through
the statement protocol) or in-process Sessions.
"""
from __future__ import annotations

import dataclasses
import time
from typing import List, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class VerificationResult:
    query: str
    status: str  # MATCH | MISMATCH | CONTROL_FAILED | TEST_FAILED
    control_ms: float = 0.0
    test_ms: float = 0.0
    detail: Optional[str] = None


def _normalize(rows, tol=1e-6):
    out = []
    for r in rows:
        norm = []
        for v in r:
            if isinstance(v, float):
                norm.append(round(v, 6))
            else:
                norm.append(v)
        out.append(tuple(norm))
    return sorted(out, key=repr)


class _SessionRunner:
    def __init__(self, session):
        self.session = session

    def run(self, sql: str):
        return self.session.execute(sql).to_pylist()


class _ServerRunner:
    def __init__(self, uri: str):
        from ..client.client import StatementClient

        self.client = StatementClient(uri)

    def run(self, sql: str):
        _, rows = self.client.execute(sql)
        return [tuple(r) for r in rows]


def _runner(endpoint):
    if isinstance(endpoint, str):
        return _ServerRunner(endpoint)
    return _SessionRunner(endpoint)


class Verifier:
    """Replays queries control-vs-test and diffs results (Validator)."""

    def __init__(self, control, test):
        self.control = _runner(control)
        self.test = _runner(test)

    def verify_one(self, sql: str) -> VerificationResult:
        t0 = time.perf_counter()
        try:
            expected = self.control.run(sql)
        except Exception as e:
            return VerificationResult(
                sql, "CONTROL_FAILED", detail=f"{type(e).__name__}: {e}"
            )
        control_ms = (time.perf_counter() - t0) * 1000
        t1 = time.perf_counter()
        try:
            actual = self.test.run(sql)
        except Exception as e:
            return VerificationResult(
                sql, "TEST_FAILED", control_ms=control_ms,
                detail=f"{type(e).__name__}: {e}",
            )
        test_ms = (time.perf_counter() - t1) * 1000
        a, b = _normalize(expected), _normalize(actual)
        if a == b:
            return VerificationResult(sql, "MATCH", control_ms, test_ms)
        detail = (
            f"control {len(a)} rows vs test {len(b)} rows; "
            f"first control row: {a[0] if a else None!r}; "
            f"first test row: {b[0] if b else None!r}"
        )
        return VerificationResult(sql, "MISMATCH", control_ms, test_ms, detail)

    def verify(self, queries: Sequence[str]) -> List[VerificationResult]:
        return [self.verify_one(q) for q in queries]

    @staticmethod
    def summarize(results: Sequence[VerificationResult]) -> dict:
        out = {"MATCH": 0, "MISMATCH": 0, "CONTROL_FAILED": 0,
               "TEST_FAILED": 0}
        for r in results:
            out[r.status] += 1
        out["total"] = len(results)
        return out
