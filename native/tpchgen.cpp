// Native TPC-H lineitem generator — the host data plane's hot loop.
//
// Reference parity: the reference's scan feed path is Java
// (plugin/trino-tpch TpchRecordSetProvider streaming io.trino.tpch dbgen
// rows); this engine's equivalent host-side feed is counter-based
// (splitmix64) column generation.  The numpy implementation
// (trino_tpu/connectors/tpch.py) makes several vectorized passes with
// temporaries; this fused single-pass C++ version generates all fixed-width
// lineitem columns at memory bandwidth and is the model for further native
// runtime pieces (page serde, exchange buffers).
//
// Semantics MUST match trino_tpu/connectors/tpch.py exactly — tests compare
// both paths element-wise (tests/test_native_gen.py).

#include <cstdint>

namespace {

inline uint64_t mix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ULL;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
  return x ^ (x >> 31);
}

inline uint64_t h64(uint64_t base, uint64_t idx) { return mix64(idx ^ base); }

inline int64_t uint_in(uint64_t base, uint64_t idx, int64_t lo, int64_t hi) {
  return lo + static_cast<int64_t>(h64(base, idx) % (uint64_t)(hi - lo + 1));
}

constexpr int64_t EPOCH_1992 = 8035;
constexpr int64_t ORDER_DATE_SPAN = 2406 - 151;
constexpr int64_t CURRENT_DATE = 9298;

}  // namespace

extern "C" {

// bases: column hash bases in fixed order:
//  0 l_count        1 o_orderdate    2 l_shipdate    3 l_partkey
//  4 l_supp_slot    5 l_quantity     6 l_discount    7 l_tax
//  8 l_commitdate   9 l_receiptdate 10 l_returnflag 11 l_shipinstruct
// 12 l_shipmode    13 l_comment     14 o_custkey (unused here)
//
// Output arrays must have capacity >= 7 * (hi_order - lo_order).
// Returns the number of rows generated.
int64_t gen_lineitem(
    int64_t lo_order, int64_t hi_order, int64_t npart, int64_t nsupp,
    int64_t ncomments, const uint64_t* bases,
    int64_t* orderkey, int64_t* partkey, int64_t* suppkey,
    int64_t* linenumber, int64_t* quantity, int64_t* extendedprice,
    int64_t* discount, int64_t* tax, int32_t* shipdate, int32_t* commitdate,
    int32_t* receiptdate, int32_t* returnflag, int32_t* linestatus,
    int32_t* shipinstruct, int32_t* shipmode, int32_t* comment) {
  int64_t row = 0;
  for (int64_t j = lo_order; j < hi_order; ++j) {
    const uint64_t uj = (uint64_t)j;
    const int64_t lines = 1 + (int64_t)(h64(bases[0], uj) % 7ULL);
    const int64_t odate = EPOCH_1992 + (int64_t)(h64(bases[1], uj) %
                                                 (uint64_t)ORDER_DATE_SPAN);
    const int64_t okey = (j / 8) * 32 + (j % 8) + 1;
    for (int64_t ln = 0; ln < lines; ++ln, ++row) {
      const uint64_t lid = uj * 8ULL + (uint64_t)ln;
      const int64_t pk = 1 + (int64_t)(h64(bases[3], lid) % (uint64_t)npart);
      const int64_t qty = uint_in(bases[5], lid, 1, 50);
      const int64_t price_cents =
          90000 + (pk / 10) % 20001 + 100 * (pk % 1000);
      const int64_t ship =
          odate + 1 + (int64_t)(h64(bases[2], lid) % 121ULL);
      const int64_t receipt = ship + uint_in(bases[9], lid, 1, 30);
      const int64_t slot = (int64_t)(h64(bases[4], lid) % 4ULL);
      orderkey[row] = okey;
      partkey[row] = pk;
      suppkey[row] = (pk + slot * (nsupp / 4 + (pk - 1) / nsupp)) % nsupp + 1;
      linenumber[row] = ln + 1;
      quantity[row] = qty * 100;
      extendedprice[row] = qty * price_cents;
      discount[row] = uint_in(bases[6], lid, 0, 10);
      tax[row] = uint_in(bases[7], lid, 0, 8);
      shipdate[row] = (int32_t)ship;
      commitdate[row] = (int32_t)(odate + uint_in(bases[8], lid, 30, 90));
      receiptdate[row] = (int32_t)receipt;
      returnflag[row] = receipt <= CURRENT_DATE
                            ? (int32_t)((h64(bases[10], lid) % 2ULL) * 2)
                            : 1;
      linestatus[row] = ship > CURRENT_DATE ? 1 : 0;
      shipinstruct[row] = (int32_t)(h64(bases[11], lid) % 4ULL);
      shipmode[row] = (int32_t)(h64(bases[12], lid) % 7ULL);
      comment[row] = (int32_t)(h64(bases[13], lid) % (uint64_t)ncomments);
    }
  }
  return row;
}

// Generic column fillers reused by other tables --------------------------

void fill_h64_mod(uint64_t base, int64_t lo, int64_t hi, int64_t mod,
                  int32_t* out) {
  for (int64_t i = lo; i < hi; ++i)
    out[i - lo] = (int32_t)(h64(base, (uint64_t)i) % (uint64_t)mod);
}

void fill_uint_in(uint64_t base, int64_t lo, int64_t hi, int64_t a, int64_t b,
                  int64_t* out) {
  for (int64_t i = lo; i < hi; ++i) out[i - lo] = uint_in(base, (uint64_t)i, a, b);
}

}  // extern "C"
